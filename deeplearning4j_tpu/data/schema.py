"""Schema + TransformProcess (DataVec transform layer analog).

Reference: datavec-api ``org.datavec.api.transform.schema.Schema`` and
``org.datavec.api.transform.TransformProcess`` (SURVEY.md §2.3 DataVec core
row): a declarative, schema-checked pipeline of column transforms compiled
once and applied per record. This rebuild keeps the same two-phase shape —
``TransformProcess.Builder`` validates each step against the evolving schema
at BUILD time (so column-name typos fail before any data flows), and
``execute`` applies the compiled steps to record collections.

Transforms operate on host-side Python records (the DataVec layer is a CPU
ETL stage in the reference too); the accelerator sees only the final dense
arrays assembled by ``RecordReaderDataSetIterator``.
"""

from __future__ import annotations

import json
import math
from typing import Any, Callable, Dict, List, Optional, Sequence

from .records import Record


class ColumnType:
    NUMERIC = "numeric"       # float/int cell
    INTEGER = "integer"
    CATEGORICAL = "categorical"
    STRING = "string"
    TIME = "time"


class Schema:
    """Ordered, typed column list (reference: Schema.Builder)."""

    class Builder:
        def __init__(self) -> None:
            self._cols: List[Dict[str, Any]] = []

        def add_column_double(self, name: str) -> "Schema.Builder":
            self._cols.append({"name": name, "type": ColumnType.NUMERIC})
            return self

        add_column_float = add_column_double

        def add_column_integer(self, name: str) -> "Schema.Builder":
            self._cols.append({"name": name, "type": ColumnType.INTEGER})
            return self

        def add_column_long(self, name: str) -> "Schema.Builder":
            return self.add_column_integer(name)

        def add_column_categorical(self, name: str,
                                   state_names: Sequence[str]) \
                -> "Schema.Builder":
            self._cols.append({"name": name, "type": ColumnType.CATEGORICAL,
                               "states": list(state_names)})
            return self

        def add_column_string(self, name: str) -> "Schema.Builder":
            self._cols.append({"name": name, "type": ColumnType.STRING})
            return self

        def add_column_time(self, name: str) -> "Schema.Builder":
            self._cols.append({"name": name, "type": ColumnType.TIME})
            return self

        def build(self) -> "Schema":
            return Schema(self._cols)

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()

    def __init__(self, cols: List[Dict[str, Any]]):
        self._cols = [dict(c) for c in cols]
        names = [c["name"] for c in self._cols]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names: {names}")

    # -- queries ----------------------------------------------------------
    def num_columns(self) -> int:
        return len(self._cols)

    def column_names(self) -> List[str]:
        return [c["name"] for c in self._cols]

    def column_type(self, name: str) -> str:
        return self._col(name)["type"]

    def categorical_states(self, name: str) -> List[str]:
        c = self._col(name)
        if c["type"] != ColumnType.CATEGORICAL:
            raise ValueError(f"column {name!r} is {c['type']}, "
                             "not categorical")
        return list(c["states"])

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self._cols):
            if c["name"] == name:
                return i
        raise KeyError(f"no column {name!r}; have {self.column_names()}")

    def _col(self, name: str) -> Dict[str, Any]:
        return self._cols[self.index_of(name)]

    def to_json(self) -> str:
        return json.dumps({"columns": self._cols})

    @staticmethod
    def from_json(s: str) -> "Schema":
        return Schema(json.loads(s)["columns"])

    def __eq__(self, other) -> bool:
        return isinstance(other, Schema) and self._cols == other._cols


class _Step:
    """One compiled transform: fn(record) -> record | None (None = filtered
    out), plus the schema it produces."""

    def __init__(self, name: str, fn: Callable[[Record], Optional[Record]],
                 out_schema: Schema):
        self.name = name
        self.fn = fn
        self.out_schema = out_schema


class TransformProcess:
    """Schema-validated transform pipeline (reference: TransformProcess)."""

    class Builder:
        def __init__(self, initial_schema: Schema):
            self._initial = initial_schema
            self._schema = initial_schema
            self._steps: List[_Step] = []

        # -- column surgery ---------------------------------------------
        def remove_columns(self, *names: str) -> "TransformProcess.Builder":
            idxs = sorted(self._schema.index_of(n) for n in names)
            keep = [i for i in range(self._schema.num_columns())
                    if i not in idxs]
            out = Schema([self._schema._cols[i] for i in keep])

            def fn(rec, keep=tuple(keep)):
                return [rec[i] for i in keep]

            self._push(f"remove{names}", fn, out)
            return self

        def remove_all_columns_except(self, *names: str) \
                -> "TransformProcess.Builder":
            drop = [n for n in self._schema.column_names() if n not in names]
            return self.remove_columns(*drop)

        def rename_column(self, old: str, new: str) \
                -> "TransformProcess.Builder":
            i = self._schema.index_of(old)
            cols = [dict(c) for c in self._schema._cols]
            cols[i]["name"] = new
            self._push(f"rename {old}->{new}", lambda rec: rec, Schema(cols))
            return self

        def reorder_columns(self, *names: str) -> "TransformProcess.Builder":
            idxs = [self._schema.index_of(n) for n in names]
            if len(idxs) != self._schema.num_columns():
                raise ValueError("reorder must list every column")
            out = Schema([self._schema._cols[i] for i in idxs])

            def fn(rec, idxs=tuple(idxs)):
                return [rec[i] for i in idxs]

            self._push("reorder", fn, out)
            return self

        def duplicate_column(self, name: str, new_name: str) \
                -> "TransformProcess.Builder":
            i = self._schema.index_of(name)
            col = dict(self._schema._cols[i])
            col["name"] = new_name
            out = Schema(self._schema._cols + [col])

            def fn(rec, i=i):
                return rec + [rec[i]]

            self._push(f"dup {name}", fn, out)
            return self

        # -- type conversions --------------------------------------------
        def string_to_categorical(self, name: str,
                                  state_names: Sequence[str]) \
                -> "TransformProcess.Builder":
            i = self._schema.index_of(name)
            cols = [dict(c) for c in self._schema._cols]
            cols[i] = {"name": name, "type": ColumnType.CATEGORICAL,
                       "states": list(state_names)}
            states = set(state_names)

            def fn(rec, i=i, states=states):
                if rec[i] not in states:
                    raise ValueError(
                        f"value {rec[i]!r} not a declared state of "
                        f"column {name!r}")
                return rec

            self._push(f"str->cat {name}", fn, Schema(cols))
            return self

        def categorical_to_integer(self, name: str) \
                -> "TransformProcess.Builder":
            i = self._schema.index_of(name)
            states = self._schema.categorical_states(name)
            lookup = {s: k for k, s in enumerate(states)}
            cols = [dict(c) for c in self._schema._cols]
            cols[i] = {"name": name, "type": ColumnType.INTEGER}

            def fn(rec, i=i, lookup=lookup):
                rec = list(rec)
                rec[i] = lookup[rec[i]]
                return rec

            self._push(f"cat->int {name}", fn, Schema(cols))
            return self

        def categorical_to_one_hot(self, name: str) \
                -> "TransformProcess.Builder":
            i = self._schema.index_of(name)
            states = self._schema.categorical_states(name)
            lookup = {s: k for k, s in enumerate(states)}
            cols = [dict(c) for c in self._schema._cols]
            onehot_cols = [{"name": f"{name}[{s}]",
                            "type": ColumnType.INTEGER} for s in states]
            cols[i:i + 1] = onehot_cols

            def fn(rec, i=i, lookup=lookup, n=len(states)):
                hot = [0] * n
                hot[lookup[rec[i]]] = 1
                return rec[:i] + hot + rec[i + 1:]

            self._push(f"cat->onehot {name}", fn, Schema(cols))
            return self

        def convert_to_double(self, name: str) -> "TransformProcess.Builder":
            i = self._schema.index_of(name)
            cols = [dict(c) for c in self._schema._cols]
            cols[i] = {"name": name, "type": ColumnType.NUMERIC}

            def fn(rec, i=i):
                rec = list(rec)
                rec[i] = float(rec[i])
                return rec

            self._push(f"->double {name}", fn, Schema(cols))
            return self

        def convert_to_integer(self, name: str) -> "TransformProcess.Builder":
            i = self._schema.index_of(name)
            cols = [dict(c) for c in self._schema._cols]
            cols[i] = {"name": name, "type": ColumnType.INTEGER}

            def fn(rec, i=i):
                rec = list(rec)
                rec[i] = int(float(rec[i]))
                return rec

            self._push(f"->int {name}", fn, Schema(cols))
            return self

        # -- math / string ops -------------------------------------------
        def double_math_op(self, name: str, op: str, value: float) \
                -> "TransformProcess.Builder":
            i = self._schema.index_of(name)
            self._require(name, (ColumnType.NUMERIC, ColumnType.INTEGER))
            ops = {"add": lambda v: v + value,
                   "subtract": lambda v: v - value,
                   "multiply": lambda v: v * value,
                   "divide": lambda v: v / value,
                   "modulus": lambda v: v % value,
                   "power": lambda v: v ** value}
            if op not in ops:
                raise ValueError(f"unknown math op {op!r}")
            f = ops[op]

            def fn(rec, i=i):
                rec = list(rec)
                rec[i] = f(float(rec[i]))
                return rec

            self._push(f"{op} {name}", fn, self._schema)
            return self

        def min_max_normalize(self, name: str, lo: float, hi: float) \
                -> "TransformProcess.Builder":
            """(x - lo) / (hi - lo) with the column's known range
            (reference: MinMaxNormalizer transform)."""
            i = self._schema.index_of(name)
            self._require(name, (ColumnType.NUMERIC, ColumnType.INTEGER))
            span = hi - lo
            if span <= 0:
                raise ValueError("hi must exceed lo")

            def fn(rec, i=i):
                rec = list(rec)
                rec[i] = (float(rec[i]) - lo) / span
                return rec

            self._push(f"minmax {name}", fn, self._schema)
            return self

        def string_map_transform(self, name: str, fn_str: Callable[[str], str]) \
                -> "TransformProcess.Builder":
            i = self._schema.index_of(name)

            def fn(rec, i=i):
                rec = list(rec)
                rec[i] = fn_str(str(rec[i]))
                return rec

            self._push(f"strmap {name}", fn, self._schema)
            return self

        # -- filters ------------------------------------------------------
        def filter_invalid_values(self, *names: str) \
                -> "TransformProcess.Builder":
            """Drop records whose named numeric cells fail to parse
            (reference: FilterInvalidValues)."""
            idxs = [self._schema.index_of(n) for n in names]

            def fn(rec, idxs=tuple(idxs)):
                for i in idxs:
                    try:
                        v = float(rec[i])
                    except (TypeError, ValueError):
                        return None
                    if math.isnan(v) or math.isinf(v):
                        return None
                return rec

            self._push(f"filter-invalid {names}", fn, self._schema)
            return self

        def filter(self, predicate: Callable[[Record], bool],
                   name: str = "filter") -> "TransformProcess.Builder":
            """Keep records where predicate(record) is True."""

            def fn(rec):
                return rec if predicate(rec) else None

            self._push(name, fn, self._schema)
            return self

        # -- plumbing ------------------------------------------------------
        def _require(self, name: str, types) -> None:
            t = self._schema.column_type(name)
            if t not in types:
                raise ValueError(
                    f"column {name!r} has type {t}, need one of {types}")

        def _push(self, name, fn, out_schema) -> None:
            self._steps.append(_Step(name, fn, out_schema))
            self._schema = out_schema

        def build(self) -> "TransformProcess":
            return TransformProcess(self._initial, self._steps)

    @staticmethod
    def builder(initial_schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(initial_schema)

    def __init__(self, initial_schema: Schema, steps: List[_Step]):
        self.initial_schema = initial_schema
        self._steps = steps

    def final_schema(self) -> Schema:
        return self._steps[-1].out_schema if self._steps \
            else self.initial_schema

    def execute(self, records) -> List[Record]:
        """Apply the pipeline to an iterable of records; filtered records
        are dropped (reference: LocalTransformExecutor.execute)."""
        out = []
        for rec in records:
            if len(rec) != self.initial_schema.num_columns():
                raise ValueError(
                    f"record width {len(rec)} != schema width "
                    f"{self.initial_schema.num_columns()}: {rec!r}")
            cur: Optional[Record] = list(rec)
            for step in self._steps:
                cur = step.fn(cur)
                if cur is None:
                    break
            if cur is not None:
                out.append(cur)
        return out

    def transform(self, record: Record) -> Optional[Record]:
        cur: Optional[Record] = list(record)
        for step in self._steps:
            cur = step.fn(cur)
            if cur is None:
                return None
        return cur
