"""Column analysis over record collections (AnalyzeLocal analog).

Reference: datavec ``transform.analysis.AnalyzeLocal.analyze(schema, rr)``
→ ``DataAnalysis`` with per-column statistics (SURVEY §2.3 DataVec core
row): numeric min/max/mean/stdev/zero- and missing-counts + histogram,
categorical state counts, string length stats.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .records import Record
from .schema import Schema

_NUMERIC = ("double", "numeric", "integer", "long", "time")


@dataclass
class ColumnAnalysis:
    name: str
    ctype: str
    count: int = 0
    count_missing: int = 0
    # numeric
    min: Optional[float] = None
    max: Optional[float] = None
    mean: Optional[float] = None
    stdev: Optional[float] = None
    count_zero: int = 0
    histogram_buckets: Optional[List[float]] = None
    histogram_counts: Optional[List[int]] = None
    # categorical / string
    state_counts: Optional[Dict[str, int]] = None
    min_length: Optional[int] = None
    max_length: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items() if v is not None}


class DataAnalysis:
    def __init__(self, schema: Schema, columns: List[ColumnAnalysis]):
        self.schema = schema
        self._by_name = {c.name: c for c in columns}
        self.columns = columns

    def column_analysis(self, name: str) -> ColumnAnalysis:
        return self._by_name[name]

    def to_json(self) -> str:
        return json.dumps({c.name: c.to_dict() for c in self.columns},
                          indent=2)

    def __str__(self) -> str:
        return self.to_json()


class AnalyzeLocal:
    """reference: AnalyzeLocal.analyze — single-pass local analysis."""

    @staticmethod
    def analyze(schema: Schema, records: Sequence[Record],
                n_histogram_buckets: int = 20) -> DataAnalysis:
        cols = []
        names = schema.column_names()
        for i, name in enumerate(names):
            ctype = schema.column_type(name)
            values = [r[i] for r in records]
            present = [v for v in values if v is not None and v != ""]
            ca = ColumnAnalysis(name=name, ctype=ctype, count=len(values),
                                count_missing=len(values) - len(present))
            if ctype in _NUMERIC and present:
                arr = np.asarray([float(v) for v in present], np.float64)
                ca.min = float(arr.min())
                ca.max = float(arr.max())
                ca.mean = float(arr.mean())
                ca.stdev = float(arr.std(ddof=1)) if arr.size > 1 else 0.0
                ca.count_zero = int((arr == 0).sum())
                counts, edges = np.histogram(arr, bins=n_histogram_buckets)
                ca.histogram_buckets = [float(e) for e in edges]
                ca.histogram_counts = [int(c) for c in counts]
            elif ctype == "categorical" and present:
                sc: Dict[str, int] = {}
                for v in present:
                    sc[str(v)] = sc.get(str(v), 0) + 1
                ca.state_counts = sc
            elif ctype == "string" and present:
                lens = [len(str(v)) for v in present]
                ca.min_length = min(lens)
                ca.max_length = max(lens)
            cols.append(ca)
        return DataAnalysis(schema, cols)
