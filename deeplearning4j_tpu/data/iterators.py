"""DataSetIterator SPI + MNIST/EMNIST/IRIS/CIFAR fetchers.

Reference: nd4j DataSetIterator + dl4j-data ``MnistDataSetIterator`` /
``IrisDataSetIterator`` / fetchers (SURVEY.md §2.3 dataset iterators row).

MNIST: the reference auto-downloads IDX files (``MnistDataFetcher``). This
environment has no egress, so the fetcher (a) reads IDX files from
``DL4J_TPU_DATA_DIR`` (default ~/.deeplearning4j_tpu/data) when present —
format-compatible with the standard MNIST distribution — and (b) otherwise
generates a deterministic synthetic digit set with the same shapes/dtypes
(28x28 grayscale, 10 classes, procedurally drawn glyph-like patterns) so the
full pipeline trains and benchmarks without network access. The synthetic
fallback is clearly marked via ``.synthetic``.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Iterator, List, Optional, Tuple

import numpy as np

from .dataset import DataSet
from ..ndarray.ndarray import NDArray


class DataSetIterator:
    """Iteration SPI (reference org.nd4j.linalg.dataset.api.iterator)."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def batch(self) -> int:
        raise NotImplementedError

    def set_pre_processor(self, normalizer) -> None:
        self._pre_processor = normalizer

    def _apply_pre(self, ds: DataSet) -> DataSet:
        pre = getattr(self, "_pre_processor", None)
        if pre is not None:
            pre.pre_process(ds)
        return ds


class NDArrayDataSetIterator(DataSetIterator):
    """Iterate (features, labels) arrays in minibatches."""

    def __init__(self, features, labels, batch_size: int, shuffle: bool = False,
                 seed: int = 123):
        self.features = np.asarray(features.value if isinstance(features, NDArray) else features)
        self.labels = np.asarray(labels.value if isinstance(labels, NDArray) else labels)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0

    def batch(self) -> int:
        return self.batch_size

    def __iter__(self):
        idx = np.arange(len(self.features))
        if self.shuffle:
            np.random.RandomState(self.seed + self._epoch).shuffle(idx)
        self._epoch += 1
        for i in range(0, len(idx), self.batch_size):
            sel = idx[i:i + self.batch_size]
            yield self._apply_pre(DataSet(self.features[sel], self.labels[sel]))


class ExistingDataSetIterator(DataSetIterator):
    def __init__(self, datasets: List[DataSet]):
        self.datasets = datasets

    def __iter__(self):
        for ds in self.datasets:
            yield self._apply_pre(ds)

    def batch(self):
        return self.datasets[0].num_examples() if self.datasets else 0


class MultipleEpochsIterator(DataSetIterator):
    def __init__(self, epochs: int, inner: DataSetIterator):
        self.epochs = epochs
        self.inner = inner

    def __iter__(self):
        for _ in range(self.epochs):
            self.inner.reset()
            yield from self.inner

    def reset(self):
        self.inner.reset()

    def batch(self):
        return self.inner.batch()


# --- MNIST -------------------------------------------------------------------

_DATA_DIR = os.environ.get("DL4J_TPU_DATA_DIR",
                           os.path.expanduser("~/.deeplearning4j_tpu/data"))


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _find_idx(names: List[str]) -> Optional[str]:
    for name in names:
        for cand in (os.path.join(_DATA_DIR, name), os.path.join(_DATA_DIR, name + ".gz")):
            if os.path.exists(cand):
                return cand
    return None


def _synthetic_mnist(n: int, seed: int, train: bool) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic digit-like 28x28 glyphs: each class = a distinct stroke
    pattern + per-example jitter/noise. Linearly non-trivial, CNN-learnable."""
    rng = np.random.RandomState(seed + (0 if train else 1))
    labels = rng.randint(0, 10, n)
    images = np.zeros((n, 28, 28), np.float32)
    yy, xx = np.mgrid[0:28, 0:28]
    for i, c in enumerate(labels):
        ox, oy = rng.randint(-3, 4), rng.randint(-3, 4)
        thick = 1.5 + rng.rand()
        cxs = 14 + ox
        cys = 14 + oy
        img = np.zeros((28, 28), np.float32)
        # class-specific stroke geometry
        if c == 0:
            r = ((yy - cys) ** 2 / 81 + (xx - cxs) ** 2 / 36)
            img = np.exp(-((r - 1.0) ** 2) * 8 / thick)
        elif c == 1:
            img = np.exp(-((xx - cxs) ** 2) / thick ** 2) * (np.abs(yy - cys) < 10)
        elif c == 2:
            img = (np.exp(-((yy - cys + 8) ** 2 + (xx - cxs) ** 2 - 36) ** 2 / 300) +
                   np.exp(-((yy - cys - (xx - cxs) * 0.8 - 4) ** 2) / thick ** 2) * (np.abs(xx - cxs) < 7) +
                   np.exp(-((yy - cys - 9) ** 2) / thick ** 2) * (np.abs(xx - cxs) < 7))
        elif c == 3:
            img = (np.exp(-((yy - cys + 5) ** 2 / 4 + (xx - cxs) ** 2 / 25 - 1) ** 2 * 2) +
                   np.exp(-((yy - cys - 5) ** 2 / 4 + (xx - cxs) ** 2 / 25 - 1) ** 2 * 2))
        elif c == 4:
            img = (np.exp(-((xx - cxs - 3) ** 2) / thick ** 2) * (np.abs(yy - cys) < 9) +
                   np.exp(-((yy - cys) ** 2) / thick ** 2) * (np.abs(xx - cxs) < 8) +
                   np.exp(-((yy - cys + (xx - cxs) - 6) ** 2) / (2 * thick ** 2)) * (yy < cys + 1))
        elif c == 5:
            img = (np.exp(-((yy - cys + 8) ** 2) / thick ** 2) * (np.abs(xx - cxs) < 7) +
                   np.exp(-((xx - cxs + 6) ** 2) / thick ** 2) * (np.abs(yy - cys + 4) < 5) +
                   np.exp(-((yy - cys - 4) ** 2 / 16 + (xx - cxs) ** 2 / 36 - 1) ** 2 * 3))
        elif c == 6:
            img = (np.exp(-((yy - cys - 4) ** 2 / 25 + (xx - cxs) ** 2 / 25 - 1) ** 2 * 3) +
                   np.exp(-((xx - cxs + 4 - (cys - yy) * 0.3) ** 2) / thick ** 2) * (yy < cys + 2))
        elif c == 7:
            img = (np.exp(-((yy - cys + 8) ** 2) / thick ** 2) * (np.abs(xx - cxs) < 8) +
                   np.exp(-((xx - cxs - 6 + (yy - cys + 8) * 0.55) ** 2) / thick ** 2) * (yy > cys - 9))
        elif c == 8:
            img = (np.exp(-((yy - cys + 5) ** 2 / 9 + (xx - cxs) ** 2 / 16 - 1) ** 2 * 3) +
                   np.exp(-((yy - cys - 5) ** 2 / 12 + (xx - cxs) ** 2 / 20 - 1) ** 2 * 3))
        else:
            img = (np.exp(-((yy - cys + 4) ** 2 / 16 + (xx - cxs) ** 2 / 16 - 1) ** 2 * 3) +
                   np.exp(-((xx - cxs - 4 + (yy - cys) * 0.2) ** 2) / thick ** 2) * (yy > cys - 6))
        img = np.clip(img, 0, 1)
        img += rng.randn(28, 28) * 0.05
        images[i] = np.clip(img, 0, 1) * 255.0
    return images.astype(np.uint8), labels.astype(np.int64)


class MnistDataSetIterator(DataSetIterator):
    """Reference dl4j-data MnistDataSetIterator: 28x28 → flat [784] features in
    [0,1], one-hot [10] labels."""

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, seed: int = 6,
                 flatten: bool = True):
        self.batch_size = batch_size
        self.flatten = flatten
        self.synthetic = False
        n_default = 60000 if train else 10000
        n = num_examples or n_default
        img_path = _find_idx(["train-images-idx3-ubyte" if train else "t10k-images-idx3-ubyte",
                              "train-images.idx3-ubyte" if train else "t10k-images.idx3-ubyte"])
        lbl_path = _find_idx(["train-labels-idx1-ubyte" if train else "t10k-labels-idx1-ubyte",
                              "train-labels.idx1-ubyte" if train else "t10k-labels.idx1-ubyte"])
        if img_path and lbl_path:
            images = _read_idx(img_path)[:n]
            labels = _read_idx(lbl_path)[:n]
        else:
            self.synthetic = True
            n = min(n, 12000 if train else 2000)
            images, labels = _synthetic_mnist(n, seed, train)
        feats = images.astype(np.float32) / 255.0
        self.features = feats.reshape(len(feats), -1) if flatten \
            else feats.reshape(len(feats), 1, 28, 28)
        self.labels = np.eye(10, dtype=np.float32)[labels]

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self.features)

    def __iter__(self):
        for i in range(0, len(self.features), self.batch_size):
            yield self._apply_pre(DataSet(self.features[i:i + self.batch_size],
                                          self.labels[i:i + self.batch_size]))


class IrisDataSetIterator(DataSetIterator):
    """Reference IrisDataSetIterator — the canonical 150-example table is small
    enough to embed via its generating statistics; we synthesize the standard
    three-cluster structure deterministically."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150):
        rng = np.random.RandomState(42)
        n_per = num_examples // 3
        means = np.array([[5.0, 3.4, 1.5, 0.25], [5.9, 2.8, 4.3, 1.3],
                          [6.6, 3.0, 5.6, 2.0]], np.float32)
        stds = np.array([[0.35, 0.38, 0.17, 0.1], [0.51, 0.31, 0.47, 0.2],
                         [0.64, 0.32, 0.55, 0.27]], np.float32)
        feats, labels = [], []
        for c in range(3):
            feats.append(rng.randn(n_per, 4).astype(np.float32) * stds[c] + means[c])
            labels.append(np.full(n_per, c))
        self.features = np.concatenate(feats)
        self.labels = np.eye(3, dtype=np.float32)[np.concatenate(labels)]
        perm = rng.permutation(len(self.features))
        self.features, self.labels = self.features[perm], self.labels[perm]
        self.batch_size = batch_size

    def batch(self):
        return self.batch_size

    def __iter__(self):
        for i in range(0, len(self.features), self.batch_size):
            yield self._apply_pre(DataSet(self.features[i:i + self.batch_size],
                                          self.labels[i:i + self.batch_size]))


def _synthetic_class_images(n: int, n_classes: int, hw: int, channels: int,
                            seed: int, train: bool):
    """Per-class smooth random prototype + per-example shift/noise —
    deterministic, CNN-learnable, linearly non-trivial (the synthetic
    fallback pattern the MNIST iterator established)."""
    rng = np.random.RandomState(seed + (0 if train else 1))
    protos = np.zeros((n_classes, channels, hw, hw), np.float32)
    for c in range(n_classes):
        prng = np.random.RandomState(1000 + c)
        base = prng.randn(channels, 8, 8)
        # smooth upsample: nearest then box blur
        big = np.repeat(np.repeat(base, hw // 8 + 1, 1), hw // 8 + 1, 2)
        big = big[:, :hw, :hw]
        k = np.ones((3, 3), np.float32) / 9.0
        for ch in range(channels):
            p = np.pad(big[ch], 1, mode="edge")
            big[ch] = sum(p[dy:dy + hw, dx:dx + hw] * k[dy, dx]
                          for dy in range(3) for dx in range(3))
        protos[c] = big
    protos = (protos - protos.min()) / (np.ptp(protos) + 1e-9)
    labels = rng.randint(0, n_classes, n)
    images = np.zeros((n, channels, hw, hw), np.float32)
    for i, c in enumerate(labels):
        dx, dy = rng.randint(-3, 4, 2)
        img = np.roll(np.roll(protos[c], dy, axis=1), dx, axis=2)
        images[i] = np.clip(img + rng.randn(channels, hw, hw) * 0.15, 0, 1)
    return (images * 255).astype(np.uint8), labels.astype(np.int64)


class Cifar10DataSetIterator(DataSetIterator):
    """Reference dl4j-data Cifar10DataSetIterator: 32x32x3 in [0,1] (NCHW),
    one-hot [10]. Loads the standard binary batches when present under
    $DL4J_TPU_DATA_DIR/cifar-10-batches-bin; otherwise a deterministic
    synthetic fallback (marked via ``.synthetic``) keeps pipelines and CI
    runnable without egress."""

    LABELS = ["airplane", "automobile", "bird", "cat", "deer", "dog",
              "frog", "horse", "ship", "truck"]

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, seed: int = 6):
        self.batch_size = batch_size
        self.synthetic = False
        n = num_examples or (50000 if train else 10000)
        root = os.path.join(_DATA_DIR, "cifar-10-batches-bin")
        files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
                 else ["test_batch.bin"])
        paths = [os.path.join(root, f) for f in files]
        if all(os.path.exists(p) for p in paths):
            recs = []
            for p in paths:
                raw = np.fromfile(p, np.uint8).reshape(-1, 3073)
                recs.append(raw)
            raw = np.concatenate(recs)[:n]
            labels = raw[:, 0].astype(np.int64)
            images = raw[:, 1:].reshape(-1, 3, 32, 32)
        else:
            self.synthetic = True
            n = min(n, 8000 if train else 1500)
            images, labels = _synthetic_class_images(n, 10, 32, 3, seed,
                                                     train)
        self.features = images.astype(np.float32) / 255.0
        self.labels = np.eye(10, dtype=np.float32)[labels]

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self.features)

    def __iter__(self):
        for i in range(0, len(self.features), self.batch_size):
            yield self._apply_pre(DataSet(
                self.features[i:i + self.batch_size],
                self.labels[i:i + self.batch_size]))


class EmnistDataSetIterator(DataSetIterator):
    """Reference dl4j-data EmnistDataSetIterator. ``dataset`` picks the
    split ("letters": 26 classes, "digits"/"mnist": 10, "balanced": 47);
    idx files are looked up like MNIST's, with the synthetic per-class
    fallback otherwise."""

    _CLASSES = {"letters": 26, "digits": 10, "mnist": 10, "balanced": 47,
                "byclass": 62, "bymerge": 47}

    def __init__(self, dataset: str, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, seed: int = 6,
                 flatten: bool = True):
        if dataset not in self._CLASSES:
            raise ValueError(f"unknown EMNIST split {dataset!r}; one of "
                             f"{sorted(self._CLASSES)}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.flatten = flatten
        self.synthetic = False
        n_classes = self._CLASSES[dataset]
        n = num_examples or (60000 if train else 10000)
        tag = "train" if train else "test"
        img_path = _find_idx(
            [f"emnist-{dataset}-{tag}-images-idx3-ubyte"])
        lbl_path = _find_idx(
            [f"emnist-{dataset}-{tag}-labels-idx1-ubyte"])
        if img_path and lbl_path:
            images = _read_idx(img_path)[:n]
            labels = _read_idx(lbl_path)[:n].astype(np.int64)
            if dataset == "letters":     # letters labels are 1-based
                labels = labels - 1
            images = images.reshape(len(images), 1, 28, 28)
            # EMNIST idx files store each image TRANSPOSED relative to
            # MNIST orientation (the reference fetcher and torchvision
            # both transpose on read)
            images = images.transpose(0, 1, 3, 2)
        else:
            self.synthetic = True
            n = min(n, 6000 if train else 1000)
            images, labels = _synthetic_class_images(n, n_classes, 28, 1,
                                                     seed, train)
        feats = images.astype(np.float32) / 255.0
        self.features = feats.reshape(len(feats), -1) if flatten \
            else feats.reshape(len(feats), 1, 28, 28)
        self.labels = np.eye(n_classes, dtype=np.float32)[labels]

    def num_classes(self) -> int:
        return self.labels.shape[1]

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self.features)

    def __iter__(self):
        for i in range(0, len(self.features), self.batch_size):
            yield self._apply_pre(DataSet(
                self.features[i:i + self.batch_size],
                self.labels[i:i + self.batch_size]))
