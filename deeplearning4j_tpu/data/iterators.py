"""DataSetIterator SPI + MNIST/EMNIST/IRIS/CIFAR fetchers.

Reference: nd4j DataSetIterator + dl4j-data ``MnistDataSetIterator`` /
``IrisDataSetIterator`` / fetchers (SURVEY.md §2.3 dataset iterators row).

MNIST: the reference auto-downloads IDX files (``MnistDataFetcher``). This
environment has no egress, so the fetcher (a) reads IDX files from
``DL4J_TPU_DATA_DIR`` (default ~/.deeplearning4j_tpu/data) when present —
format-compatible with the standard MNIST distribution — and (b) otherwise
generates a deterministic synthetic digit set with the same shapes/dtypes
(28x28 grayscale, 10 classes, procedurally drawn glyph-like patterns) so the
full pipeline trains and benchmarks without network access. The synthetic
fallback is clearly marked via ``.synthetic``.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Iterator, List, Optional, Tuple

import numpy as np

from .dataset import DataSet
from ..ndarray.ndarray import NDArray


class DataSetIterator:
    """Iteration SPI (reference org.nd4j.linalg.dataset.api.iterator)."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def batch(self) -> int:
        raise NotImplementedError

    def set_pre_processor(self, normalizer) -> None:
        self._pre_processor = normalizer

    def _apply_pre(self, ds: DataSet) -> DataSet:
        pre = getattr(self, "_pre_processor", None)
        if pre is not None:
            pre.pre_process(ds)
        return ds

    # --- supervised-restart protocol (parallel.distributed) ------------
    # A source with cross-epoch state (per-epoch shuffle RNG) opts into
    # in-process restart by exposing its rewindable state: the
    # TrainingSupervisor captures source_state() at fit entry and
    # restores it before every restarted attempt, so the checkpoint
    # cursor's host replay sees the SAME epoch/shuffle sequence the
    # killed attempt saw. Stateless-per-epoch sources need neither.
    def source_state(self) -> Optional[dict]:
        return None

    def restore_source_state(self, state: dict) -> None:
        pass


class NDArrayDataSetIterator(DataSetIterator):
    """Iterate (features, labels) arrays in minibatches."""

    def __init__(self, features, labels, batch_size: int, shuffle: bool = False,
                 seed: int = 123, drop_remainder: bool = False):
        self.features = np.asarray(features.value if isinstance(features, NDArray) else features)
        self.labels = np.asarray(labels.value if isinstance(labels, NDArray) else labels)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self._epoch = 0

    def batch(self) -> int:
        return self.batch_size

    def source_state(self) -> dict:
        # the per-epoch shuffle key is seed + _epoch: rewinding _epoch is
        # all an in-process restart needs to replay identical shuffles
        return {"epoch": self._epoch}

    def restore_source_state(self, state: dict) -> None:
        self._epoch = int(state.get("epoch", 0))

    def __iter__(self):
        idx = np.arange(len(self.features))
        if self.shuffle:
            np.random.RandomState(self.seed + self._epoch).shuffle(idx)
        self._epoch += 1
        stop = len(idx)
        if self.drop_remainder:
            stop = (stop // self.batch_size) * self.batch_size
        for i in range(0, stop, self.batch_size):
            sel = idx[i:i + self.batch_size]
            yield self._apply_pre(DataSet(self.features[sel], self.labels[sel]))


class ExistingDataSetIterator(DataSetIterator):
    def __init__(self, datasets: List[DataSet]):
        self.datasets = datasets

    def __iter__(self):
        for ds in self.datasets:
            yield self._apply_pre(ds)

    def batch(self):
        return self.datasets[0].num_examples() if self.datasets else 0


class MultipleEpochsIterator(DataSetIterator):
    def __init__(self, epochs: int, inner: DataSetIterator):
        self.epochs = epochs
        self.inner = inner

    def __iter__(self):
        for _ in range(self.epochs):
            self.inner.reset()
            yield from self.inner

    def reset(self):
        self.inner.reset()

    def batch(self):
        return self.inner.batch()


# --- MNIST -------------------------------------------------------------------

_DATA_DIR = os.environ.get("DL4J_TPU_DATA_DIR",
                           os.path.expanduser("~/.deeplearning4j_tpu/data"))


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _find_idx(names: List[str]) -> Optional[str]:
    for name in names:
        for cand in (os.path.join(_DATA_DIR, name), os.path.join(_DATA_DIR, name + ".gz")):
            if os.path.exists(cand):
                return cand
    return None


def _synthetic_mnist(n: int, seed: int, train: bool) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic digit-like 28x28 glyphs: each class = a distinct stroke
    pattern + per-example jitter/noise. Linearly non-trivial, CNN-learnable."""
    rng = np.random.RandomState(seed + (0 if train else 1))
    labels = rng.randint(0, 10, n)
    images = np.zeros((n, 28, 28), np.float32)
    yy, xx = np.mgrid[0:28, 0:28]
    for i, c in enumerate(labels):
        ox, oy = rng.randint(-3, 4), rng.randint(-3, 4)
        thick = 1.5 + rng.rand()
        cxs = 14 + ox
        cys = 14 + oy
        img = np.zeros((28, 28), np.float32)
        # class-specific stroke geometry
        if c == 0:
            r = ((yy - cys) ** 2 / 81 + (xx - cxs) ** 2 / 36)
            img = np.exp(-((r - 1.0) ** 2) * 8 / thick)
        elif c == 1:
            img = np.exp(-((xx - cxs) ** 2) / thick ** 2) * (np.abs(yy - cys) < 10)
        elif c == 2:
            img = (np.exp(-((yy - cys + 8) ** 2 + (xx - cxs) ** 2 - 36) ** 2 / 300) +
                   np.exp(-((yy - cys - (xx - cxs) * 0.8 - 4) ** 2) / thick ** 2) * (np.abs(xx - cxs) < 7) +
                   np.exp(-((yy - cys - 9) ** 2) / thick ** 2) * (np.abs(xx - cxs) < 7))
        elif c == 3:
            img = (np.exp(-((yy - cys + 5) ** 2 / 4 + (xx - cxs) ** 2 / 25 - 1) ** 2 * 2) +
                   np.exp(-((yy - cys - 5) ** 2 / 4 + (xx - cxs) ** 2 / 25 - 1) ** 2 * 2))
        elif c == 4:
            img = (np.exp(-((xx - cxs - 3) ** 2) / thick ** 2) * (np.abs(yy - cys) < 9) +
                   np.exp(-((yy - cys) ** 2) / thick ** 2) * (np.abs(xx - cxs) < 8) +
                   np.exp(-((yy - cys + (xx - cxs) - 6) ** 2) / (2 * thick ** 2)) * (yy < cys + 1))
        elif c == 5:
            img = (np.exp(-((yy - cys + 8) ** 2) / thick ** 2) * (np.abs(xx - cxs) < 7) +
                   np.exp(-((xx - cxs + 6) ** 2) / thick ** 2) * (np.abs(yy - cys + 4) < 5) +
                   np.exp(-((yy - cys - 4) ** 2 / 16 + (xx - cxs) ** 2 / 36 - 1) ** 2 * 3))
        elif c == 6:
            img = (np.exp(-((yy - cys - 4) ** 2 / 25 + (xx - cxs) ** 2 / 25 - 1) ** 2 * 3) +
                   np.exp(-((xx - cxs + 4 - (cys - yy) * 0.3) ** 2) / thick ** 2) * (yy < cys + 2))
        elif c == 7:
            img = (np.exp(-((yy - cys + 8) ** 2) / thick ** 2) * (np.abs(xx - cxs) < 8) +
                   np.exp(-((xx - cxs - 6 + (yy - cys + 8) * 0.55) ** 2) / thick ** 2) * (yy > cys - 9))
        elif c == 8:
            img = (np.exp(-((yy - cys + 5) ** 2 / 9 + (xx - cxs) ** 2 / 16 - 1) ** 2 * 3) +
                   np.exp(-((yy - cys - 5) ** 2 / 12 + (xx - cxs) ** 2 / 20 - 1) ** 2 * 3))
        else:
            img = (np.exp(-((yy - cys + 4) ** 2 / 16 + (xx - cxs) ** 2 / 16 - 1) ** 2 * 3) +
                   np.exp(-((xx - cxs - 4 + (yy - cys) * 0.2) ** 2) / thick ** 2) * (yy > cys - 6))
        img = np.clip(img, 0, 1)
        img += rng.randn(28, 28) * 0.05
        images[i] = np.clip(img, 0, 1) * 255.0
    return images.astype(np.uint8), labels.astype(np.int64)


class MnistDataSetIterator(DataSetIterator):
    """Reference dl4j-data MnistDataSetIterator: 28x28 → flat [784] features in
    [0,1], one-hot [10] labels."""

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, seed: int = 6,
                 flatten: bool = True):
        self.batch_size = batch_size
        self.flatten = flatten
        self.synthetic = False
        n_default = 60000 if train else 10000
        n = num_examples or n_default
        img_path = _find_idx(["train-images-idx3-ubyte" if train else "t10k-images-idx3-ubyte",
                              "train-images.idx3-ubyte" if train else "t10k-images.idx3-ubyte"])
        lbl_path = _find_idx(["train-labels-idx1-ubyte" if train else "t10k-labels-idx1-ubyte",
                              "train-labels.idx1-ubyte" if train else "t10k-labels.idx1-ubyte"])
        if img_path and lbl_path:
            images = _read_idx(img_path)[:n]
            labels = _read_idx(lbl_path)[:n]
        else:
            self.synthetic = True
            n = min(n, 12000 if train else 2000)
            images, labels = _synthetic_mnist(n, seed, train)
        feats = images.astype(np.float32) / 255.0
        self.features = feats.reshape(len(feats), -1) if flatten \
            else feats.reshape(len(feats), 1, 28, 28)
        self.labels = np.eye(10, dtype=np.float32)[labels]

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self.features)

    def __iter__(self):
        for i in range(0, len(self.features), self.batch_size):
            yield self._apply_pre(DataSet(self.features[i:i + self.batch_size],
                                          self.labels[i:i + self.batch_size]))


class IrisDataSetIterator(DataSetIterator):
    """Reference IrisDataSetIterator — the canonical 150-example table is small
    enough to embed via its generating statistics; we synthesize the standard
    three-cluster structure deterministically."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150):
        rng = np.random.RandomState(42)
        n_per = num_examples // 3
        means = np.array([[5.0, 3.4, 1.5, 0.25], [5.9, 2.8, 4.3, 1.3],
                          [6.6, 3.0, 5.6, 2.0]], np.float32)
        stds = np.array([[0.35, 0.38, 0.17, 0.1], [0.51, 0.31, 0.47, 0.2],
                         [0.64, 0.32, 0.55, 0.27]], np.float32)
        feats, labels = [], []
        for c in range(3):
            feats.append(rng.randn(n_per, 4).astype(np.float32) * stds[c] + means[c])
            labels.append(np.full(n_per, c))
        self.features = np.concatenate(feats)
        self.labels = np.eye(3, dtype=np.float32)[np.concatenate(labels)]
        perm = rng.permutation(len(self.features))
        self.features, self.labels = self.features[perm], self.labels[perm]
        self.batch_size = batch_size

    def batch(self):
        return self.batch_size

    def __iter__(self):
        for i in range(0, len(self.features), self.batch_size):
            yield self._apply_pre(DataSet(self.features[i:i + self.batch_size],
                                          self.labels[i:i + self.batch_size]))


def _synthetic_class_images(n: int, n_classes: int, hw: int, channels: int,
                            seed: int, train: bool):
    """Per-class smooth random prototype + per-example shift/noise —
    deterministic, CNN-learnable, linearly non-trivial (the synthetic
    fallback pattern the MNIST iterator established)."""
    rng = np.random.RandomState(seed + (0 if train else 1))
    protos = np.zeros((n_classes, channels, hw, hw), np.float32)
    for c in range(n_classes):
        prng = np.random.RandomState(1000 + c)
        base = prng.randn(channels, 8, 8)
        # smooth upsample: nearest then box blur
        big = np.repeat(np.repeat(base, hw // 8 + 1, 1), hw // 8 + 1, 2)
        big = big[:, :hw, :hw]
        k = np.ones((3, 3), np.float32) / 9.0
        for ch in range(channels):
            p = np.pad(big[ch], 1, mode="edge")
            big[ch] = sum(p[dy:dy + hw, dx:dx + hw] * k[dy, dx]
                          for dy in range(3) for dx in range(3))
        protos[c] = big
    protos = (protos - protos.min()) / (np.ptp(protos) + 1e-9)
    labels = rng.randint(0, n_classes, n)
    images = np.zeros((n, channels, hw, hw), np.float32)
    for i, c in enumerate(labels):
        dx, dy = rng.randint(-3, 4, 2)
        img = np.roll(np.roll(protos[c], dy, axis=1), dx, axis=2)
        images[i] = np.clip(img + rng.randn(channels, hw, hw) * 0.15, 0, 1)
    return (images * 255).astype(np.uint8), labels.astype(np.int64)


class Cifar10DataSetIterator(DataSetIterator):
    """Reference dl4j-data Cifar10DataSetIterator: 32x32x3 in [0,1] (NCHW),
    one-hot [10]. Loads the standard binary batches when present under
    $DL4J_TPU_DATA_DIR/cifar-10-batches-bin; otherwise a deterministic
    synthetic fallback (marked via ``.synthetic``) keeps pipelines and CI
    runnable without egress."""

    LABELS = ["airplane", "automobile", "bird", "cat", "deer", "dog",
              "frog", "horse", "ship", "truck"]

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, seed: int = 6):
        self.batch_size = batch_size
        self.synthetic = False
        n = num_examples or (50000 if train else 10000)
        root = os.path.join(_DATA_DIR, "cifar-10-batches-bin")
        files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
                 else ["test_batch.bin"])
        paths = [os.path.join(root, f) for f in files]
        if all(os.path.exists(p) for p in paths):
            recs = []
            for p in paths:
                raw = np.fromfile(p, np.uint8).reshape(-1, 3073)
                recs.append(raw)
            raw = np.concatenate(recs)[:n]
            labels = raw[:, 0].astype(np.int64)
            images = raw[:, 1:].reshape(-1, 3, 32, 32)
        else:
            self.synthetic = True
            n = min(n, 8000 if train else 1500)
            images, labels = _synthetic_class_images(n, 10, 32, 3, seed,
                                                     train)
        self.features = images.astype(np.float32) / 255.0
        self.labels = np.eye(10, dtype=np.float32)[labels]

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self.features)

    def __iter__(self):
        for i in range(0, len(self.features), self.batch_size):
            yield self._apply_pre(DataSet(
                self.features[i:i + self.batch_size],
                self.labels[i:i + self.batch_size]))


class EmnistDataSetIterator(DataSetIterator):
    """Reference dl4j-data EmnistDataSetIterator. ``dataset`` picks the
    split ("letters": 26 classes, "digits"/"mnist": 10, "balanced": 47);
    idx files are looked up like MNIST's, with the synthetic per-class
    fallback otherwise."""

    _CLASSES = {"letters": 26, "digits": 10, "mnist": 10, "balanced": 47,
                "byclass": 62, "bymerge": 47}

    def __init__(self, dataset: str, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, seed: int = 6,
                 flatten: bool = True):
        if dataset not in self._CLASSES:
            raise ValueError(f"unknown EMNIST split {dataset!r}; one of "
                             f"{sorted(self._CLASSES)}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.flatten = flatten
        self.synthetic = False
        n_classes = self._CLASSES[dataset]
        n = num_examples or (60000 if train else 10000)
        tag = "train" if train else "test"
        img_path = _find_idx(
            [f"emnist-{dataset}-{tag}-images-idx3-ubyte"])
        lbl_path = _find_idx(
            [f"emnist-{dataset}-{tag}-labels-idx1-ubyte"])
        if img_path and lbl_path:
            images = _read_idx(img_path)[:n]
            labels = _read_idx(lbl_path)[:n].astype(np.int64)
            if dataset == "letters":     # letters labels are 1-based
                labels = labels - 1
            images = images.reshape(len(images), 1, 28, 28)
            # EMNIST idx files store each image TRANSPOSED relative to
            # MNIST orientation (the reference fetcher and torchvision
            # both transpose on read)
            images = images.transpose(0, 1, 3, 2)
        else:
            self.synthetic = True
            n = min(n, 6000 if train else 1000)
            images, labels = _synthetic_class_images(n, n_classes, 28, 1,
                                                     seed, train)
        feats = images.astype(np.float32) / 255.0
        self.features = feats.reshape(len(feats), -1) if flatten \
            else feats.reshape(len(feats), 1, 28, 28)
        self.labels = np.eye(n_classes, dtype=np.float32)[labels]

    def num_classes(self) -> int:
        return self.labels.shape[1]

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self.features)

    def __iter__(self):
        for i in range(0, len(self.features), self.batch_size):
            yield self._apply_pre(DataSet(
                self.features[i:i + self.batch_size],
                self.labels[i:i + self.batch_size]))


class LFWDataSetIterator(DataSetIterator):
    """Reference dl4j-data LFWDataSetIterator (SURVEY §2.3 datasets row):
    face images labeled by person, loaded from a local
    ``<data dir>/lfw/<person>/<img>.jpg`` tree when present (the
    reference's auto-download has no egress analog here), else the
    established synthetic per-class fallback (marked ``.synthetic``).
    Images are NCHW float32 in [0, 1]."""

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 image_hw: int = 64, n_classes: int = 20, train: bool = True,
                 seed: int = 11):
        self.batch_size = batch_size
        self.synthetic = False
        root = os.path.join(_DATA_DIR, "lfw")
        loaded = None
        if os.path.isdir(root):
            loaded = _load_image_tree(root, image_hw,
                                      num_examples or 13233)
        if loaded is not None:
            images, labels, self._names = loaded
            # one-hot width = ALL class dirs (a capped load may not reach
            # the last ones); per-class split honors the train flag
            n_classes = len(self._names)
            sel = _stratified_split(labels, train, seed=seed)
            images, labels = images[sel], labels[sel]
        else:
            self.synthetic = True
            n = min(num_examples or 1600, 4000)
            images, labels = _synthetic_class_images(
                n, n_classes, image_hw, 3, seed, train)
            self._names = [f"person_{c}" for c in range(n_classes)]
        self.features = images.astype(np.float32) / 255.0
        self.labels = np.eye(n_classes, dtype=np.float32)[labels]

    def num_classes(self) -> int:
        return self.labels.shape[1]

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self.features)

    def __iter__(self):
        for i in range(0, len(self.features), self.batch_size):
            yield self._apply_pre(DataSet(
                self.features[i:i + self.batch_size],
                self.labels[i:i + self.batch_size]))


class TinyImageNetDataSetIterator(DataSetIterator):
    """Reference dl4j-data TinyImageNetDataSetIterator: 64x64x3, 200
    classes, loaded from a local ``<data dir>/tiny-imagenet-200`` tree
    (``train/<wnid>/images/*.JPEG``) when present, else the synthetic
    per-class fallback (capped well below the real 100k examples)."""

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 train: bool = True, seed: int = 12):
        self.batch_size = batch_size
        self.synthetic = False
        base = os.path.join(_DATA_DIR, "tiny-imagenet-200")
        loaded = None
        if train and os.path.isdir(os.path.join(base, "train")):
            loaded = _load_image_tree(os.path.join(base, "train"), 64,
                                      num_examples or 100_000,
                                      nested="images")
            if loaded is not None:
                images, labels, names = loaded
                n_classes = len(names)
        elif not train and os.path.isdir(os.path.join(base, "val")):
            # the real val split is FLAT (val/images/*.JPEG +
            # val_annotations.txt mapping file → wnid), not per-class dirs
            loaded = self._load_val(base, num_examples or 10_000)
            if loaded is not None:
                images, labels, n_classes = loaded
        if loaded is None:
            self.synthetic = True
            n_classes = 200
            n = min(num_examples or 2000, 10_000)
            images, labels = _synthetic_class_images(
                n, n_classes, 64, 3, seed, train)
        self.features = images.astype(np.float32) / 255.0
        self.labels = np.eye(n_classes, dtype=np.float32)[labels]

    @staticmethod
    def _load_val(base: str, limit: int):
        """val/images/*.JPEG labeled via val_annotations.txt, with wnid →
        index taken from the sorted train/ class dirs (the canonical
        label order)."""
        try:
            from PIL import Image
        except ImportError:
            return None
        ann = os.path.join(base, "val", "val_annotations.txt")
        train_root = os.path.join(base, "train")
        if not os.path.exists(ann) or not os.path.isdir(train_root):
            return None
        classes = sorted(d for d in os.listdir(train_root)
                         if os.path.isdir(os.path.join(train_root, d)))
        class_of = {c: i for i, c in enumerate(classes)}
        images, labels = [], []
        with open(ann, encoding="utf-8") as f:
            for line in f:
                parts = line.split("\t")
                if len(parts) < 2 or parts[1] not in class_of:
                    continue
                p = os.path.join(base, "val", "images", parts[0])
                if not os.path.exists(p):
                    continue
                img = Image.open(p).convert("RGB")
                if img.size != (64, 64):
                    img = img.resize((64, 64))
                images.append(np.asarray(img, np.uint8).transpose(2, 0, 1))
                labels.append(class_of[parts[1]])
                if len(images) >= limit:
                    break
        if not images:
            return None
        return (np.stack(images), np.asarray(labels, np.int64),
                len(classes))

    def num_classes(self) -> int:
        return self.labels.shape[1]

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self.features)

    def __iter__(self):
        for i in range(0, len(self.features), self.batch_size):
            yield self._apply_pre(DataSet(
                self.features[i:i + self.batch_size],
                self.labels[i:i + self.batch_size]))


def _load_image_tree(root: str, hw: int, limit: int,
                     nested: Optional[str] = None):
    """<root>/<class>/[nested/]*.{jpg,jpeg,png} → (uint8 NCHW, labels,
    class names); None when PIL is unavailable or the tree is empty.
    The ``limit`` cap applies PER CLASS (ceil(limit / n_classes)) so a
    capped load still spans every class instead of truncating the
    alphabetical walk to the first few."""
    try:
        from PIL import Image
    except ImportError:
        return None
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    if not classes:
        return None
    per_class = max(1, -(-limit // len(classes)))
    images, labels = [], []
    for ci, cname in enumerate(classes):
        d = os.path.join(root, cname)
        if nested and os.path.isdir(os.path.join(d, nested)):
            d = os.path.join(d, nested)
        taken = 0
        for f in sorted(os.listdir(d)):
            if not f.lower().endswith((".jpg", ".jpeg", ".png")):
                continue
            img = Image.open(os.path.join(d, f)).convert("RGB")
            if img.size != (hw, hw):
                img = img.resize((hw, hw))
            images.append(np.asarray(img, np.uint8).transpose(2, 0, 1))
            labels.append(ci)
            taken += 1
            if taken >= per_class or len(images) >= limit:
                break
        if len(images) >= limit:
            break
    if not images:
        return None
    return (np.stack(images), np.asarray(labels, np.int64), classes)


def _stratified_split(labels: np.ndarray, train: bool, frac: float = 0.75,
                      seed: int = 0) -> np.ndarray:
    """Deterministic PER-CLASS train/test index split (the reference
    iterators split within each class, not with one global permutation)."""
    sel = []
    rng = np.random.RandomState(seed)
    for c in np.unique(labels):
        idx = np.flatnonzero(labels == c)
        idx = idx[rng.permutation(len(idx))]
        cut = int(round(len(idx) * frac))
        sel.append(idx[:cut] if train else idx[cut:])
    return np.sort(np.concatenate(sel)) if sel else np.zeros(0, np.int64)


class UciSequenceDataSetIterator(DataSetIterator):
    """Reference dl4j-data UciSequenceDataSetIterator: the UCI
    synthetic-control time series (600 sequences x 60 steps, 6 classes:
    normal, cyclic, increasing, decreasing, upward shift, downward
    shift). Reads a local ``synthetic_control.data`` when present;
    otherwise REGENERATES the six patterns with the dataset's own
    published generator equations (the original UCI data is itself
    synthetic, so the fallback is the same distribution, marked
    ``.synthetic``). Features [B, 60, 1], one-hot labels [B, 6]."""

    N_CLASSES = 6
    T = 60

    def __init__(self, batch_size: int, train: bool = True, seed: int = 13):
        self.batch_size = batch_size
        self.synthetic = False
        path = _find_idx(["synthetic_control.data"])
        if path:
            raw = np.loadtxt(path)               # [600, 60]
            labels = np.repeat(np.arange(6), 100)
        else:
            self.synthetic = True
            raw, labels = self._generate(600, seed + (0 if train else 1))
        # 75/25 split STRATIFIED per class (the reference splits within
        # each class block, never a global permutation)
        sel = _stratified_split(labels, train, seed=seed)
        self.features = raw[sel, :, None].astype(np.float32)
        self.labels = np.eye(self.N_CLASSES,
                             dtype=np.float32)[labels[sel]]

    @staticmethod
    def _generate(n: int, seed: int):
        """The six synthetic-control equations (Alcock & Manolopoulos):
        m=30, s=2; cyclic adds a sine, trends add +/- gradient, shifts
        add a step at a random changepoint."""
        rng = np.random.RandomState(seed)
        T = UciSequenceDataSetIterator.T
        t = np.arange(T, dtype=np.float64)
        seqs, labels = [], []
        per = n // 6
        for c in range(6):
            for _ in range(per):
                base = 30.0 + 2.0 * rng.standard_normal(T)
                if c == 1:    # cyclic
                    a = rng.uniform(10, 15)
                    period = rng.uniform(10, 15)
                    base += a * np.sin(2 * np.pi * t / period)
                elif c == 2:  # increasing trend
                    base += rng.uniform(0.2, 0.5) * t
                elif c == 3:  # decreasing trend
                    base -= rng.uniform(0.2, 0.5) * t
                elif c == 4:  # upward shift
                    p = rng.randint(T // 3, 2 * T // 3)
                    base += rng.uniform(7.5, 20) * (t >= p)
                elif c == 5:  # downward shift
                    p = rng.randint(T // 3, 2 * T // 3)
                    base -= rng.uniform(7.5, 20) * (t >= p)
                seqs.append(base)
                labels.append(c)
        return np.asarray(seqs), np.asarray(labels, np.int64)

    def num_classes(self) -> int:
        return self.N_CLASSES

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self.features)

    def __iter__(self):
        for i in range(0, len(self.features), self.batch_size):
            yield self._apply_pre(DataSet(
                self.features[i:i + self.batch_size],
                self.labels[i:i + self.batch_size]))
