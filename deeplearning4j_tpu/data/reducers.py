"""Reduction (group-by aggregation) and joins over record collections.

Reference: datavec-api ``transform.reduce.Reducer`` (+ ``ReduceOp``) and
``transform.join.Join`` (SURVEY §2.3 DataVec core row). Same shapes: a
``Reducer`` groups records by key columns and aggregates every other
column with a configured op; a ``Join`` merges two record collections on
key columns with Inner/LeftOuter/RightOuter/FullOuter semantics.

Host-side pure Python/numpy — this is ETL front matter feeding the
vectorized DataSet assembly, not device math.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .records import Record
from .schema import Schema

_NUMERIC = ("double", "numeric", "integer", "long", "time")


def _agg(op: str, values: List[Any]):
    if op == "count":
        return len(values)
    if op == "count_unique":
        return len(set(values))
    if op == "first":
        return values[0]
    if op == "last":
        return values[-1]
    arr = np.asarray([float(v) for v in values], np.float64)
    if op == "sum":
        return float(arr.sum())
    if op == "mean":
        return float(arr.mean())
    if op == "min":
        return float(arr.min())
    if op == "max":
        return float(arr.max())
    if op == "range":
        return float(arr.max() - arr.min())
    if op == "stdev":
        return float(arr.std(ddof=1)) if arr.size > 1 else 0.0
    raise ValueError(f"unknown reduce op {op!r}")


_OUT_TYPE = {"count": "long", "count_unique": "long", "sum": "double",
             "mean": "double", "min": "double", "max": "double",
             "range": "double", "stdev": "double"}


class Reducer:
    """reference: Reducer.Builder(ReduceOp default).keyColumns(...)
    .sumColumns(...).meanColumns(...)... then ``reduce(records)``."""

    class Builder:
        def __init__(self, default_op: str = "first"):
            self._default = default_op
            self._keys: Tuple[str, ...] = ()
            self._ops: Dict[str, str] = {}

        def key_columns(self, *names: str) -> "Reducer.Builder":
            self._keys = names
            return self

        def _set(self, op: str, names: Sequence[str]) -> "Reducer.Builder":
            for n in names:
                self._ops[n] = op
            return self

        def sum_columns(self, *n): return self._set("sum", n)
        def mean_columns(self, *n): return self._set("mean", n)
        def min_columns(self, *n): return self._set("min", n)
        def max_columns(self, *n): return self._set("max", n)
        def range_columns(self, *n): return self._set("range", n)
        def stdev_columns(self, *n): return self._set("stdev", n)
        def count_columns(self, *n): return self._set("count", n)
        def count_unique_columns(self, *n): return self._set("count_unique", n)
        def first_columns(self, *n): return self._set("first", n)
        def last_columns(self, *n): return self._set("last", n)

        def build(self) -> "Reducer":
            if not self._keys:
                raise ValueError("key_columns required")
            return Reducer(self._keys, self._ops, self._default)

    @staticmethod
    def builder(default_op: str = "first") -> "Reducer.Builder":
        return Reducer.Builder(default_op)

    def __init__(self, keys: Sequence[str], ops: Dict[str, str],
                 default_op: str):
        self.keys = tuple(keys)
        self.ops = dict(ops)
        self.default_op = default_op

    def output_schema(self, schema: Schema) -> Schema:
        b = Schema.builder()
        for name in schema.column_names():
            if name in self.keys:
                ctype = schema.column_type(name)
            else:
                op = self.ops.get(name, self.default_op)
                ctype = _OUT_TYPE.get(op, schema.column_type(name))
            out_name = name if name in self.keys else \
                f"{self.ops.get(name, self.default_op)}({name})"
            if ctype == "integer":
                b.add_column_integer(out_name)
            elif ctype == "long":
                b.add_column_long(out_name)
            elif ctype == "categorical":
                b.add_column_categorical(out_name,
                                         schema.categorical_states(name))
            elif ctype == "string":
                b.add_column_string(out_name)
            else:
                b.add_column_double(out_name)
        return b.build()

    def reduce(self, schema: Schema, records: Sequence[Record]
               ) -> List[Record]:
        key_idx = [schema.index_of(k) for k in self.keys]
        names = schema.column_names()
        groups: "OrderedDict[Tuple, List[Record]]" = OrderedDict()
        for rec in records:
            k = tuple(rec[i] for i in key_idx)
            groups.setdefault(k, []).append(rec)
        out = []
        for k, rows in groups.items():
            rec_out: Record = []
            for i, name in enumerate(names):
                if name in self.keys:
                    rec_out.append(rows[0][i])
                else:
                    op = self.ops.get(name, self.default_op)
                    rec_out.append(_agg(op, [r[i] for r in rows]))
            out.append(rec_out)
        return out


class Join:
    """reference: transform.join.Join.Builder(JoinType).setJoinColumns(...)
    over two schemas; ``execute`` merges the record collections. Output
    columns = left columns + right columns minus the (shared) keys."""

    INNER = "inner"
    LEFT_OUTER = "left_outer"
    RIGHT_OUTER = "right_outer"
    FULL_OUTER = "full_outer"

    class Builder:
        def __init__(self, join_type: str = "inner"):
            self._type = join_type
            self._keys: Tuple[str, ...] = ()
            self._left: Optional[Schema] = None
            self._right: Optional[Schema] = None

        def set_join_columns(self, *names: str) -> "Join.Builder":
            self._keys = names
            return self

        def set_schemas(self, left: Schema, right: Schema) -> "Join.Builder":
            self._left, self._right = left, right
            return self

        def build(self) -> "Join":
            if not self._keys or self._left is None or self._right is None:
                raise ValueError("join columns + both schemas required")
            return Join(self._type, self._keys, self._left, self._right)

    @staticmethod
    def builder(join_type: str = "inner") -> "Join.Builder":
        return Join.Builder(join_type)

    def __init__(self, join_type: str, keys: Sequence[str], left: Schema,
                 right: Schema):
        if join_type not in (self.INNER, self.LEFT_OUTER, self.RIGHT_OUTER,
                             self.FULL_OUTER):
            raise ValueError(f"unknown join type {join_type!r}")
        self.join_type = join_type
        self.keys = tuple(keys)
        self.left = left
        self.right = right

    def output_schema(self) -> Schema:
        b = Schema.builder()
        added = set()

        def add(schema, name):
            ctype = schema.column_type(name)
            if ctype == "integer":
                b.add_column_integer(name)
            elif ctype == "long":
                b.add_column_long(name)
            elif ctype == "categorical":
                b.add_column_categorical(name,
                                         schema.categorical_states(name))
            elif ctype == "string":
                b.add_column_string(name)
            else:
                b.add_column_double(name)
            added.add(name)

        for n in self.left.column_names():
            add(self.left, n)
        for n in self.right.column_names():
            if n not in self.keys:
                add(self.right, f"right_{n}" if n in added else n)
        return b.build()

    def execute(self, left_records: Sequence[Record],
                right_records: Sequence[Record]) -> List[Record]:
        lk = [self.left.index_of(k) for k in self.keys]
        rk = [self.right.index_of(k) for k in self.keys]
        r_nonkey = [i for i, n in enumerate(self.right.column_names())
                    if n not in self.keys]
        r_by_key: "OrderedDict[Tuple, List[Record]]" = OrderedDict()
        for rec in right_records:
            r_by_key.setdefault(tuple(rec[i] for i in rk), []).append(rec)
        out: List[Record] = []
        matched_right = set()
        for rec in left_records:
            k = tuple(rec[i] for i in lk)
            matches = r_by_key.get(k)
            if matches:
                matched_right.add(k)
                for rrec in matches:
                    out.append(list(rec) + [rrec[i] for i in r_nonkey])
            elif self.join_type in (self.LEFT_OUTER, self.FULL_OUTER):
                out.append(list(rec) + [None] * len(r_nonkey))
        if self.join_type in (self.RIGHT_OUTER, self.FULL_OUTER):
            left_names = self.left.column_names()
            for k, rrecs in r_by_key.items():
                if k in matched_right:
                    continue
                for rrec in rrecs:
                    rec_out: Record = []
                    for n in left_names:
                        if n in self.keys:
                            rec_out.append(k[self.keys.index(n)])
                        else:
                            rec_out.append(None)
                    rec_out.extend(rrec[i] for i in r_nonkey)
                    out.append(rec_out)
        return out
