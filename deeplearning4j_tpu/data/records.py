"""RecordReader SPI + file splits (DataVec core analog).

Reference: datavec-api ``org.datavec.api.records.reader.RecordReader`` with
``CSVRecordReader`` / ``LineRecordReader`` / ``CSVSequenceRecordReader`` and
``org.datavec.api.split.{FileSplit, CollectionInputSplit}`` (SURVEY.md §2.3
DataVec core row).

A record is a plain Python list of cell values (the reference's
``List<Writable>``); a sequence record is a list of records. Readers are
restartable iterators over an input split — host-side pure Python, feeding
the vectorized DataSet assembly in ``record_iterator.py``.
"""

from __future__ import annotations

import csv
import io
from pathlib import Path
from typing import Any, Iterator, List, Optional, Sequence, Union

Record = List[Any]
SequenceRecord = List[Record]
PathLike = Union[str, Path]


class InputSplit:
    """Source-of-URIs SPI (reference: org.datavec.api.split.InputSplit)."""

    def locations(self) -> List[Path]:
        raise NotImplementedError


class FileSplit(InputSplit):
    """All files under a root (or a single file), optionally filtered by
    extension, sorted for determinism (reference: FileSplit)."""

    def __init__(self, root: PathLike,
                 allowed_extensions: Optional[Sequence[str]] = None,
                 recursive: bool = True):
        self.root = Path(root)
        self.allowed = (tuple(e.lower().lstrip(".") for e in
                              allowed_extensions)
                        if allowed_extensions else None)
        self.recursive = recursive

    def locations(self) -> List[Path]:
        if self.root.is_file():
            return [self.root]
        pattern = "**/*" if self.recursive else "*"
        files = [p for p in self.root.glob(pattern) if p.is_file()]
        if self.allowed is not None:
            files = [p for p in files
                     if p.suffix.lower().lstrip(".") in self.allowed]
        return sorted(files)


class CollectionInputSplit(InputSplit):
    def __init__(self, paths: Sequence[PathLike]):
        self._paths = [Path(p) for p in paths]

    def locations(self) -> List[Path]:
        return list(self._paths)


class RecordReader:
    """One record at a time from an input split (reference: RecordReader —
    initialize(split) / hasNext / next / reset)."""

    def initialize(self, split: InputSplit) -> None:
        self._split = split
        self.reset()

    def reset(self) -> None:
        self._iter = self._make_iter()

    def has_next(self) -> bool:
        if not hasattr(self, "_peek"):
            try:
                self._peek = next(self._iter)
            except StopIteration:
                return False
        return True

    def next(self) -> Record:
        if self.has_next():
            rec = self._peek
            del self._peek
            return rec
        raise StopIteration

    def __iter__(self) -> Iterator[Record]:
        self.reset()
        while self.has_next():
            yield self.next()

    def _make_iter(self) -> Iterator[Record]:
        raise NotImplementedError


class LineRecordReader(RecordReader):
    """One line → one single-cell record (reference: LineRecordReader)."""

    def _make_iter(self) -> Iterator[Record]:
        for path in self._split.locations():
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                for line in f:
                    yield [line.rstrip("\n")]


class CSVRecordReader(RecordReader):
    """CSV rows → records of string cells (reference: CSVRecordReader —
    skip_num_lines for headers, configurable delimiter/quote)."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ",",
                 quote: str = '"'):
        self.skip_num_lines = skip_num_lines
        self.delimiter = delimiter
        self.quote = quote

    def _make_iter(self) -> Iterator[Record]:
        for path in self._split.locations():
            with open(path, "r", encoding="utf-8", newline="") as f:
                reader = csv.reader(f, delimiter=self.delimiter,
                                    quotechar=self.quote)
                for i, row in enumerate(reader):
                    if i < self.skip_num_lines or not row:
                        continue
                    yield list(row)


class SequenceRecordReader(RecordReader):
    """SPI for time-series readers: next_sequence() yields a list of
    records (reference: SequenceRecordReader)."""

    def next_sequence(self) -> SequenceRecord:
        raise NotImplementedError

    def sequences(self) -> Iterator[SequenceRecord]:
        self.reset()
        while self.has_next():
            yield self.next_sequence()


class CSVSequenceRecordReader(SequenceRecordReader):
    """One CSV file per sequence (reference: CSVSequenceRecordReader)."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        self.skip_num_lines = skip_num_lines
        self.delimiter = delimiter

    def _make_iter(self) -> Iterator[SequenceRecord]:
        for path in self._split.locations():
            with open(path, "r", encoding="utf-8", newline="") as f:
                reader = csv.reader(f, delimiter=self.delimiter)
                seq = [list(row) for i, row in enumerate(reader)
                       if i >= self.skip_num_lines and row]
            if seq:
                yield seq

    def next_sequence(self) -> SequenceRecord:
        return self.next()


class CollectionRecordReader(RecordReader):
    """In-memory records (reference: CollectionRecordReader) — used by
    TransformProcess results and tests."""

    def __init__(self, records: Sequence[Record]):
        self._records = [list(r) for r in records]
        self.reset()

    def initialize(self, split: Optional[InputSplit] = None) -> None:
        self.reset()

    def _make_iter(self) -> Iterator[Record]:
        return iter([list(r) for r in self._records])
