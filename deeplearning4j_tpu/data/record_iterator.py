"""RecordReader → DataSet iterators + async prefetch.

Reference: deeplearning4j-datavec-iterators
``RecordReaderDataSetIterator`` / ``SequenceRecordReaderDataSetIterator``
(label-column extraction, one-hot for classification, regression mode,
alignment + padding masks) and deeplearning4j-utility-iterators
``AsyncDataSetIterator`` (SURVEY.md §2.1 datasets row, §2.3 DataVec rows;
VERDICT round-1 weak #3 names the missing prefetch as the LeNet TPU
bottleneck).

``AsyncDataSetIterator`` here overlaps the three host stages with device
compute: a background thread reads + vectorizes the next batches while the
accelerator trains on the current one, optionally staging arrays onto the
device (``jax.device_put``) ahead of use so ``fit`` never waits on H2D.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Sequence

import numpy as np

from .dataset import DataSet
from .iterators import DataSetIterator
from .records import RecordReader, SequenceRecordReader


class RecordReaderDataSetIterator(DataSetIterator):
    """Assemble flat records into (features, labels) DataSet batches.

    Classification: ``label_index`` column → one-hot over ``num_classes``.
    Regression: ``regression=True`` keeps label columns as float values
    (``label_index``..``label_index_to`` inclusive, reference semantics).
    Image records (cell 0 is an ndarray) batch by stacking.
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.label_index_to = label_index_to if label_index_to is not None \
            else label_index

    def batch(self) -> int:
        return self.batch_size

    def reset(self) -> None:
        self.reader.reset()

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        batch: List[list] = []
        for rec in self.reader:
            batch.append(rec)
            if len(batch) == self.batch_size:
                yield self._apply_pre(self._assemble(batch))
                batch = []
        if batch:
            yield self._apply_pre(self._assemble(batch))

    def _assemble(self, batch: List[list]) -> DataSet:
        first = batch[0]
        if isinstance(first[0], np.ndarray) and first[0].ndim >= 2:
            # image records: [chw_array, label]
            x = np.stack([r[0] for r in batch]).astype(np.float32)
            y_idx = np.asarray([int(r[1]) for r in batch])
            n = self.num_classes or \
                (self.reader.num_labels()
                 if hasattr(self.reader, "num_labels") else 0)
            if not n:
                # per-batch max(label)+1 would give inconsistent one-hot
                # widths across batches
                raise ValueError("classification needs num_classes (or a "
                                 "reader exposing num_labels())")
            y = np.eye(n, dtype=np.float32)[y_idx]
            return DataSet(x, y)
        width = len(first)
        li = self.label_index % width if self.label_index is not None else None
        if li is None:
            x = np.asarray(batch, dtype=np.float32)
            return DataSet(x, None)
        lt = self.label_index_to % width
        feat_cols = [i for i in range(width) if not li <= i <= lt]
        x = np.asarray([[float(r[i]) for i in feat_cols] for r in batch],
                       dtype=np.float32)
        if self.regression:
            y = np.asarray([[float(r[i]) for i in range(li, lt + 1)]
                            for r in batch], dtype=np.float32)
        else:
            if not self.num_classes:
                raise ValueError("classification needs num_classes")
            y_idx = np.asarray([int(float(r[li])) for r in batch])
            if (y_idx < 0).any() or (y_idx >= self.num_classes).any():
                raise ValueError(
                    f"label index out of range [0, {self.num_classes}): "
                    f"{sorted(set(y_idx.tolist()))[:10]}")
            y = np.eye(self.num_classes, dtype=np.float32)[y_idx]
        return DataSet(x, y)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequence records → [N, T, F] batches with per-timestep label masks,
    padded to the longest sequence in the batch (reference:
    SequenceRecordReaderDataSetIterator, ALIGN_END label alignment with
    padding masks; SURVEY §5.7 masking row).

    DOCUMENTED LAYOUT DIVERGENCE: the reference emits [batch, features,
    time]; this framework's recurrent layers are jax-natural
    [batch, time, features] throughout (see nn/conf/layers LSTM), so the
    iterator emits that — labels [N, T, C] one-hot for classification,
    [N, T] masks marking real timesteps.
    """

    def __init__(self, reader: SequenceRecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression

    def batch(self) -> int:
        return self.batch_size

    def reset(self) -> None:
        self.reader.reset()

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        batch: List[list] = []
        for seq in self.reader.sequences():
            batch.append(seq)
            if len(batch) == self.batch_size:
                yield self._apply_pre(self._assemble(batch))
                batch = []
        if batch:
            yield self._apply_pre(self._assemble(batch))

    def _assemble(self, seqs: List[list]) -> DataSet:
        width = len(seqs[0][0])
        li = self.label_index % width
        feat_cols = [i for i in range(width) if i != li]
        T = max(len(s) for s in seqs)
        N, F = len(seqs), len(feat_cols)
        x = np.zeros((N, T, F), np.float32)
        mask = np.zeros((N, T), np.float32)
        if self.regression:
            y = np.zeros((N, T, 1), np.float32)
        else:
            if not self.num_classes:
                raise ValueError("classification needs num_classes")
            y = np.zeros((N, T, self.num_classes), np.float32)
        for n, seq in enumerate(seqs):
            for t, rec in enumerate(seq):
                for f, col in enumerate(feat_cols):
                    x[n, t, f] = float(rec[col])
                mask[n, t] = 1.0
                if self.regression:
                    y[n, t, 0] = float(rec[li])
                else:
                    y[n, t, int(float(rec[li]))] = 1.0
        return DataSet(x, y, features_mask=mask, labels_mask=mask)


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch wrapper (reference:
    AsyncDataSetIterator with its blocking queue of ``queue_size``).

    ``device_prefetch=True`` additionally stages each batch's arrays onto
    the default device from the worker thread, overlapping H2D transfer
    with the current training step — the role the reference's workspace
    pre-population plays on CUDA.
    """

    _END = object()

    def __init__(self, base: DataSetIterator, queue_size: int = 4,
                 device_prefetch: bool = True,
                 feature_transform=None):
        self.base = base
        self.queue_size = queue_size
        self.device_prefetch = device_prefetch
        # Optional jax fn applied to the FEATURES on device after the put
        # (e.g. ``lambda x: x.astype(jnp.float32) / 255`` for uint8 image
        # containers: shipping the 4×-smaller raw bytes and converting on
        # device moves the cast off the host decode thread — measured 5×
        # on the 1-core bench host, BASELINE.md round-4 pre-decoded row)
        if feature_transform is not None and not device_prefetch:
            raise ValueError("feature_transform is applied on device and "
                             "requires device_prefetch=True")
        if feature_transform is None:
            self._feature_transform = None
        else:
            from ..common import xprof

            self._feature_transform = xprof.register_jit(
                "data/feature_transform",
                __import__("jax").jit(feature_transform))

    def batch(self) -> int:
        return self.base.batch()

    def reset(self) -> None:
        self.base.reset()

    def _stage(self, ds) -> DataSet:
        import jax

        from ..ndarray.ndarray import NDArray

        if isinstance(ds, tuple):
            # raw numpy (x, y) from a jax-free worker (the binary-record
            # fast path) — build the DataSet here on the consumer thread.
            # device_prefetch=False matches the non-tuple branch: no
            # explicit committed device_put; the NDArray wrap still runs
            # jnp.asarray (a default-device transfer on TPU), exactly as
            # it would when the caller constructs a DataSet itself
            x, y = ds
            if self.device_prefetch:
                xd = NDArray(jax.device_put(x))
                if self._feature_transform is not None:
                    xd = NDArray(self._feature_transform(xd.value))
                yd = NDArray(jax.device_put(y)) if y is not None else None
            else:
                xd = NDArray(x)
                yd = NDArray(y) if y is not None else None
            out = DataSet.__new__(DataSet)
            out.features = xd
            out.labels = yd
            out.features_mask = None
            out.labels_mask = None
            return out
        if not self.device_prefetch:
            return ds

        def put(nd):
            if nd is None:
                return None
            return NDArray(jax.device_put(nd.value))

        out = DataSet.__new__(DataSet)
        out.features = put(ds.features)
        if self._feature_transform is not None and out.features is not None:
            out.features = NDArray(
                self._feature_transform(out.features.value))
        out.labels = put(ds.labels)
        out.features_mask = put(ds.features_mask)
        out.labels_mask = put(ds.labels_mask)
        return out

    def __iter__(self) -> Iterator[DataSet]:
        from ..common.background import prefetch_iter

        # Device staging runs on the CONSUMER thread. Round-4 measurement:
        # device_put from a non-main thread through the axon relay
        # serializes cross-thread array use catastrophically (11.7 s/step
        # vs 84 ms for an identical ResNet batch), and consumer-side
        # device_put is itself async, so nothing is lost on direct
        # backends. CAVEAT: the worker thread is fully jax-free only for
        # bases yielding raw (x, y) numpy tuples (binary-record
        # ``raw_numpy=True``); bases that construct DataSet inside their
        # own __next__ still touch jax there, because NDArray eagerly
        # converts (ndarray.py) — prefer the tuple protocol for new bases.
        for ds in prefetch_iter(iter(self.base), maxsize=self.queue_size):
            yield self._stage(ds)
