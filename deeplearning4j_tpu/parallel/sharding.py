"""Tensor-parallel sharding rules.

The reference has NO tensor parallelism (SURVEY.md §2.4 marks it absent);
on TPU it is a compiler annotation, so the rebuild provides it natively:
given a model's parameter pytree and a mesh with a ``model`` axis, produce a
matching tree of ``NamedSharding`` that splits the large matmul weights —
dense W=[in,out] on the output dim, conv W=[O,I,kh,kw] on the output-channel
dim — and lets GSPMD insert the ICI collectives (scaling-book recipe: pick a
mesh, annotate, let XLA do the rest).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def tp_param_specs(params: Any, mesh: Mesh, axis: str = "model"):
    """PartitionSpec tree for tensor-parallel params; replicates anything that
    doesn't divide evenly (correct, just not sharded)."""
    size = mesh.shape[axis]

    def spec_for(leaf):
        shape = leaf.shape
        if len(shape) == 2 and shape[1] % size == 0 and shape[1] >= size:
            return P(None, axis)                    # dense [in, out]
        if len(shape) == 4 and shape[0] % size == 0 and shape[0] >= size:
            return P(axis, None, None, None)        # conv OIHW [out, ...]
        if len(shape) == 1 and shape[0] % size == 0 and shape[0] >= 2 * size:
            return P(axis)                          # bias / bn per-channel
        return P()

    return jax.tree.map(spec_for, params)


def tp_shardings(params: Any, mesh: Mesh, axis: str = "model"):
    specs = tp_param_specs(params, mesh, axis)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda s: isinstance(s, P))


def apply_tp(params: Any, mesh: Mesh, axis: str = "model"):
    """Materialize params with tensor-parallel placement."""
    sh = tp_shardings(params, mesh, axis)
    return jax.tree.map(jax.device_put, params, sh)
