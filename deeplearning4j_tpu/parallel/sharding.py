"""Tensor-parallel sharding rules + the ZeRO-1 flat param-bucketing scheme.

The reference has NO tensor parallelism (SURVEY.md §2.4 marks it absent);
on TPU it is a compiler annotation, so the rebuild provides it natively:
given a model's parameter pytree and a mesh with a ``model`` axis, produce a
matching tree of ``NamedSharding`` that splits the large matmul weights —
dense W=[in,out] on the output dim, conv W=[O,I,kh,kw] on the output-channel
dim — and lets GSPMD insert the ICI collectives (scaling-book recipe: pick a
mesh, annotate, let XLA do the rest).

The second half of this module is the flat layout behind cross-replica
weight-update sharding (ZeRO-1; arXiv:2004.13336): a parameter pytree is
raveled into one 1-D buffer per dtype ("bucket"), zero-padded to a multiple
of the data-axis size, and split EVENLY over the replicas — so uneven layer
sizes still balance (replica i owns elements [i*s, (i+1)*s) of every
bucket, not layer i). The layout is a pure permutation: it depends only on
the pytree structure and leaf shapes, NOT on the replica count (only the
zero padding does), which is what makes updater state saved from an N-way
run restorable into an M-way run exactly.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FLAT_PREFIX = "flat::"   # bucket keys ("flat::float32") mark the flat layout


def tp_param_specs(params: Any, mesh: Mesh, axis: str = "model"):
    """PartitionSpec tree for tensor-parallel params; replicates anything that
    doesn't divide evenly (correct, just not sharded)."""
    size = mesh.shape[axis]

    def spec_for(leaf):
        shape = leaf.shape
        if len(shape) == 2 and shape[1] % size == 0 and shape[1] >= size:
            return P(None, axis)                    # dense [in, out]
        if len(shape) == 4 and shape[0] % size == 0 and shape[0] >= size:
            return P(axis, None, None, None)        # conv OIHW [out, ...]
        if len(shape) == 1 and shape[0] % size == 0 and shape[0] >= 2 * size:
            return P(axis)                          # bias / bn per-channel
        return P()

    return jax.tree.map(spec_for, params)


def tp_shardings(params: Any, mesh: Mesh, axis: str = "model"):
    specs = tp_param_specs(params, mesh, axis)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda s: isinstance(s, P))


def apply_tp(params: Any, mesh: Mesh, axis: str = "model"):
    """Materialize params with tensor-parallel placement."""
    sh = tp_shardings(params, mesh, axis)
    return jax.tree.map(jax.device_put, params, sh)


# --------------------------------------------------------------------------
# ZeRO-1 flat param bucketing
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class _Bucket:
    key: str                 # "flat::<dtype>"
    dtype: Any               # numpy dtype
    leaf_idx: Tuple[int, ...]    # positions in the tree.flatten leaf order
    sizes: Tuple[int, ...]       # element count per leaf
    shapes: Tuple[Tuple[int, ...], ...]
    total: int               # true (unpadded) element count
    padded: int              # total rounded up to a multiple of n_shards
    shard: int               # padded // n_shards


def _leaf_layers(params) -> List[int]:
    """Layer/group index per leaf in ``jax.tree.flatten`` order — matches
    ``optimize.telemetry.groups`` (list order for MLN param lists, sorted
    node name for graph dicts), so flat-shard telemetry lands in the same
    per-layer slots as the dense path's."""
    from ..optimize.telemetry import groups

    out: List[int] = []
    for i, g in enumerate(groups(params)):
        out.extend([i] * len(jax.tree.leaves(g)))
    return out


class Zero1Plan:
    """The deterministic flat layout for one (params, n_shards) pair.

    ``flatten``/``unflatten`` are pure permutations (ravel + concat /
    split + reshape — no arithmetic), so running an ELEMENTWISE updater on
    the flat buffers is bit-identical to running it leaf-by-leaf; the
    in-graph versions trace into the compiled step, and ``xp=np`` gives
    the host-side versions checkpointing uses."""

    def __init__(self, params, n_shards: int):
        from ..optimize.telemetry import groups

        leaves, treedef = jax.tree.flatten(params)
        self.treedef = treedef
        self.n_shards = int(n_shards)
        self.n_leaves = len(leaves)
        self.n_layers = len(groups(params))
        layer_of = _leaf_layers(params)
        by_dtype: Dict[str, List[int]] = {}
        for i, leaf in enumerate(leaves):
            by_dtype.setdefault(str(np.dtype(leaf.dtype)), []).append(i)
        self.buckets: List[_Bucket] = []
        self._bounds: Dict[str, np.ndarray] = {}
        self._interval_layers: Dict[str, np.ndarray] = {}
        for dt, idxs in sorted(by_dtype.items()):
            sizes = tuple(int(np.prod(leaves[i].shape)) for i in idxs)
            shapes = tuple(tuple(leaves[i].shape) for i in idxs)
            total = sum(sizes)
            padded = -(-total // self.n_shards) * self.n_shards
            b = _Bucket(key=FLAT_PREFIX + dt, dtype=np.dtype(dt),
                        leaf_idx=tuple(idxs), sizes=sizes, shapes=shapes,
                        total=total, padded=padded,
                        shard=padded // self.n_shards)
            self.buckets.append(b)
            # per-leaf flat-position boundaries (n_leaves+1 entries — the
            # TINY tables telemetry derives segment ids from in-graph;
            # the pad tail [total, padded) maps to the overflow interval,
            # layer id ``n_layers``, that segment-summed telemetry drops)
            self._bounds[b.key] = np.concatenate(
                [[0], np.cumsum(sizes)]).astype(np.int32)
            self._interval_layers[b.key] = np.asarray(
                [layer_of[i] for i in idxs] + [self.n_layers], np.int32)

    # -- layout transforms (xp=jnp traces into the step; xp=np is host) --
    def flatten(self, tree, xp=jnp) -> Dict[str, Any]:
        leaves = jax.tree.leaves(tree)
        if len(leaves) != self.n_leaves:
            raise ValueError(f"tree has {len(leaves)} leaves, plan expects "
                             f"{self.n_leaves}")
        out = {}
        for b in self.buckets:
            parts = [xp.ravel(leaves[i]) for i in b.leaf_idx]
            if b.padded > b.total:
                # pad in the LEAVES' dtype, not the bucket key's: a
                # low-precision updater-state tree (state_dtype=bfloat16)
                # flattens through its params' f32-keyed buckets, and an
                # f32 zero tail would silently promote the whole bucket
                parts.append(xp.zeros((b.padded - b.total,),
                                      parts[0].dtype))
            out[b.key] = xp.concatenate(parts) if len(parts) > 1 else parts[0]
        return out

    def unflatten(self, flats: Dict[str, Any], xp=jnp):
        leaves: List[Any] = [None] * self.n_leaves
        for b in self.buckets:
            flat = flats[b.key]
            pos = 0
            for i, sz, shape in zip(b.leaf_idx, b.sizes, b.shapes):
                leaves[i] = xp.reshape(flat[pos:pos + sz], shape)
                pos += sz
        return jax.tree.unflatten(self.treedef, leaves)

    def unflatten_diff(self, flats: Dict[str, Any]):
        """:meth:`unflatten` with its exact adjoint spelled out. The
        autodiff transpose of slice-and-reshape lowers as one
        full-bucket-size ``pad`` + ``add_any`` PER LEAF, so a flat-backward
        step through plain :meth:`unflatten` materializes O(n_leaves)
        bucket-sized temporaries. But unflatten is a pure permutation
        whose adjoint IS :meth:`flatten` — one concatenate per bucket —
        and the pad tail's cotangent is identically zero, which flatten's
        zero tail reproduces bitwise. Use this form wherever a step
        differentiates through the flat layout."""
        @jax.custom_vjp
        def _unflat(f):
            return self.unflatten(f)

        def _fwd(f):
            return self.unflatten(f), None

        def _bwd(_, ct):
            return (self.flatten(ct),)

        _unflat.defvjp(_fwd, _bwd)
        return _unflat(flats)

    def shard_slice(self, flats: Dict[str, Any], idx) -> Dict[str, Any]:
        """Replica ``idx``'s even slice of every bucket (in-graph)."""
        return {b.key: jax.lax.dynamic_slice(flats[b.key],
                                             (idx * b.shard,), (b.shard,))
                for b in self.buckets}

    def unpadded_views(self, flats: Dict[str, Any]) -> Dict[str, Any]:
        """Each bucket's live prefix (``[:total]``, a static slice) with
        the worker-count pad tail dropped. This is the integrity-fold
        contract (:func:`common.integrity.fingerprint_flats`): the pad
        tail's length changes with the replica count, so any digest that
        folded it in would break fingerprint stability across elastic
        resizes — only the live prefix is ever hashed."""
        return {b.key: flats[b.key][:b.total] for b in self.buckets}

    def shard_segment_ids(self, key: str, idx, shard: int):
        """Telemetry layer id for each flat position of replica ``idx``'s
        slice of bucket ``key``, derived IN-GRAPH from the bucket's tiny
        leaf-boundary tables — NOT a [padded] int32 constant baked into
        the executable (that would cost 4 bytes per model parameter per
        compiled step, against a feature whose point is cutting memory).
        Ascending (leaves follow layer order; pad bin ``n_layers`` last),
        so ``segment_sum(..., indices_are_sorted=True)`` stays valid."""
        pos = idx * shard + jnp.arange(shard, dtype=jnp.int32)
        k = jnp.searchsorted(jnp.asarray(self._bounds[key]), pos,
                             side="right") - 1
        return jnp.asarray(self._interval_layers[key])[k]

    def bucket_bytes(self) -> int:
        return sum(b.padded * b.dtype.itemsize for b in self.buckets)

    # -- updater-state layout conversion --------------------------------
    def flatten_state(self, state, xp=np):
        """Dense (params-mirroring) updater-state tree → flat buckets.
        Only subtrees shaped like the params flatten; anything else (none
        of the built-in updaters produce one) is passed through."""
        if not isinstance(state, dict):
            return state
        out = {}
        for k, v in state.items():
            if jax.tree.structure(v) == self.treedef:
                out[k] = self.flatten(v, xp=xp)
            else:
                out[k] = v
        return out

    def unflatten_state_inplan(self, state, xp=jnp):
        """Flat updater state already in THIS plan's exact padded layout →
        dense tree. Unlike :meth:`unflatten_state` it never touches numpy
        (no repad/validation), so it is safe to TRACE into a compiled
        step — the single-device fused-update path densifies the state it
        just updated with this."""
        out = {}
        for k, v in state.items():
            if isinstance(v, dict) and v and all(
                    str(kk).startswith(FLAT_PREFIX) for kk in v):
                out[k] = self.unflatten(
                    {b.key: v[b.key][:b.total] for b in self.buckets},
                    xp=xp)
            else:
                out[k] = v
        return out

    def unflatten_state(self, state, xp=np):
        """Flat-bucketed updater state → dense tree (strips padding).
        Accepts buckets padded for a DIFFERENT shard count: the layout is
        replica-count-independent, so only the zero tail differs."""
        if not is_flat_state(state):
            return state
        out = {}
        for k, v in state.items():
            if isinstance(v, dict) and v and all(
                    str(kk).startswith(FLAT_PREFIX) for kk in v):
                out[k] = self.unflatten(
                    {b.key: self._repad(v[b.key], b, strip_only=True)
                     for b in self.buckets}, xp=xp)
            else:
                out[k] = v
        return out

    def _repad(self, arr, b: _Bucket, strip_only: bool = False):
        """Normalize one bucket array saved under any shard count to this
        plan's padding (exact: real elements are untouched, only the zero
        tail is cut/grown)."""
        arr = np.asarray(arr)
        if arr.size < b.total:
            raise ValueError(
                f"flat updater bucket {b.key} has {arr.size} elements; "
                f"params imply {b.total} — checkpoint does not match the "
                "model")
        arr = arr[:b.total]
        if strip_only:
            return arr
        if b.padded > b.total:
            arr = np.concatenate(
                [arr, np.zeros((b.padded - b.total,), arr.dtype)])
        return arr

    def reshard_state(self, state):
        """Flat state (any previous shard count) → flat host state padded
        for THIS plan. Dense trees are flattened first."""
        if is_flat_state(state):
            return {k: ({b.key: self._repad(v[b.key], b)
                         for b in self.buckets}
                        if isinstance(v, dict) and v and all(
                            str(kk).startswith(FLAT_PREFIX) for kk in v)
                        else v)
                    for k, v in state.items()}
        return self.flatten_state(
            jax.tree.map(np.asarray, state), xp=np)


def is_flat_state(state) -> bool:
    """True when ``state`` is in the ZeRO-1 flat-bucket layout (top-level
    values are dicts keyed ``flat::<dtype>``)."""
    if not isinstance(state, dict) or not state:
        return False
    return any(isinstance(v, dict) and v
               and all(str(k).startswith(FLAT_PREFIX) for k in v)
               for v in state.values())


def unflatten_updater_state(state, params, xp=np):
    """Host-side convenience: flat updater state → dense tree mirroring
    ``params`` (identity for dense state). Checkpoint writers call this so
    the on-disk updater layout is ALWAYS the dense one — a ZeRO-1 run's
    checkpoint restores into a single-device fit, a dense run, or a
    ZeRO-1 run with a different worker count without format negotiation."""
    if not is_flat_state(state):
        return state
    return Zero1Plan(params, 1).unflatten_state(state, xp=xp)
