from .mesh import (make_mesh, make_pipeline_mesh, replicated, data_sharded,
                   shard_batch, elastic_pool, serving_devices)
from .accumulator import (GradientsAccumulator, DenseAllReduceAccumulator,
                          EncodedGradientsAccumulator,
                          ReduceScatterAccumulator, ThresholdAlgorithm,
                          AdaptiveThresholdAlgorithm, FixedThresholdAlgorithm,
                          TargetSparsityThresholdAlgorithm)
from .wrapper import ParallelWrapper
from .fleet import (FleetTrainer, FleetEarlyStop, FleetStatsSink)
from .sharding import (tp_param_specs, tp_shardings, apply_tp, Zero1Plan,
                       unflatten_updater_state)
from .inference import ParallelInference
from .serving import (ServingEngine, BucketLadder, OversizeRequest,
                      Overloaded, SLOClass, AdmissionController,
                      BrownoutController, PublishHandle, serving_health)
from .autoscale import Autoscaler, AutoscalePolicy
from .distributed import (SharedTrainingMaster, TrainingSupervisor,
                          SupervisedFitResult, RestartBudgetExceeded,
                          RestartStorm, Preempted, HangDetected,
                          AbandonedAttempt, ElasticResizeRequested,
                          classify_failure,
                          supervise_processes, initialize, shutdown)
from .cluster import (ClusterRuntime, ClusterInitError, BarrierTimeout,
                      GroupCommitError, read_heartbeats, stale_ranks,
                      merge_rank_blackboxes,
                      cpu_multiprocess_collectives_available)
from .ring_attention import ring_attention, ring_self_attention
from .sharded_embeddings import ShardedEmbedding
from .pipeline import (HeterogeneousPipeline, PipelineParallel,
                       PipelineTrainer, pipeline_apply, pipeline_from_mln,
                       schedule_meta, stack_stage_params, stage_partition)
