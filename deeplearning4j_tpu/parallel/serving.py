"""Production inference serving: continuous batching over AOT shape buckets.

The millions-of-users tier (ROADMAP item 2). :class:`ParallelInference`
gives this stack a replica pool with health probes, retirement,
resurrection and per-request deadlines — but it dispatches each coalesced
batch AT ITS OWN SHAPE, so concurrent traffic at varying batch/sequence
sizes retraces and serializes behind jit compiles. This module closes the
gap with the compile-once-run-many recipe the whole-graph-compilation
literature argues for (TVM, arXiv:1802.04799; nGraph, arXiv:1801.08058):

- **Shape buckets** (:class:`BucketLadder`): a configurable batch-size
  ladder (and optional sequence-length ladder). Every request is padded UP
  to the smallest admitting bucket, so the set of shapes the model ever
  sees is small, fixed, and known at startup.
- **AOT executables per bucket**: each bucket's inference function is
  ``jax.jit(...).lower(...).compile()``-d at pool startup
  (:meth:`ServingEngine.warmup`), so steady-state serving NEVER traces —
  the ``serving/traces_after_warmup`` counter must stay 0 and the
  serving-smoke bench hard-fails when it doesn't. Warmup cost is paid
  once, up front, per bucket (the ``serving/warmup`` profiler section
  ledgers it).
- **Pad-and-mask reuse**: bucket padding is :func:`data.pipeline.pad_rows`
  — the SAME wrap-real-rows rule the training pipeline uses, so padding
  rows are provably inert: a pad slot is an exact copy of a real row,
  per-example inference computes for it exactly what it computed for the
  real row, and the scatter slices it off. ``tests/test_serving.py``
  proves the bucketed output BITWISE-equal to an unpadded direct
  ``model.output``. (BatchNorm is no caveat here: inference-mode BN uses
  running stats, which are per-example.)
- **Continuous batching**: replica workers drain the shared request queue
  into the largest fillable bucket under a ``max_wait_ms`` deadline — a
  request that would overflow the largest bucket (or mismatch the batch's
  non-batch shape) is stashed for the next batch, never dropped.
- **bf16 inference params** (``Builder.bf16(True)``): one cast at startup
  (and on :meth:`refresh_params`), halving weight bytes and engaging the
  bf16 matmul units; inputs/outputs stay float32 at the API boundary.
  Numerics change (~1e-2 relative) — the bitwise guarantee above is the
  fp32 path's.
- **Replica-pool integration**: ServingEngine IS a ParallelInference — it
  inherits retirement, health-probe resurrection, deadlines and shutdown
  draining. Retirement is additionally TRANSPARENT to in-flight requests:
  a dying replica's batch is requeued (bounded by ``max_requeues``, true
  queue-entry timestamps preserved) instead of failed, so the
  kill-a-replica-mid-load drill completes with zero failed requests while
  the PR-4 resurrection machinery refills the pool. When the LAST replica
  dies, queued requests still fail fast (the pool's bounded-latency
  contract outranks transparency).

**Admission rule for oversize requests** (documented contract): a request
with more rows than the largest batch bucket is, under
``oversize="split"`` (the default), split into largest-bucket-sized
chunks served independently and re-concatenated in order — its latency is
then bounded by ``ceil(n/max_batch)`` bucket dispatches; under
``oversize="reject"`` it raises :class:`OversizeRequest` synchronously at
submission, before anything is queued. A sequence length over the ladder
ALWAYS rejects — time steps cannot be split across executables by a
serving layer that does not know the model's temporal semantics.

HTTP serving lives on the existing UI server: ``UIServer.attach_serving``
exposes ``POST /api/infer`` next to ``/api/health`` (whose ``serving``
section is :func:`serving_health`). Load-test with
``python bench.py --config serving-smoke`` — an open-loop Poisson
generator with hard-fail p50/p99/QPS SLO gates and a
kill-a-replica-mid-load drill.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..common import faultinject, flightrec
from ..common.profiler import OpProfiler
from ..data.pipeline import pad_rows
from ..ndarray.ndarray import NDArray
from ..ndarray.rng import get_random
from .inference import ParallelInference, _Request, logger
from .mesh import serving_devices

# live engines, for the /api/health serving census (weak: dropped → gone)
_ENGINES: "weakref.WeakSet" = weakref.WeakSet()

_MISS = object()     # _exec sentinel: None is a real (generic-model) entry


class OversizeRequest(ValueError):
    """A request the bucket ladder refuses to admit: more rows than the
    largest batch bucket under ``oversize="reject"``, or a sequence longer
    than the largest sequence bucket (never splittable). Raised
    synchronously at submission — nothing is queued."""


class BucketLadder:
    """The bucket policy: sorted batch-size ladder, optional sequence-
    length ladder, and the oversize admission rule (see module docstring).

    ``bucket_batch(n)`` / ``bucket_seq(t)`` return the smallest admitting
    rung; ``admit(n)`` returns the chunk row-counts a request is served
    as (``[n]`` for an in-ladder request)."""

    def __init__(self, batch_sizes: Sequence[int] = (1, 2, 4, 8, 16, 32),
                 seq_lens: Optional[Sequence[int]] = None,
                 oversize: str = "split"):
        sizes = sorted({int(b) for b in batch_sizes})
        if not sizes or sizes[0] < 1:
            raise ValueError(f"batch ladder needs positive sizes, got "
                             f"{batch_sizes!r}")
        self.batch_sizes: Tuple[int, ...] = tuple(sizes)
        self.seq_lens: Optional[Tuple[int, ...]] = None
        if seq_lens is not None:
            sl = sorted({int(t) for t in seq_lens})
            if not sl or sl[0] < 1:
                raise ValueError(f"sequence ladder needs positive lengths, "
                                 f"got {seq_lens!r}")
            self.seq_lens = tuple(sl)
        if oversize not in ("split", "reject"):
            raise ValueError(f"oversize must be 'split' or 'reject', got "
                             f"{oversize!r}")
        self.oversize = oversize

    @property
    def max_batch(self) -> int:
        return self.batch_sizes[-1]

    def bucket_batch(self, n: int) -> Optional[int]:
        """Smallest batch bucket >= n, or None when n exceeds the ladder."""
        for b in self.batch_sizes:
            if b >= n:
                return b
        return None

    def bucket_seq(self, t: int) -> int:
        """Smallest sequence bucket >= t. Oversize sequences ALWAYS
        reject (module docstring: time steps cannot be split)."""
        assert self.seq_lens is not None
        for s in self.seq_lens:
            if s >= t:
                return s
        raise OversizeRequest(
            f"sequence length {t} exceeds the largest sequence bucket "
            f"{self.seq_lens[-1]}; lengthen the ladder or truncate "
            f"upstream")

    def admit(self, n: int) -> List[int]:
        """The admission rule. Raises :class:`OversizeRequest` under
        ``oversize='reject'``; splits into max-bucket chunks (+ remainder)
        under ``'split'``."""
        if n < 1:
            raise ValueError(f"a request needs at least one row, got {n}")
        if n <= self.max_batch:
            return [n]
        if self.oversize == "reject":
            raise OversizeRequest(
                f"request of {n} rows exceeds the largest batch bucket "
                f"{self.max_batch} (oversize='reject'); split it client-"
                f"side or configure oversize='split'")
        chunks = [self.max_batch] * (n // self.max_batch)
        if n % self.max_batch:
            chunks.append(n % self.max_batch)
        return chunks

    def shapes(self, feat: Tuple[int, ...]) -> List[Tuple[int, ...]]:
        """Every input shape the ladder admits — the warmup compile set.
        ``feat`` is the per-request feature shape (no batch dim); with a
        sequence ladder its leading entry is the time axis and is replaced
        by each sequence rung."""
        if self.seq_lens is None:
            return [(b,) + tuple(feat) for b in self.batch_sizes]
        if not feat:
            raise ValueError("a sequence ladder needs a feature shape "
                             "with a leading time axis")
        return [(b, t) + tuple(feat[1:])
                for b in self.batch_sizes for t in self.seq_lens]


# THE fp32-boundary cast, shared with the training side's low-precision
# updater state — learning/precision.py owns the dtype-boundary rules
# (one doc, one helper; this module used to carry its own copy).
from ..learning.precision import cast_floating as _cast_floating


class ServingEngine(ParallelInference):
    """The serving tier: a ParallelInference replica pool whose workers
    drain the shared queue into padded shape buckets served by
    AOT-compiled executables. See the module docstring for the policy
    contract; see :class:`Builder` for knobs."""

    class Builder(ParallelInference.Builder):
        def __init__(self, model):
            super().__init__(model)
            self._max_wait_ms = 2.0      # serving default: tight window
            self._ladder: Optional[BucketLadder] = None
            self._input_shape: Optional[Tuple[int, ...]] = None
            self._in_dtype = np.float32
            self._bf16 = False
            self._warmup = True
            self._max_requeues = 2
            self._pin_devices = False

        def inference_mode(self, mode: str) -> "ServingEngine.Builder":
            """Serving IS continuous batching — the drain loop, stash and
            bucket fill only exist in batched mode, so anything else is
            refused loudly instead of silently coerced."""
            if mode.lower() != "batched":
                raise ValueError(
                    f"ServingEngine only serves in 'batched' mode (its "
                    f"continuous-batching drain loop IS the engine), got "
                    f"{mode!r}; use a plain ParallelInference for "
                    f"sequential dispatch")
            return self

        inferenceMode = inference_mode

        def buckets(self, batch_sizes: Sequence[int],
                    seq_lens: Optional[Sequence[int]] = None,
                    oversize: str = "split") -> "ServingEngine.Builder":
            """The bucket ladder (see :class:`BucketLadder`)."""
            self._ladder = BucketLadder(batch_sizes, seq_lens, oversize)
            return self

        def ladder(self, ladder: BucketLadder) -> "ServingEngine.Builder":
            self._ladder = ladder
            return self

        def input_shape(self, shape: Sequence[int],
                        dtype=np.float32) -> "ServingEngine.Builder":
            """Per-request feature shape (WITHOUT the batch dim) — what
            warmup compiles against. With a sequence ladder the leading
            entry is the time axis (any value; the ladder replaces it)."""
            self._input_shape = tuple(int(s) for s in shape)
            self._in_dtype = np.dtype(dtype)
            return self

        def bf16(self, enabled: bool = True) -> "ServingEngine.Builder":
            """Serve with bfloat16 params (one startup cast; float32 at
            the API boundary). Numerics caveat in the module docstring."""
            self._bf16 = enabled
            return self

        def warmup(self, enabled: bool) -> "ServingEngine.Builder":
            """Compile the bucket set at build() (default). Disabling
            defers each bucket's compile to its first hit — only for
            tests; production startup should eat the cost up front."""
            self._warmup = enabled
            return self

        def max_requeues(self, n: int) -> "ServingEngine.Builder":
            """How many replica deaths one request may ride through
            (requeue budget) before it fails like the replica did."""
            self._max_requeues = max(0, int(n))
            return self

        def pin_devices(self, enabled: bool = True
                        ) -> "ServingEngine.Builder":
            """Pin replica workers round-robin across devices
            (:func:`mesh.serving_devices`): each replica gets its own
            device-resident param copy and per-device executables, so
            replicas run on different chips instead of contending for one
            XLA stream. Costs one param copy + one compile set per
            distinct device."""
            self._pin_devices = enabled
            return self

        def build(self) -> "ServingEngine":
            if self._input_shape is None:
                raise ValueError(
                    "ServingEngine needs Builder.input_shape(...): the "
                    "AOT bucket executables are compiled against it at "
                    "warmup, before any request arrives")
            return ServingEngine(
                self._model, self._ladder or BucketLadder(),
                self._input_shape, in_dtype=self._in_dtype,
                bf16=self._bf16, warmup=self._warmup,
                max_requeues=self._max_requeues,
                pin_devices=self._pin_devices,
                batch_limit=self._batch_limit,
                queue_limit=self._queue_limit,
                max_wait_ms=self._max_wait_ms, workers=self._workers,
                request_timeout_ms=self._request_timeout_ms,
                resurrect=self._resurrect,
                resurrect_backoff_ms=self._resurrect_backoff_ms,
                max_resurrections=self._max_resurrections)

    def __init__(self, model, ladder: BucketLadder,
                 input_shape: Tuple[int, ...], in_dtype=np.float32,
                 bf16: bool = False, warmup: bool = True,
                 max_requeues: int = 2, pin_devices: bool = False,
                 **pool_kwargs):
        # subclass state FIRST: super().__init__ starts the drain threads,
        # which call into the overridden _drain immediately
        self.ladder = ladder
        self._feat = tuple(input_shape)
        self._in_dtype = np.dtype(in_dtype)
        self._bf16 = bf16
        self.max_requeues = max_requeues
        self._compute_dtype = jnp.bfloat16 if bf16 else None
        self._devices = (serving_devices(pool_kwargs.get("workers", 1))
                         if pin_devices else [None])
        # worker -> pinned device slot; a retired worker's slot is freed
        # for its replacement (resurrection mints NEW worker ids, so a
        # plain worker_id % ndev would drift every pool generation onto
        # the wrong chips)
        self._dev_of: Dict[int, int] = {}
        self._dev_free: List[int] = []
        self._stash_lock = threading.Lock()
        self._stashq: "collections.deque" = collections.deque()
        self._exec: Dict[Any, Any] = {}     # (shape, dev_idx) -> runner
        self._exec_lock = threading.Lock()
        self._lat_lock = threading.Lock()
        self._latencies: "collections.deque" = collections.deque(maxlen=4096)
        self._batch_seq = 0
        self._admit_seq = 0          # request ordinal (serving/enqueue)
        self._hwm = 0
        self._warm = False
        # THIS engine's trace count (bumped trace-time in _make_infer):
        # the after-warmup alarm must not fire on another engine's warmup
        # bumping the shared trace/serving_infer ledger counter
        self._trace_cell = [0]
        self._traces_seen = 0
        # None = unknown (shape heuristic), True/False once warmup has
        # probed whether outputs carry a per-timestep axis to slice
        self._seq_out_per_timestep: Optional[bool] = None
        self._aot = (hasattr(model, "_forward")
                     and hasattr(model, "_params"))
        self._infer_jit = None
        self._dev_params: Dict[int, Any] = {}
        pool_kwargs.setdefault("mode", "batched")
        super().__init__(model, **pool_kwargs)
        if self._aot:
            self._key = get_random().next_key()
            self._snapshot_params()
        if warmup:
            self.warmup()
        _ENGINES.add(self)

    # --- params / executables -----------------------------------------
    def _snapshot_params(self) -> None:
        params, states = self.model._params, self.model._states
        if self._bf16:
            params = _cast_floating(params, jnp.bfloat16)
            states = _cast_floating(states, jnp.bfloat16)
        for i, dev in enumerate(self._devices):
            if dev is None:
                self._dev_params[i] = (params, states)
            else:
                self._dev_params[i] = jax.device_put((params, states), dev)

    def refresh_params(self) -> None:
        """Re-snapshot the model's (possibly retrained) params into the
        serving copies. CHEAP: the AOT executables take params as
        arguments, so same-shape updates swap in without any recompile
        (bf16 pays its cast again)."""
        if not self._aot:
            return
        self._snapshot_params()

    def _make_infer(self):
        model = self.model
        cdt = self._compute_dtype
        cell = self._trace_cell

        def infer(params, states, x, key):
            # trace-time only: the retrace ledger the serving SLO gates on
            OpProfiler.get().count("trace/serving_infer")
            cell[0] += 1
            if cdt is not None:
                x = x.astype(cdt)
            out, _ = model._forward(params, states, x, False, key, None)
            return out.astype(jnp.float32)

        return infer

    def _compile_bucket(self, shape: Tuple[int, ...],
                        dev_idx: int = 0):
        """AOT-compile (``.lower().compile()``) the bucket executable for
        one input shape (and one pinned device, when pinning). Called for
        the whole ladder at :meth:`warmup`; a lazy hit (warmup disabled)
        compiles here on first use."""
        key = (shape, dev_idx)
        # lock-free hot path: every steady-state dispatch lands here, and
        # it must not queue behind another worker's (lazy) compile
        exe = self._exec.get(key, _MISS)
        if exe is not _MISS:
            return exe
        with self._exec_lock:
            if key in self._exec:
                return self._exec[key]
            if self._aot:
                if self._infer_jit is None:
                    self._infer_jit = jax.jit(self._make_infer())
                params, states = self._dev_params[dev_idx]
                aval = jax.ShapeDtypeStruct(shape, self._in_dtype)
                exe = self._infer_jit.lower(
                    params, states, aval, self._key).compile()
            else:
                # generic model (no jittable forward exposed): no AOT
                # executable — the model.output call right after this in
                # _run_bucket warms its jit cache at the bucket shape.
                # "never traces in steady state" still holds (every
                # later request reuses the shape), but the trace ledger
                # cannot see inside
                exe = None
            self._exec[key] = exe
            OpProfiler.get().count("serving/buckets_compiled")
            return exe

    def warmup(self) -> Dict[str, float]:
        """Compile every ladder bucket (× pinned device) up front — pool
        startup pays the whole trace/compile bill so steady-state serving
        never does. Returns {shape: seconds}; total time is ledgered
        under the ``serving/warmup`` profiler section."""
        prof = OpProfiler.get()
        timings: Dict[str, float] = {}
        seq_out: Dict[int, Optional[int]] = {}
        with prof.time_section("serving/warmup"):
            for shape in self.ladder.shapes(self._feat):
                for i in range(len(self._devices)):
                    t0 = time.perf_counter()
                    self._compile_bucket(shape, i)
                    # execute once too: the first run of a fresh
                    # executable pays allocator/dispatch setup that must
                    # not land on the first real request's latency
                    out = self._run_bucket(np.zeros(shape, self._in_dtype),
                                           i)
                    if i == 0 and self.ladder.seq_lens is not None:
                        seq_out[shape[1]] = (out.shape[1]
                                             if out.ndim >= 2 else None)
                    timings[f"{shape}@{i}" if len(self._devices) > 1
                            else str(shape)] = time.perf_counter() - t0
        if len(seq_out) >= 2:
            # ≥2 sequence rungs disambiguate per-timestep outputs (dim 1
            # tracks the padded length) from pooled ones (constant dim 1
            # that may coincide with ONE rung); a single rung stays on
            # the dispatch-time shape heuristic
            # graftlint: disable=lock-discipline -- startup phase: warmup
            # completes before the pool serves; _warm below is the fence
            self._seq_out_per_timestep = all(w == t
                                             for t, w in seq_out.items())
        # graftlint: disable=lock-discipline -- startup publication:
        # workers only consult the trace alarm once _warm flips, and both
        # stores happen-before any dispatch observes _warm=True
        self._traces_seen = self._trace_cell[0]
        # graftlint: disable=lock-discipline -- same startup publication
        self._warm = True
        return timings

    def _run_bucket(self, padded: np.ndarray,
                    dev_idx: int = 0) -> np.ndarray:
        exe = self._compile_bucket(tuple(padded.shape),
                                   dev_idx % len(self._devices))
        if exe is None:                       # generic-model fallback
            out = self.model.output(padded)
            out = out[0] if isinstance(out, list) else out
            return out.to_numpy()
        params, states = self._dev_params[dev_idx % len(self._devices)]
        return np.asarray(exe(params, states,
                              padded.astype(self._in_dtype, copy=False),
                              self._key))

    def _run(self, batch: np.ndarray) -> NDArray:
        """Single-batch path (health probes, sequential mode): the same
        bucket executables, padded and sliced like any served request."""
        n = batch.shape[0]
        bucket = self.ladder.bucket_batch(n)
        if bucket is None:
            return super()._run(batch)        # oversize probe: direct
        padded, _w = pad_rows(batch, bucket)
        return NDArray(self._run_bucket(padded)[:n])

    # --- request admission ---------------------------------------------
    def output_async(self, x) -> Future:
        """Admit one request (see the module docstring's admission rule).
        Oversize rejections and ladder violations raise SYNCHRONOUSLY —
        nothing is queued; every admitted request resolves through its
        future (deadline-bounded via :meth:`output`)."""
        arr = np.asarray(x.value if isinstance(x, NDArray) else x)
        if arr.ndim != len(self._feat) + 1:
            raise ValueError(
                f"request rank {arr.ndim} does not match the serving "
                f"input shape (batch, *{self._feat})")
        if arr.dtype != self._in_dtype:
            arr = arr.astype(self._in_dtype)
        prof = OpProfiler.get()
        with self._lock:
            # the documented serving REQUEST ordinal (0, 1, 2, ... per
            # output_async call) — distinct from _req_seq, which ticks
            # once per queued CHUNK and would leave enqueue-drill
            # indices unreachable for split requests
            admit_seq = self._admit_seq
            self._admit_seq += 1
        t_real = None
        if self.ladder.seq_lens is not None:
            t = int(arr.shape[1])
            tb = self.ladder.bucket_seq(t)    # oversize seq: raises
            if arr.shape[2:] != self._feat[1:]:
                raise ValueError(
                    f"request feature shape {arr.shape[2:]} does not "
                    f"match the serving input shape {self._feat[1:]}")
            if tb != t:
                arr, _w = pad_rows(arr, tb, axis=1)
                prof.count("serving/seq_padded")
            t_real = t
        elif arr.shape[1:] != self._feat:
            raise ValueError(
                f"request feature shape {arr.shape[1:]} does not match "
                f"the serving input shape {self._feat}")
        try:
            chunks = self.ladder.admit(arr.shape[0])
        except OversizeRequest:
            prof.count("serving/oversize_rejected")
            raise
        fired = faultinject.fault_point("serving/enqueue", admit_seq)
        del fired  # advisory kinds have no enqueue-side meaning (yet)
        if len(chunks) == 1:
            return self._submit(arr, t_real)
        prof.count("serving/oversize_split")
        futs, off = [], 0
        for c in chunks:
            futs.append(self._submit(arr[off:off + c], t_real))
            off += c
        return self._aggregate(futs)

    def _submit(self, arr: np.ndarray, t_real: Optional[int]) -> Future:
        fut: Future = Future()
        if self._shutdown:
            fut.set_exception(RuntimeError(
                "ServingEngine is shut down; no replicas will serve this "
                "request"))
            return fut
        if self.alive_replicas() == 0:
            fut.set_exception(RuntimeError(
                "all serving replicas have been retired; a resurrection "
                "may be pending — retry, or rebuild the engine"))
            return fut
        with self._lock:
            seq = self._req_seq
            self._req_seq += 1
            depth = self._queue.qsize() + 1
            if depth > self._hwm:
                self._hwm = depth
                prof = OpProfiler.get()
                # the shared gauge is the FLEET high-water: only ever
                # raise it, or a lightly-loaded engine's write would
                # mask another engine's backlog
                if depth > prof.counter_value("serving/queue_depth_hwm"):
                    prof.gauge("serving/queue_depth_hwm", depth)
        # request lifecycle, leg 1 of enqueue → batch → dispatch → reply;
        # the request ordinal IS the correlation id, so one grep follows
        # a request through replica deaths and requeues. Emitted BEFORE
        # the queue put: once a worker can see the request, its batch/
        # reply events must not be able to precede this one. Guarded
        # like legs 2/4: per-request kwargs stay off the disabled path
        if flightrec.enabled():
            flightrec.event("serving/enqueue", corr=f"req{seq}", req=seq,
                            rows=int(arr.shape[0]))
        self._enqueue(_Request(arr, fut, seq, time.monotonic(),
                               t_real=t_real))
        return fut

    def _aggregate(self, futs: List[Future]) -> Future:
        """Recombine a split oversize request: chunk results concatenate
        in submission order; the first chunk failure fails the whole
        request (partial answers are worse than retried ones)."""
        parent: Future = Future()
        parent.enqueued_at = min(getattr(f, "enqueued_at", time.monotonic())
                                 for f in futs)
        remaining = [len(futs)]
        lock = threading.Lock()

        def one_done(f: Future) -> None:
            with lock:
                if parent.done():
                    return
                exc = f.exception()
                if exc is not None:
                    parent.set_exception(exc)
                    return
                remaining[0] -= 1
                if remaining[0]:
                    return
            parts = [fu.result().to_numpy() for fu in futs]
            parent.set_result(NDArray(np.concatenate(parts, axis=0)))

        for f in futs:
            f.add_done_callback(one_done)
        return parent

    # --- continuous-batching drain --------------------------------------
    def _next_request(self, timeout: float) -> Optional[_Request]:
        with self._stash_lock:
            if self._stashq:
                return self._stashq.popleft()
        try:
            return self._queue.get(timeout=max(0.0, timeout))
        except queue.Empty:
            return None

    def _stash(self, req: _Request) -> None:
        """Hold a request this batch cannot take (bucket overflow or a
        non-batch-shape mismatch) for the NEXT batch — stashed requests
        outrank the queue, so nothing is starved or reordered past one
        batch."""
        with self._stash_lock:
            self._stashq.append(req)

    def _drain(self, worker_id: int) -> None:
        prof = OpProfiler.get()
        with self._lock:
            if worker_id not in self._dev_of:
                # claim a pinned-device slot: a retired worker's freed
                # slot first (the replacement takes over its chip),
                # round-robin otherwise (the startup pool)
                self._dev_of[worker_id] = (
                    self._dev_free.pop() if self._dev_free
                    else worker_id % len(self._devices))
        while not self._shutdown:
            first = self._next_request(0.1)
            if first is None:
                continue
            batch, rows = [first], first.n
            shape_tail = first.arr.shape[1:]
            # fill toward the LARGEST bucket under one absolute deadline
            # (continuous batching: the window caps added latency, the
            # ladder caps the fill)
            deadline = time.monotonic() + self.max_wait_s
            while rows < self.ladder.max_batch:
                nxt = self._next_request(deadline - time.monotonic())
                if nxt is None:
                    break
                if (nxt.arr.shape[1:] != shape_tail
                        or rows + nxt.n > self.ladder.max_batch):
                    self._stash(nxt)
                    break
                batch.append(nxt)
                rows += nxt.n
            with self._lock:
                self._busy += 1
            try:
                self._dispatch(worker_id, batch, rows, prof)
            except faultinject.DeadReplicaFault:
                return          # replica retired inside _dispatch
            finally:
                with self._lock:
                    self._busy -= 1
        with self._lock:
            self._alive -= 1

    def _dispatch(self, worker_id: int, batch: List[_Request], rows: int,
                  prof) -> None:
        with self._lock:
            ordinal = self._batch_seq
            self._batch_seq += 1
        # leg 2: the batch formed by continuous batching — emitted BEFORE
        # the dispatch drill site, so a killed dispatch still shows which
        # requests were aboard (the incident-reconstruction contract).
        # enabled() guard: the reqs list is per-batch hot-path allocation
        # that must not be built just to be discarded
        if flightrec.enabled():
            flightrec.event("serving/batch", batch=ordinal, rows=rows,
                            worker=worker_id,
                            reqs=[int(r.seq) for r in batch])
        try:
            faultinject.fault_point("serving/dispatch", ordinal)
        except faultinject.TransientFault:
            # one deterministic requeue-and-retry (drill for the retry
            # path); the requests keep their queue-entry timestamps
            self._requeue(batch, faultinject.TransientFault(
                "serving dispatch retry budget exhausted"))
            return
        except faultinject.DeadReplicaFault as e:
            self._retire_serving(worker_id, e, batch)
            raise
        bucket = self.ladder.bucket_batch(rows)
        merged = (batch[0].arr if len(batch) == 1
                  else np.concatenate([r.arr for r in batch], axis=0))
        padded, _w = pad_rows(merged, bucket)
        try:
            with prof.time_section("serving/dispatch"):
                result = self._run_bucket(
                    padded, self._dev_of.get(worker_id, 0))
        except faultinject.DeadReplicaFault as e:
            self._retire_serving(worker_id, e, batch)
            raise
        except Exception as e:
            prof.count("serving/batch_errors")
            for r in batch:
                if not r.fut.done():
                    r.fut.set_exception(e)
            return
        except BaseException as e:
            # bookkeeping parity with ParallelInference._serve_batch: an
            # injected SimulatedCrash must still retire cleanly
            self._retire(worker_id, e, [r.fut for r in batch])
            raise
        # graftlint: disable=lock-discipline -- last-write-wins slot: one
        # atomic reference store of a fresh owning copy (same contract as
        # ParallelInference._serve_batch)
        self._probe_input = padded[:1].copy()
        t_done = time.monotonic()
        t_pad = padded.shape[1] if padded.ndim >= 2 else None
        off = 0
        lats = []
        for r in batch:
            out = result[off:off + r.n]
            off += r.n
            if (r.t_real is not None and out.ndim >= 2
                    and out.shape[1] == t_pad
                    and self._seq_out_per_timestep is not False):
                # per-timestep output: slice the sequence pad back off.
                # warmup probes the ladder to rule OUT pooled outputs
                # whose width merely coincides with one sequence rung
                out = out[:, :r.t_real]
            lats.append(t_done - r.t_enq)
            r.fut.set_result(NDArray(out))
            # leg 4 (leg 3, the dispatch itself, is the profiler's
            # serving/dispatch section — an X lane in the Chrome trace);
            # guarded: per-request latency math + kwargs stay off the
            # disabled hot path
            if flightrec.enabled():
                flightrec.event(
                    "serving/reply", corr=f"req{r.seq}", req=int(r.seq),
                    batch=ordinal,
                    latency_ms=round((t_done - r.t_enq) * 1e3, 3))
        with self._lat_lock:
            self._latencies.extend(lats)
        prof.count("serving/requests", len(batch))
        prof.count("serving/batches")
        prof.count("serving/rows", rows)
        prof.count("serving/pad_rows", bucket - rows)
        prof.count("serving/capacity_rows", bucket)
        if self._warm:
            traces = self._trace_cell[0]
            if traces > self._traces_seen:
                # the one thing steady-state serving must never do. Under
                # the pool lock: concurrent workers racing the unlocked
                # read-modify-write would double-count the alarm delta
                with self._lock:
                    delta = traces - self._traces_seen
                    if delta > 0:
                        prof.count("serving/traces_after_warmup", delta)
                        self._traces_seen = traces
                if delta > 0:
                    logger.warning("serving traced AFTER warmup (shape "
                                   "%s) — a bucket escaped the warmup "
                                   "set", padded.shape)

    def _requeue(self, batch: List[_Request], exhausted_exc) -> None:
        prof = OpProfiler.get()
        for r in batch:
            r.attempts += 1
            if r.attempts > self.max_requeues:
                if not r.fut.done():
                    r.fut.set_exception(exhausted_exc)
                continue
            try:
                self._queue.put_nowait(r)
            except queue.Full:
                if not r.fut.done():
                    r.fut.set_exception(TimeoutError(
                        "serving queue full while requeueing a request "
                        "from a retired replica"))
                continue
            # only a requeue that actually landed is a ride-through
            prof.count("serving/requeued")

    def _retire_serving(self, worker_id: int, exc: BaseException,
                        batch: List[_Request]) -> None:
        """Retirement TRANSPARENT to in-flight requests: requeue the
        dying replica's batch (bounded by ``max_requeues``) so surviving
        replicas serve it, then run the pool's shared retirement
        bookkeeping (which fails whatever is queued if this was the LAST
        replica — bounded latency outranks transparency — and schedules
        resurrection)."""
        flightrec.event("serving/retire", severity="warn",
                        worker=worker_id, error=repr(exc)[:200],
                        requeued=[int(r.seq) for r in batch])
        self._requeue(batch, exc)
        with self._lock:
            # free the dead worker's pinned-device slot for its
            # resurrected replacement
            dev = self._dev_of.pop(worker_id, None)
            if dev is not None:
                self._dev_free.append(dev)
        self._retire(worker_id, exc, [])      # casualties already failed

    def _probe(self) -> None:
        """Resurrection health probe on the device slot the REPLACEMENT
        will claim — the base class probes through ``_run``, which always
        dispatches on device 0 and would validate a healthy chip while
        refilling a dead one's slot."""
        faultinject.fault_point("inference/probe", self._next_probe_seq())
        probe = self._probe_input
        if probe is None:
            return
        with self._lock:
            dev = self._dev_free[-1] if self._dev_free else 0
        bucket = self.ladder.bucket_batch(probe.shape[0])
        if bucket is None:
            self._run(probe)
            return
        padded, _w = pad_rows(probe, bucket)
        self._run_bucket(padded, dev)

    def shutdown(self, drain_timeout_s: float = 2.0) -> None:
        super().shutdown(drain_timeout_s)
        # out of the health census: a shut-down engine must not report
        # itself (or its stale latency window) as live serving capacity
        _ENGINES.discard(self)

    def _fail_queued(self, exc) -> int:
        """The stash is queue too: a request held for the next batch must
        fail with the rest when the pool dies or shuts down — the base
        contract ('no waiter is left hanging') covers both stores."""
        n = super()._fail_queued(exc)
        while True:
            with self._stash_lock:
                if not self._stashq:
                    return n
                req = self._stashq.popleft()
            if not req.fut.done():
                req.fut.set_exception(exc)
                n += 1

    # --- stats ----------------------------------------------------------
    def latency_stats(self) -> Dict[str, float]:
        """Rolling p50/p99 over the last ≤4096 served requests, in ms."""
        with self._lat_lock:
            window = list(self._latencies)
        if not window:
            return {"window": 0}
        arr = np.asarray(window) * 1e3
        return {"window": len(window),
                "p50_ms": float(np.percentile(arr, 50)),
                "p99_ms": float(np.percentile(arr, 99)),
                "max_ms": float(arr.max())}

    def serving_stats(self) -> Dict[str, Any]:
        """This engine's census for :func:`serving_health`: pool
        live/retired/resurrected, bucket/warmup state, queue-depth
        high-water, rolling latency quantiles."""
        out: Dict[str, Any] = dict(self.pool_stats())
        out.update(self.latency_stats())
        with self._exec_lock:
            out["buckets_compiled"] = len(self._exec)
        out["warm"] = self._warm
        out["queue_depth_hwm"] = self._hwm
        out["bf16"] = self._bf16
        return out


def serving_health() -> Dict[str, Any]:
    """The ``/api/health`` "serving" section: the profiler's
    ``serving_stats()`` ledger (requests, batches, fill ratio, pad waste,
    traces-after-warmup, dispatch/warmup time) merged with a per-engine
    census and the rolling latency quantiles only the engines hold."""
    out: Dict[str, Any] = dict(OpProfiler.get().serving_stats())
    engines = list(_ENGINES)
    out["engines"] = len(engines)
    if engines:
        out["engine_stats"] = [e.serving_stats() for e in engines]
        samples: List[float] = []
        for e in engines:
            with e._lat_lock:
                samples.extend(e._latencies)
        if samples:
            arr = np.asarray(samples) * 1e3
            out["latency_p50_ms"] = float(np.percentile(arr, 50))
            out["latency_p99_ms"] = float(np.percentile(arr, 99))
    return out
