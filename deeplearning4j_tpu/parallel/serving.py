"""Production inference serving: continuous batching over AOT shape buckets.

The millions-of-users tier (ROADMAP item 2). :class:`ParallelInference`
gives this stack a replica pool with health probes, retirement,
resurrection and per-request deadlines — but it dispatches each coalesced
batch AT ITS OWN SHAPE, so concurrent traffic at varying batch/sequence
sizes retraces and serializes behind jit compiles. This module closes the
gap with the compile-once-run-many recipe the whole-graph-compilation
literature argues for (TVM, arXiv:1802.04799; nGraph, arXiv:1801.08058):

- **Shape buckets** (:class:`BucketLadder`): a configurable batch-size
  ladder (and optional sequence-length ladder). Every request is padded UP
  to the smallest admitting bucket, so the set of shapes the model ever
  sees is small, fixed, and known at startup.
- **AOT executables per bucket**: each bucket's inference function is
  ``jax.jit(...).lower(...).compile()``-d at pool startup
  (:meth:`ServingEngine.warmup`), so steady-state serving NEVER traces —
  the ``serving/traces_after_warmup`` counter must stay 0 and the
  serving-smoke bench hard-fails when it doesn't. Warmup cost is paid
  once, up front, per bucket (the ``serving/warmup`` profiler section
  ledgers it).
- **Pad-and-mask reuse**: bucket padding is :func:`data.pipeline.pad_rows`
  — the SAME wrap-real-rows rule the training pipeline uses, so padding
  rows are provably inert: a pad slot is an exact copy of a real row,
  per-example inference computes for it exactly what it computed for the
  real row, and the scatter slices it off. ``tests/test_serving.py``
  proves the bucketed output BITWISE-equal to an unpadded direct
  ``model.output``. (BatchNorm is no caveat here: inference-mode BN uses
  running stats, which are per-example.)
- **Continuous batching**: replica workers drain the shared request queue
  into the largest fillable bucket under a ``max_wait_ms`` deadline — a
  request that would overflow the largest bucket (or mismatch the batch's
  non-batch shape) is stashed for the next batch, never dropped.
- **bf16 inference params** (``Builder.bf16(True)``): one cast at startup
  (and on :meth:`refresh_params`), halving weight bytes and engaging the
  bf16 matmul units; inputs/outputs stay float32 at the API boundary.
  Numerics change (~1e-2 relative) — the bitwise guarantee above is the
  fp32 path's.
- **Replica-pool integration**: ServingEngine IS a ParallelInference — it
  inherits retirement, health-probe resurrection, deadlines and shutdown
  draining. Retirement is additionally TRANSPARENT to in-flight requests:
  a dying replica's batch is requeued (bounded by ``max_requeues``, true
  queue-entry timestamps preserved) instead of failed, so the
  kill-a-replica-mid-load drill completes with zero failed requests while
  the PR-4 resurrection machinery refills the pool. When the LAST replica
  dies, queued requests still fail fast (the pool's bounded-latency
  contract outranks transparency).

**Admission rule for oversize requests** (documented contract): a request
with more rows than the largest batch bucket is, under
``oversize="split"`` (the default), split into largest-bucket-sized
chunks served independently and re-concatenated in order — its latency is
then bounded by ``ceil(n/max_batch)`` bucket dispatches; under
``oversize="reject"`` it raises :class:`OversizeRequest` synchronously at
submission, before anything is queued. A sequence length over the ladder
ALWAYS rejects — time steps cannot be split across executables by a
serving layer that does not know the model's temporal semantics.

**Overload safety** (ISSUE 11): requests optionally carry an SLO CLASS
(:class:`SLOClass` — e.g. ``gold``/``silver``/``batch``, each with a
priority, a p99 budget, and a per-class queue budget). Admission is
synchronous: a shed request gets :class:`Overloaded` (HTTP 429) with a
``Retry-After`` derived from the MEASURED queue drain rate, never a slot
in a queue it would time out of. Under overload the
:class:`BrownoutController` sheds classes strictly
lowest-priority-first — one level step per controller tick, cleared only
after several consecutive clean evaluations (hysteresis; a request is
never flapped) — defending the top class's p99 budget. The queue-depth
signal is a decaying WINDOWED high-water mark (``queue_depth_hwm``; the
lifetime max lives separately in ``queue_depth_peak``), so it can drive
scale-DOWN as well as scale-up.

**Elastic capacity**: ``scale_to(n)`` grows/shrinks the worker pool
online — new workers reuse the already-compiled bucket executables
(recompiles stay at one per bucket x device slot at ANY replica count),
surplus workers exit at a batch boundary. The closed-loop autoscaler
driving it from the windowed HWM / rolling p99 / fill-ratio signals is
:class:`parallel.autoscale.Autoscaler`.

**Canaried train-to-serve handoff**: :meth:`ServingEngine.
publish_checkpoint` hot-swaps retrained weights onto ONE canary replica
(zero recompiles — the executables take params as arguments), promotes
fleet-wide after an SLO-clean window, and auto-rollbacks BITWISE (the
exact prior device arrays are restored) on violation; the ``pub<N>``
correlation id chains train-commit -> canary -> promote/rollback in the
flight recorder.

HTTP serving lives on the existing UI server: ``UIServer.attach_serving``
exposes ``POST /api/infer`` next to ``/api/health`` (whose ``serving``
section is :func:`serving_health`); sheds map to ``429`` +
``Retry-After``. Load-test with ``python bench.py --config
serving-smoke`` (open-loop Poisson, hard-fail p50/p99/QPS SLO gates,
kill-a-replica drill) and ``--config autoscale-smoke`` (diurnal + spike
replay at 5x the serving-smoke rate, shed-order/scale-latency/canary
gates).
"""

from __future__ import annotations

import collections
import os
import queue
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..common import faultinject, flightrec, xprof
from ..common import integrity as _integ
from ..common.profiler import OpProfiler
from ..data.pipeline import pad_rows
from ..ndarray.ndarray import NDArray
from ..ndarray.rng import get_random
from .inference import ParallelInference, _Request, logger
from .mesh import serving_devices

# live engines, for the /api/health serving census (weak: dropped → gone)
_ENGINES: "weakref.WeakSet" = weakref.WeakSet()

# process-global publication ordinal: the pub<N> correlation id must be
# unique across every engine's lifetime or one grep of the timeline
# could conflate two publications (it is also the serving/promote fault
# drill index — see next_publication_ordinal)
_pub_lock = threading.Lock()
_pub_next = [0]


def next_publication_ordinal() -> int:
    """The ordinal (= ``serving/promote`` fault index, = the N in the
    ``pub<N>`` correlation id) the NEXT ``publish_checkpoint`` call will
    get — how drills target a specific publication deterministically."""
    with _pub_lock:
        return _pub_next[0]

_MISS = object()     # _exec sentinel: None is a real (generic-model) entry


class OversizeRequest(ValueError):
    """A request the bucket ladder refuses to admit: more rows than the
    largest batch bucket under ``oversize="reject"``, or a sequence longer
    than the largest sequence bucket (never splittable). Raised
    synchronously at submission — nothing is queued."""


class BucketLadder:
    """The bucket policy: sorted batch-size ladder, optional sequence-
    length ladder, and the oversize admission rule (see module docstring).

    ``bucket_batch(n)`` / ``bucket_seq(t)`` return the smallest admitting
    rung; ``admit(n)`` returns the chunk row-counts a request is served
    as (``[n]`` for an in-ladder request)."""

    def __init__(self, batch_sizes: Sequence[int] = (1, 2, 4, 8, 16, 32),
                 seq_lens: Optional[Sequence[int]] = None,
                 oversize: str = "split"):
        sizes = sorted({int(b) for b in batch_sizes})
        if not sizes or sizes[0] < 1:
            raise ValueError(f"batch ladder needs positive sizes, got "
                             f"{batch_sizes!r}")
        self.batch_sizes: Tuple[int, ...] = tuple(sizes)
        self.seq_lens: Optional[Tuple[int, ...]] = None
        if seq_lens is not None:
            sl = sorted({int(t) for t in seq_lens})
            if not sl or sl[0] < 1:
                raise ValueError(f"sequence ladder needs positive lengths, "
                                 f"got {seq_lens!r}")
            self.seq_lens = tuple(sl)
        if oversize not in ("split", "reject"):
            raise ValueError(f"oversize must be 'split' or 'reject', got "
                             f"{oversize!r}")
        self.oversize = oversize

    @property
    def max_batch(self) -> int:
        return self.batch_sizes[-1]

    def bucket_batch(self, n: int) -> Optional[int]:
        """Smallest batch bucket >= n, or None when n exceeds the ladder."""
        for b in self.batch_sizes:
            if b >= n:
                return b
        return None

    def bucket_seq(self, t: int) -> int:
        """Smallest sequence bucket >= t. Oversize sequences ALWAYS
        reject (module docstring: time steps cannot be split)."""
        assert self.seq_lens is not None
        for s in self.seq_lens:
            if s >= t:
                return s
        raise OversizeRequest(
            f"sequence length {t} exceeds the largest sequence bucket "
            f"{self.seq_lens[-1]}; lengthen the ladder or truncate "
            f"upstream")

    def admit(self, n: int) -> List[int]:
        """The admission rule. Raises :class:`OversizeRequest` under
        ``oversize='reject'``; splits into max-bucket chunks (+ remainder)
        under ``'split'``."""
        if n < 1:
            raise ValueError(f"a request needs at least one row, got {n}")
        if n <= self.max_batch:
            return [n]
        if self.oversize == "reject":
            raise OversizeRequest(
                f"request of {n} rows exceeds the largest batch bucket "
                f"{self.max_batch} (oversize='reject'); split it client-"
                f"side or configure oversize='split'")
        chunks = [self.max_batch] * (n // self.max_batch)
        if n % self.max_batch:
            chunks.append(n % self.max_batch)
        return chunks

    def shapes(self, feat: Tuple[int, ...]) -> List[Tuple[int, ...]]:
        """Every input shape the ladder admits — the warmup compile set.
        ``feat`` is the per-request feature shape (no batch dim); with a
        sequence ladder its leading entry is the time axis and is replaced
        by each sequence rung."""
        if self.seq_lens is None:
            return [(b,) + tuple(feat) for b in self.batch_sizes]
        if not feat:
            raise ValueError("a sequence ladder needs a feature shape "
                             "with a leading time axis")
        return [(b, t) + tuple(feat[1:])
                for b in self.batch_sizes for t in self.seq_lens]


# THE fp32-boundary cast, shared with the training side's low-precision
# updater state — learning/precision.py owns the dtype-boundary rules
# (one doc, one helper; this module used to carry its own copy).
from ..learning.precision import cast_floating as _cast_floating


class Overloaded(RuntimeError):
    """Synchronous load-shed rejection (the HTTP tier maps it to 429):
    the engine is inside a brownout and this request's SLO class is
    currently shed, or the class's queue budget is exhausted. Carries
    ``retry_after_s`` derived from the MEASURED queue drain rate (the
    ``Retry-After`` header), so clients back off proportionally to the
    actual backlog instead of a fixed guess. Raised at submission —
    nothing is queued."""

    def __init__(self, message: str, slo_class: str, reason: str,
                 retry_after_s: float):
        super().__init__(message)
        self.slo_class = slo_class
        self.reason = reason          # "brownout" | "queue_budget" | "fault"
        self.retry_after_s = float(retry_after_s)


class SLOClass:
    """One admission class. ``priority`` orders shedding — strictly
    lowest-priority-first, and the top class is NEVER shed. ``p99_ms``
    is the class's latency budget: the top class's budget is what the
    brownout controller defends and what the canary publication's
    SLO-clean window defaults to. ``queue_budget`` bounds how many
    requests of this class may be outstanding at once (per-class
    backpressure: one flooding tenant cannot fill the shared queue for
    everyone else)."""

    def __init__(self, name: str, priority: int, p99_ms: float,
                 queue_budget: int = 128):
        self.name = str(name)
        self.priority = int(priority)
        self.p99_ms = float(p99_ms)
        self.queue_budget = int(queue_budget)
        if not self.name:
            raise ValueError("an SLO class needs a non-empty name")
        if self.p99_ms <= 0 or self.queue_budget < 1:
            raise ValueError(f"SLO class {name!r} needs p99_ms > 0 and "
                             f"queue_budget >= 1")

    def __repr__(self) -> str:
        return (f"SLOClass({self.name!r}, priority={self.priority}, "
                f"p99_ms={self.p99_ms}, queue_budget={self.queue_budget})")


class AdmissionController:
    """Per-class admission state: outstanding counts against queue
    budgets, the brownout shed LEVEL (0 admits everything; level k sheds
    the k lowest-priority classes), completion-rate tracking for
    ``Retry-After``, and the per-class shed counters
    (``serving/shed/<class>``). Shedding is strictly bottom-up BY CLASS
    and the level only moves at controller cadence with hysteresis
    (:class:`BrownoutController`) — an individual request is never
    flapped: its class is either shed right now or it is not."""

    DRAIN_WINDOW_S = 5.0

    def __init__(self, classes: Sequence[SLOClass],
                 default: Optional[str] = None):
        classes = list(classes)
        if not classes:
            raise ValueError("admission control needs >= 1 SLO class")
        names = [c.name for c in classes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO class names: {names}")
        if len({c.priority for c in classes}) != len(classes):
            raise ValueError("SLO class priorities must be unique — they "
                             "define the shed order")
        self._lock = threading.Lock()
        # ascending priority: index 0 sheds first, the last never sheds
        self.by_shed_order: Tuple[SLOClass, ...] = tuple(
            sorted(classes, key=lambda c: c.priority))
        self.top = self.by_shed_order[-1]
        self.by_name = {c.name: c for c in classes}
        self._rank = {c.name: i for i, c in enumerate(self.by_shed_order)}
        self.default = default if default is not None else self.top.name
        if self.default not in self.by_name:
            raise ValueError(f"default class {self.default!r} is not one "
                             f"of the configured SLO classes {names}")
        self._level = 0
        self._outstanding: Dict[str, int] = {c.name: 0 for c in classes}
        self._done: "collections.deque" = collections.deque(maxlen=4096)

    def resolve(self, name: Optional[str]) -> SLOClass:
        if name is None:
            name = self.default
        cls = self.by_name.get(name)
        if cls is None:
            raise ValueError(f"unknown SLO class {name!r}; configured: "
                             f"{sorted(self.by_name)}")
        return cls

    def level(self) -> int:
        with self._lock:
            return self._level

    def shed_names(self) -> List[str]:
        with self._lock:
            return [c.name for c in self.by_shed_order[:self._level]]

    def set_level(self, level: int, reason: str = "manual") -> int:
        """Move the shed level (the brownout controller's actuator, and
        the deterministic overload drill hook). Clamped so the top class
        is never shed. A CHANGE emits one ``serving/shed`` event and
        updates the ``serving/shed_level`` gauge — per level transition,
        never per request."""
        level = max(0, min(int(level), len(self.by_shed_order) - 1))
        with self._lock:
            prev = self._level
            self._level = level
        if level != prev:
            prof = OpProfiler.get()
            prof.gauge("serving/shed_level", level)
            prof.count("serving/brownout_raise" if level > prev
                       else "serving/brownout_lower")
            flightrec.event(
                "serving/shed", severity="warn", level=level, prev=prev,
                shed=[c.name for c in self.by_shed_order[:level]],
                reason=str(reason)[:200])
            logger.warning("serving brownout level %d -> %d (%s)", prev,
                           level, reason)
        return level

    def note_queued(self, name: str) -> None:
        with self._lock:
            self._outstanding[name] = self._outstanding.get(name, 0) + 1

    def release(self, name: str, n: int = 1) -> None:
        """Return ``n`` reserved slots WITHOUT recording completions —
        for an admitted request that never reached the queue (an
        injected enqueue fault); completions go through note_done so
        the drain rate only counts work that actually drained."""
        with self._lock:
            self._outstanding[name] = max(
                0, self._outstanding.get(name, 0) - n)

    def note_done(self, name: str) -> None:
        with self._lock:
            self._outstanding[name] = max(
                0, self._outstanding.get(name, 0) - 1)
            self._done.append(time.monotonic())

    def _drain_rate_locked(self, now: float) -> float:
        recent = sum(1 for t in self._done
                     if now - t <= self.DRAIN_WINDOW_S)
        return recent / self.DRAIN_WINDOW_S

    def retry_after_s(self) -> float:
        """Backlog / measured drain rate, clamped to [0.1s, 30s] — how
        long a shed client should wait before the queue has plausibly
        drained. With no completions observed yet the estimate falls
        back to a per-request pessimistic constant."""
        now = time.monotonic()
        with self._lock:
            outstanding = sum(self._outstanding.values())
            rate = self._drain_rate_locked(now)
        if rate <= 0:
            return min(30.0, 1.0 + outstanding * 0.05)
        return float(min(30.0, max(0.1, outstanding / rate)))

    def admit(self, cls: SLOClass, n_chunks: int = 1) -> None:
        """The admission decision: raises :class:`Overloaded` when the
        class is inside the brownout shed set or its queue budget is
        exhausted; otherwise RESERVES ``n_chunks`` outstanding slots
        under the same lock (check-then-reserve atomically — concurrent
        HTTP threads must not all pass the same budget headroom) and
        returns. The caller releases the reservation via the per-chunk
        completion callbacks (:meth:`note_done`) or, for a submission
        that never reaches the queue, :meth:`release`."""
        with self._lock:
            if self._rank[cls.name] < self._level:
                reason = "brownout"
            elif self._outstanding.get(cls.name, 0) + n_chunks \
                    > cls.queue_budget:
                reason = "queue_budget"
            else:
                self._outstanding[cls.name] = \
                    self._outstanding.get(cls.name, 0) + n_chunks
                return
        self.count_shed(cls.name)
        ra = self.retry_after_s()
        raise Overloaded(
            f"request shed ({reason}): class {cls.name!r} "
            + ("is inside the brownout shed set"
               if reason == "brownout" else
               f"already has {cls.queue_budget} request(s) outstanding "
               f"(its queue budget)")
            + f"; retry after {ra:.2f}s", cls.name, reason, ra)

    @staticmethod
    def count_shed(name: str) -> None:
        prof = OpProfiler.get()
        prof.count(f"serving/shed/{name}")
        prof.count("serving/shed_total")

    def stats(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            return {
                "level": self._level,
                "shed": [c.name for c in self.by_shed_order[:self._level]],
                "classes": [c.name for c in reversed(self.by_shed_order)],
                "outstanding": dict(self._outstanding),
                "drain_rate_rps": round(self._drain_rate_locked(now), 3),
            }


class BrownoutController:
    """Keeps the TOP class inside its p99 budget by progressively
    shedding lower classes. Evaluates at a fixed cadence (never
    per-request): the level RAISES one step when the top class's recent
    p99 exceeds its budget or the windowed queue-depth HWM crosses the
    depth trigger, and LOWERS one step only after ``clear_ticks``
    consecutive clean evaluations (p99 under ``hysteresis_frac`` x
    budget AND depth back under half the trigger). The asymmetry is the
    hysteresis: overload sheds within one controller interval, recovery
    un-sheds slowly enough that an oscillating load cannot flap a class
    in and out of admission."""

    def __init__(self, engine: "ServingEngine", adm: AdmissionController,
                 interval_s: float = 0.2,
                 depth_trigger: Optional[int] = None,
                 clear_ticks: int = 5, hysteresis_frac: float = 0.7):
        self.engine = engine
        self.adm = adm
        self.interval_s = float(interval_s)
        self.depth_trigger = (int(depth_trigger) if depth_trigger
                              else max(8, engine._queue.maxsize // 4))
        self.clear_ticks = max(1, int(clear_ticks))
        self.hysteresis_frac = float(hysteresis_frac)
        self._clean = 0           # single-writer: the controller thread
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dl4j-serving-brownout")
        self._thread.start()

    def evaluate(self, p99_ms: Optional[float], depth: int) -> int:
        """One control decision from measured signals (split out so
        tests and drills drive the hysteresis deterministically).
        Returns the level in force after the decision."""
        top = self.adm.top
        over = ((p99_ms is not None and p99_ms > top.p99_ms)
                or depth >= self.depth_trigger)
        level = self.adm.level()
        if over:
            self._clean = 0
            if level < len(self.adm.by_shed_order) - 1:
                return self.adm.set_level(
                    level + 1,
                    reason=f"overload: top p99={p99_ms and round(p99_ms, 1)}"
                           f"ms (budget {top.p99_ms}ms), depth={depth} "
                           f"(trigger {self.depth_trigger})")
            return level
        clean = ((p99_ms is None
                  or p99_ms <= self.hysteresis_frac * top.p99_ms)
                 and depth <= self.depth_trigger // 2)
        if not clean:
            self._clean = 0
            return level
        if level > 0:
            self._clean += 1
            if self._clean >= self.clear_ticks:
                self._clean = 0
                return self.adm.set_level(
                    level - 1, reason=f"recovered: {self.clear_ticks} "
                                      f"clean evaluations")
        return self.adm.level()

    def _run(self) -> None:
        eng = self.engine
        while not eng._shutdown:
            time.sleep(self.interval_s)
            if eng._shutdown:
                return
            try:
                self.evaluate(
                    eng._class_recent_p99(self.adm.top.name),
                    eng.queue_depth_hwm())
            except Exception:
                logger.warning("brownout evaluation failed", exc_info=True)


class PublishHandle:
    """Tracks one canaried weight publication to its terminal state.
    ``result(timeout)`` blocks for ``"promoted"`` (SLO-clean canary +
    confirm windows; the fleet serves the new weights) or
    ``"rolled_back"`` (a violation anywhere restored the prior params
    bitwise). ``corr`` is the flight-recorder correlation id chaining
    train-commit -> canary -> promote/rollback."""

    def __init__(self, corr: str, path: str):
        self.corr = corr
        self.path = path
        self.phase = "canary"
        self._done = threading.Event()
        self._outcome: Optional[str] = None

    def _finish(self, outcome: str) -> None:
        self._outcome = outcome
        self.phase = outcome
        self._done.set()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> str:
        if not self._done.wait(timeout):
            raise TimeoutError(f"publication {self.corr} still in phase "
                               f"{self.phase!r}")
        return self._outcome


class ServingEngine(ParallelInference):
    """The serving tier: a ParallelInference replica pool whose workers
    drain the shared queue into padded shape buckets served by
    AOT-compiled executables. See the module docstring for the policy
    contract; see :class:`Builder` for knobs."""

    class Builder(ParallelInference.Builder):
        def __init__(self, model):
            super().__init__(model)
            self._max_wait_ms = 2.0      # serving default: tight window
            self._ladder: Optional[BucketLadder] = None
            self._input_shape: Optional[Tuple[int, ...]] = None
            self._in_dtype = np.float32
            self._bf16 = False
            self._warmup = True
            self._max_requeues = 2
            self._pin_devices = False
            self._slo_classes: Optional[List[SLOClass]] = None
            self._default_class: Optional[str] = None
            self._brownout_kw: Dict[str, Any] = {}
            self._qwin_window_s = 5.0

        def inference_mode(self, mode: str) -> "ServingEngine.Builder":
            """Serving IS continuous batching — the drain loop, stash and
            bucket fill only exist in batched mode, so anything else is
            refused loudly instead of silently coerced."""
            if mode.lower() != "batched":
                raise ValueError(
                    f"ServingEngine only serves in 'batched' mode (its "
                    f"continuous-batching drain loop IS the engine), got "
                    f"{mode!r}; use a plain ParallelInference for "
                    f"sequential dispatch")
            return self

        inferenceMode = inference_mode

        def buckets(self, batch_sizes: Sequence[int],
                    seq_lens: Optional[Sequence[int]] = None,
                    oversize: str = "split") -> "ServingEngine.Builder":
            """The bucket ladder (see :class:`BucketLadder`)."""
            self._ladder = BucketLadder(batch_sizes, seq_lens, oversize)
            return self

        def ladder(self, ladder: BucketLadder) -> "ServingEngine.Builder":
            self._ladder = ladder
            return self

        def input_shape(self, shape: Sequence[int],
                        dtype=np.float32) -> "ServingEngine.Builder":
            """Per-request feature shape (WITHOUT the batch dim) — what
            warmup compiles against. With a sequence ladder the leading
            entry is the time axis (any value; the ladder replaces it)."""
            self._input_shape = tuple(int(s) for s in shape)
            self._in_dtype = np.dtype(dtype)
            return self

        def bf16(self, enabled: bool = True) -> "ServingEngine.Builder":
            """Serve with bfloat16 params (one startup cast; float32 at
            the API boundary). Numerics caveat in the module docstring."""
            self._bf16 = enabled
            return self

        def warmup(self, enabled: bool) -> "ServingEngine.Builder":
            """Compile the bucket set at build() (default). Disabling
            defers each bucket's compile to its first hit — only for
            tests; production startup should eat the cost up front."""
            self._warmup = enabled
            return self

        def max_requeues(self, n: int) -> "ServingEngine.Builder":
            """How many replica deaths one request may ride through
            (requeue budget) before it fails like the replica did."""
            self._max_requeues = max(0, int(n))
            return self

        def slo_classes(self, classes: Sequence[SLOClass],
                        default: Optional[str] = None
                        ) -> "ServingEngine.Builder":
            """Enable SLO-class admission control: requests carry a
            class (``output_async(x, slo_class="gold")``; ``default``
            names the class an unclassified request gets — the TOP class
            when omitted). Under overload the brownout controller sheds
            classes strictly lowest-priority-first with a synchronous
            :class:`Overloaded` (HTTP 429 + Retry-After); each class's
            ``queue_budget`` bounds its outstanding requests."""
            self._slo_classes = [c if isinstance(c, SLOClass)
                                 else SLOClass(*c) for c in classes]
            self._default_class = default
            return self

        def queue_hwm_window(self, seconds: float
                             ) -> "ServingEngine.Builder":
            """Window length of the decaying queue-depth high-water mark
            (it decays to 0 within ~2 windows of the backlog clearing);
            the autoscaler's scale-down latency is bounded below by it."""
            self._qwin_window_s = float(seconds)
            return self

        def brownout(self, interval_s: Optional[float] = None,
                     depth_trigger: Optional[int] = None,
                     clear_ticks: Optional[int] = None,
                     hysteresis_frac: Optional[float] = None
                     ) -> "ServingEngine.Builder":
            """Tune the brownout controller (only meaningful with
            :meth:`slo_classes`); see :class:`BrownoutController` for
            the semantics of each knob."""
            for k, v in (("interval_s", interval_s),
                         ("depth_trigger", depth_trigger),
                         ("clear_ticks", clear_ticks),
                         ("hysteresis_frac", hysteresis_frac)):
                if v is not None:
                    self._brownout_kw[k] = v
            return self

        def pin_devices(self, enabled: bool = True
                        ) -> "ServingEngine.Builder":
            """Pin replica workers round-robin across devices
            (:func:`mesh.serving_devices`): each replica gets its own
            device-resident param copy and per-device executables, so
            replicas run on different chips instead of contending for one
            XLA stream. Costs one param copy + one compile set per
            distinct device."""
            self._pin_devices = enabled
            return self

        def build(self) -> "ServingEngine":
            if self._input_shape is None:
                raise ValueError(
                    "ServingEngine needs Builder.input_shape(...): the "
                    "AOT bucket executables are compiled against it at "
                    "warmup, before any request arrives")
            return ServingEngine(
                self._model, self._ladder or BucketLadder(),
                self._input_shape, in_dtype=self._in_dtype,
                bf16=self._bf16, warmup=self._warmup,
                max_requeues=self._max_requeues,
                pin_devices=self._pin_devices,
                slo_classes=self._slo_classes,
                default_class=self._default_class,
                brownout_kw=self._brownout_kw,
                queue_hwm_window_s=self._qwin_window_s,
                batch_limit=self._batch_limit,
                queue_limit=self._queue_limit,
                max_wait_ms=self._max_wait_ms, workers=self._workers,
                request_timeout_ms=self._request_timeout_ms,
                resurrect=self._resurrect,
                resurrect_backoff_ms=self._resurrect_backoff_ms,
                max_resurrections=self._max_resurrections)

    def __init__(self, model, ladder: BucketLadder,
                 input_shape: Tuple[int, ...], in_dtype=np.float32,
                 bf16: bool = False, warmup: bool = True,
                 max_requeues: int = 2, pin_devices: bool = False,
                 slo_classes: Optional[Sequence[SLOClass]] = None,
                 default_class: Optional[str] = None,
                 brownout_kw: Optional[Dict[str, Any]] = None,
                 queue_hwm_window_s: float = 5.0,
                 **pool_kwargs):
        # subclass state FIRST: super().__init__ starts the drain threads,
        # which call into the overridden _drain immediately
        self.ladder = ladder
        self._feat = tuple(input_shape)
        self._in_dtype = np.dtype(in_dtype)
        self._bf16 = bf16
        self.max_requeues = max_requeues
        self._compute_dtype = jnp.bfloat16 if bf16 else None
        self._adm = (AdmissionController(slo_classes, default=default_class)
                     if slo_classes else None)
        # decaying/windowed queue-depth high-water mark (two rolling
        # windows; the scale-down-capable signal) + the lifetime peak
        self._qwin_s = float(queue_hwm_window_s)
        self._qwin_start = time.monotonic()
        self._qwin_max = 0
        self._qwin_prev = 0
        self._q_peak = 0
        self._last_dispatch_t = time.monotonic()
        self._lat_recent: "collections.deque" = collections.deque(
            maxlen=2048)                 # (t_done, latency_s), all classes
        self._class_lats: Dict[str, "collections.deque"] = {}
        self._canary: Optional[Dict[str, Any]] = None
        self._pub_threads: List[threading.Thread] = []
        self._brownout: Optional[BrownoutController] = None
        self._devices = (serving_devices(pool_kwargs.get("workers", 1))
                         if pin_devices else [None])
        # worker -> pinned device slot; a retired worker's slot is freed
        # for its replacement (resurrection mints NEW worker ids, so a
        # plain worker_id % ndev would drift every pool generation onto
        # the wrong chips)
        self._dev_of: Dict[int, int] = {}
        self._dev_free: List[int] = []
        self._stash_lock = threading.Lock()
        self._stashq: "collections.deque" = collections.deque()
        self._exec: Dict[Any, Any] = {}     # (shape, dev_idx) -> runner
        self._exec_lock = threading.Lock()
        self._lat_lock = threading.Lock()
        self._latencies: "collections.deque" = collections.deque(maxlen=4096)
        self._batch_seq = 0
        self._admit_seq = 0          # request ordinal (serving/enqueue)
        self._warm = False
        # THIS engine's trace count (bumped trace-time in _make_infer):
        # the after-warmup alarm must not fire on another engine's warmup
        # bumping the shared trace/serving_infer ledger counter
        self._trace_cell = [0]
        self._traces_seen = 0
        # None = unknown (shape heuristic), True/False once warmup has
        # probed whether outputs carry a per-timestep axis to slice
        self._seq_out_per_timestep: Optional[bool] = None
        self._aot = (hasattr(model, "_forward")
                     and hasattr(model, "_params"))
        self._infer_jit = None
        self._dev_params: Dict[int, Any] = {}
        pool_kwargs.setdefault("mode", "batched")
        super().__init__(model, **pool_kwargs)
        if self._aot:
            self._key = get_random().next_key()
            self._snapshot_params()
        if warmup:
            self.warmup()
        if self._adm is not None:
            self._brownout = BrownoutController(self, self._adm,
                                                **(brownout_kw or {}))
            self._brownout.start()
        _ENGINES.add(self)

    # --- params / executables -----------------------------------------
    def _cast_serving(self, params, states):
        if self._bf16:
            params = _cast_floating(params, jnp.bfloat16)
            states = _cast_floating(states, jnp.bfloat16)
        return params, states

    def _place_params(self, params, states) -> Dict[int, Any]:
        """One (params, states) copy per device slot — the argument set
        every AOT bucket executable takes, so swapping a slot's entry
        (refresh, canary, promote, rollback) never recompiles."""
        placed: Dict[int, Any] = {}
        for i, dev in enumerate(self._devices):
            if dev is None:
                placed[i] = (params, states)
            else:
                placed[i] = jax.device_put((params, states), dev)
        return placed

    def _snapshot_params(self) -> None:
        params, states = self._cast_serving(self.model._params,
                                            self.model._states)
        placed = self._place_params(params, states)
        with self._lock:
            self._dev_params = placed

    def _params_for(self, worker_id: Optional[int], dev_slot: int):
        """The params a dispatch uses: the canary replica reads the
        candidate weights while a publication is in its canary phase;
        everyone else reads the fleet set. One racy dict read by design
        — a phase transition swaps whole dicts under the pool lock, and
        a batch that catches the old reference simply serves the
        previous (complete, consistent) weight set."""
        can = self._canary
        if can is not None and worker_id is not None \
                and can.get("phase") == "canary" \
                and can.get("worker") == worker_id:
            return can["canary_params"]
        return self._dev_params[dev_slot]

    def refresh_params(self) -> None:
        """Re-snapshot the model's (possibly retrained) params into the
        serving copies. CHEAP: the AOT executables take params as
        arguments, so same-shape updates swap in without any recompile
        (bf16 pays its cast again). Refused while a canaried publication
        is in flight — :meth:`publish_checkpoint` owns the param set
        until it resolves, or a rollback could restore weights the
        refresh already replaced."""
        if not self._aot:
            return
        with self._lock:
            if self._canary is not None:
                raise RuntimeError(
                    f"refresh_params refused: publication "
                    f"{self._canary['corr']} is in flight (phase "
                    f"{self._canary['phase']!r}); wait for it to resolve "
                    f"or use publish_checkpoint for the next weights")
        self._snapshot_params()

    def _make_infer(self):
        model = self.model
        cdt = self._compute_dtype
        cell = self._trace_cell

        def infer(params, states, x, key):
            # trace-time only: the retrace ledger the serving SLO gates on
            OpProfiler.get().count("trace/serving_infer")
            cell[0] += 1
            if cdt is not None:
                x = x.astype(cdt)
            out, _ = model._forward(params, states, x, False, key, None)
            return out.astype(jnp.float32)

        return infer

    def _compile_bucket(self, shape: Tuple[int, ...],
                        dev_idx: int = 0):
        """AOT-compile (``.lower().compile()``) the bucket executable for
        one input shape (and one pinned device, when pinning). Called for
        the whole ladder at :meth:`warmup`; a lazy hit (warmup disabled)
        compiles here on first use."""
        key = (shape, dev_idx)
        # lock-free hot path: every steady-state dispatch lands here, and
        # it must not queue behind another worker's (lazy) compile
        exe = self._exec.get(key, _MISS)
        if exe is not _MISS:
            return exe
        with self._exec_lock:
            if key in self._exec:
                return self._exec[key]
            if self._aot:
                if self._infer_jit is None:
                    self._infer_jit = jax.jit(self._make_infer())
                params, states = self._dev_params[dev_idx]
                aval = jax.ShapeDtypeStruct(shape, self._in_dtype)
                t0 = time.monotonic()
                exe = self._infer_jit.lower(
                    params, states, aval, self._key).compile()
                # executable census: the bucket ladder's AOT executables
                # feed the xla roofline ledger (cost/memory analysis is
                # extracted from the ALREADY-compiled object — nothing
                # retraces here)
                xprof.register_aot("serving/bucket", exe,
                                   variant=f"{shape}/dev{dev_idx}",
                                   compile_s=time.monotonic() - t0)
            else:
                # generic model (no jittable forward exposed): no AOT
                # executable — the model.output call right after this in
                # _run_bucket warms its jit cache at the bucket shape.
                # "never traces in steady state" still holds (every
                # later request reuses the shape), but the trace ledger
                # cannot see inside
                exe = None
            self._exec[key] = exe
            OpProfiler.get().count("serving/buckets_compiled")
            return exe

    def warmup(self) -> Dict[str, float]:
        """Compile every ladder bucket (× pinned device) up front — pool
        startup pays the whole trace/compile bill so steady-state serving
        never does. Returns {shape: seconds}; total time is ledgered
        under the ``serving/warmup`` profiler section."""
        prof = OpProfiler.get()
        timings: Dict[str, float] = {}
        seq_out: Dict[int, Optional[int]] = {}
        with prof.time_section("serving/warmup"):
            for shape in self.ladder.shapes(self._feat):
                for i in range(len(self._devices)):
                    t0 = time.perf_counter()
                    self._compile_bucket(shape, i)
                    # execute once too: the first run of a fresh
                    # executable pays allocator/dispatch setup that must
                    # not land on the first real request's latency
                    out = self._run_bucket(np.zeros(shape, self._in_dtype),
                                           i)
                    if i == 0 and self.ladder.seq_lens is not None:
                        seq_out[shape[1]] = (out.shape[1]
                                             if out.ndim >= 2 else None)
                    timings[f"{shape}@{i}" if len(self._devices) > 1
                            else str(shape)] = time.perf_counter() - t0
        if len(seq_out) >= 2:
            # ≥2 sequence rungs disambiguate per-timestep outputs (dim 1
            # tracks the padded length) from pooled ones (constant dim 1
            # that may coincide with ONE rung); a single rung stays on
            # the dispatch-time shape heuristic
            # graftlint: disable=lock-discipline -- startup phase: warmup
            # completes before the pool serves; _warm below is the fence
            self._seq_out_per_timestep = all(w == t
                                             for t, w in seq_out.items())
        # graftlint: disable=lock-discipline -- startup publication:
        # workers only consult the trace alarm once _warm flips, and both
        # stores happen-before any dispatch observes _warm=True
        self._traces_seen = self._trace_cell[0]
        # graftlint: disable=lock-discipline -- same startup publication
        self._warm = True
        # HBM watermark: the warmup just materialized every bucket
        # executable + per-device param copies — the serving tier's
        # steady-state memory footprint starts here
        xprof.memory_watermark("serving_warmup")
        return timings

    def _run_bucket(self, padded: np.ndarray, dev_idx: int = 0,
                    worker_id: Optional[int] = None) -> np.ndarray:
        exe = self._compile_bucket(tuple(padded.shape),
                                   dev_idx % len(self._devices))
        if exe is None:                       # generic-model fallback
            out = self.model.output(padded)
            out = out[0] if isinstance(out, list) else out
            return out.to_numpy()
        params, states = self._params_for(worker_id,
                                          dev_idx % len(self._devices))
        return np.asarray(exe(params, states,
                              padded.astype(self._in_dtype, copy=False),
                              self._key))

    def _run(self, batch: np.ndarray) -> NDArray:
        """Single-batch path (health probes, sequential mode): the same
        bucket executables, padded and sliced like any served request."""
        n = batch.shape[0]
        bucket = self.ladder.bucket_batch(n)
        if bucket is None:
            return super()._run(batch)        # oversize probe: direct
        padded, _w = pad_rows(batch, bucket)
        return NDArray(self._run_bucket(padded)[:n])

    # --- request admission ---------------------------------------------
    def output_async(self, x, slo_class: Optional[str] = None) -> Future:
        """Admit one request (see the module docstring's admission rule).
        Oversize rejections, ladder violations and SLO-class sheds
        (:class:`Overloaded` — brownout or queue budget, HTTP 429) raise
        SYNCHRONOUSLY — nothing is queued; every admitted request
        resolves through its future (deadline-bounded via
        :meth:`output`). ``slo_class`` names the request's admission
        class when classes are configured; ``None`` takes the default
        class."""
        arr = np.asarray(x.value if isinstance(x, NDArray) else x)
        if arr.ndim != len(self._feat) + 1:
            raise ValueError(
                f"request rank {arr.ndim} does not match the serving "
                f"input shape (batch, *{self._feat})")
        if arr.dtype != self._in_dtype:
            arr = arr.astype(self._in_dtype)
        prof = OpProfiler.get()
        with self._lock:
            # the documented serving REQUEST ordinal (0, 1, 2, ... per
            # output_async call) — distinct from _req_seq, which ticks
            # once per queued CHUNK and would leave enqueue-drill
            # indices unreachable for split requests
            admit_seq = self._admit_seq
            self._admit_seq += 1
        cls = None
        if self._adm is not None:
            cls = self._adm.resolve(slo_class)
            try:
                # the admission drill site (request ordinal): `slow`
                # stalls the decision, `transient` forces THIS request
                # shed — the deterministic 429 drill
                faultinject.fault_point("serving/admission", admit_seq)
            except faultinject.TransientFault as e:
                AdmissionController.count_shed(cls.name)
                raise Overloaded(
                    f"injected admission fault shed request {admit_seq} "
                    f"(class {cls.name!r})", cls.name, "fault",
                    self._adm.retry_after_s()) from e
        elif slo_class is not None:
            raise ValueError(
                f"slo_class={slo_class!r} given but no SLO classes are "
                f"configured (Builder.slo_classes)")
        t_real = None
        if self.ladder.seq_lens is not None:
            t = int(arr.shape[1])
            tb = self.ladder.bucket_seq(t)    # oversize seq: raises
            if arr.shape[2:] != self._feat[1:]:
                raise ValueError(
                    f"request feature shape {arr.shape[2:]} does not "
                    f"match the serving input shape {self._feat[1:]}")
            if tb != t:
                arr, _w = pad_rows(arr, tb, axis=1)
                prof.count("serving/seq_padded")
            t_real = t
        elif arr.shape[1:] != self._feat:
            raise ValueError(
                f"request feature shape {arr.shape[1:]} does not match "
                f"the serving input shape {self._feat}")
        try:
            chunks = self.ladder.admit(arr.shape[0])
        except OversizeRequest:
            prof.count("serving/oversize_rejected")
            raise
        if cls is not None:
            self._adm.admit(cls, len(chunks))     # Overloaded: sheds here;
            #                                       reserves the chunk slots
        try:
            fired = faultinject.fault_point("serving/enqueue", admit_seq)
            del fired  # advisory kinds have no enqueue-side meaning (yet)
        except BaseException:
            if cls is not None:     # reservation must not leak on a drill
                self._adm.release(cls.name, len(chunks))
            raise
        slo = cls.name if cls is not None else None
        if len(chunks) == 1:
            return self._submit(arr, t_real, slo=slo)
        prof.count("serving/oversize_split")
        futs, off = [], 0
        for c in chunks:
            futs.append(self._submit(arr[off:off + c], t_real, slo=slo))
            off += c
        return self._aggregate(futs)

    def _submit(self, arr: np.ndarray, t_real: Optional[int],
                slo: Optional[str] = None) -> Future:
        fut: Future = Future()
        if slo is not None:
            # the slot was RESERVED in admit(); the done-callback returns
            # it on every resolution path (result, batch error, requeue
            # exhaustion, the fast-fail exits just below, shutdown — a
            # callback added after set_exception fires immediately), so
            # the per-class budget can never leak
            fut.add_done_callback(
                lambda f, _n=slo: self._adm.note_done(_n))
        if self._shutdown:
            fut.set_exception(RuntimeError(
                "ServingEngine is shut down; no replicas will serve this "
                "request"))
            return fut
        if self.alive_replicas() == 0:
            fut.set_exception(RuntimeError(
                "all serving replicas have been retired; a resurrection "
                "may be pending — retry, or rebuild the engine"))
            return fut
        with self._lock:
            seq = self._req_seq
            self._req_seq += 1
            depth = self._queue.qsize() + 1
        self._qwin_update(depth)
        self._publish_queue_gauges()
        # request lifecycle, leg 1 of enqueue → batch → dispatch → reply;
        # the request ordinal IS the correlation id, so one grep follows
        # a request through replica deaths and requeues. Emitted BEFORE
        # the queue put: once a worker can see the request, its batch/
        # reply events must not be able to precede this one. Guarded
        # like legs 2/4: per-request kwargs stay off the disabled path
        if flightrec.enabled():
            flightrec.event("serving/enqueue", corr=f"req{seq}", req=seq,
                            rows=int(arr.shape[0]))
        self._enqueue(_Request(arr, fut, seq, time.monotonic(),
                               t_real=t_real, slo=slo))
        return fut

    def _aggregate(self, futs: List[Future]) -> Future:
        """Recombine a split oversize request: chunk results concatenate
        in submission order; the first chunk failure fails the whole
        request (partial answers are worse than retried ones)."""
        parent: Future = Future()
        parent.enqueued_at = min(getattr(f, "enqueued_at", time.monotonic())
                                 for f in futs)
        remaining = [len(futs)]
        lock = threading.Lock()

        def one_done(f: Future) -> None:
            with lock:
                if parent.done():
                    return
                exc = f.exception()
                if exc is not None:
                    parent.set_exception(exc)
                    return
                remaining[0] -= 1
                if remaining[0]:
                    return
            parts = [fu.result().to_numpy() for fu in futs]
            parent.set_result(NDArray(np.concatenate(parts, axis=0)))

        for f in futs:
            f.add_done_callback(one_done)
        return parent

    # --- load signals ---------------------------------------------------
    def _qwin_update(self, depth: Optional[int] = None) -> int:
        """Roll the two-window queue-depth high-water state (and fold in
        a new sample); returns the current WINDOWED high-water mark —
        max over the current and previous windows, so it decays to 0
        within ~2 windows of the backlog clearing (the scale-DOWN-capable
        signal the old only-rising fleet gauge could never be). The
        lifetime maximum is kept separately (:attr:`queue_depth_peak`)."""
        now = time.monotonic()
        with self._lock:
            elapsed = now - self._qwin_start
            if elapsed >= 2 * self._qwin_s:
                self._qwin_prev = 0
                self._qwin_max = 0
                self._qwin_start = now
            elif elapsed >= self._qwin_s:
                self._qwin_prev = self._qwin_max
                self._qwin_max = 0
                self._qwin_start = now
            if depth is not None:
                if depth > self._qwin_max:
                    self._qwin_max = depth
                if depth > self._q_peak:
                    self._q_peak = depth
            return max(self._qwin_max, self._qwin_prev)

    def queue_depth_hwm(self) -> int:
        """The decaying/windowed queue-depth high-water mark."""
        return self._qwin_update()

    @property
    def queue_depth_peak(self) -> int:
        """Lifetime queue-depth maximum (only ever rises)."""
        return self._q_peak

    def _publish_queue_gauges(self) -> None:
        """Fleet gauges: ``serving/queue_depth_hwm`` = max WINDOWED
        high-water over live engines (falls when backlogs clear);
        ``serving/queue_depth_peak`` = lifetime fleet max (only rises).
        Computed outside any engine lock — each read takes its owner's."""
        prof = OpProfiler.get()
        win, peak = 0, 0
        for e in list(_ENGINES):
            win = max(win, e.queue_depth_hwm())
            peak = max(peak, e._q_peak)
        prof.gauge("serving/queue_depth_hwm", win)
        if peak > prof.counter_value("serving/queue_depth_peak"):
            prof.gauge("serving/queue_depth_peak", peak)

    def idle_seconds(self) -> float:
        """Seconds since the last batch dispatch (autoscaler scale-down
        signal)."""
        return time.monotonic() - self._last_dispatch_t

    def recent_p99_ms(self, window_s: float = 5.0,
                      min_samples: int = 5) -> Optional[float]:
        """p99 latency over requests completed in the trailing window
        (all classes) — the autoscaler's reactive latency signal; the
        engine-lifetime rolling quantiles stay in
        :meth:`latency_stats`."""
        now = time.monotonic()
        with self._lat_lock:
            vals = [lat for t, lat in self._lat_recent
                    if now - t <= window_s]
        if len(vals) < min_samples:
            return None
        return float(np.percentile(np.asarray(vals) * 1e3, 99))

    def _class_recent_p99(self, name: str, window_s: float = 5.0,
                          min_samples: int = 5) -> Optional[float]:
        now = time.monotonic()
        with self._lat_lock:
            dq = self._class_lats.get(name)
            vals = ([lat for t, lat in dq if now - t <= window_s]
                    if dq else [])
        if len(vals) < min_samples:
            return None
        return float(np.percentile(np.asarray(vals) * 1e3, 99))

    def class_recent_p99(self, name: str, window_s: float = 5.0,
                         min_samples: int = 5) -> Optional[float]:
        """Public windowed per-class p99 (ms) — the watchtower's
        latency-SLO signal; None until ``min_samples`` land in the
        window."""
        return self._class_recent_p99(name, window_s=window_s,
                                      min_samples=min_samples)

    def slo_classes(self) -> List[SLOClass]:
        """The configured SLO classes, highest priority first (empty for
        an unclassified engine)."""
        if self._adm is None:
            return []
        return list(reversed(self._adm.by_shed_order))

    def class_latency_stats(self) -> Dict[str, Dict[str, float]]:
        """Rolling per-SLO-class p50/p99 over each class's last ≤2048
        served requests, in ms — the engine-wide window alone cannot
        price a non-top class's burn rate."""
        with self._lat_lock:
            per_class = {name: [lat for _, lat in dq]
                         for name, dq in self._class_lats.items() if dq}
        out: Dict[str, Dict[str, float]] = {}
        for name, vals in per_class.items():
            arr = np.asarray(vals) * 1e3
            out[name] = {"window": len(vals),
                         "p50_ms": float(np.percentile(arr, 50)),
                         "p99_ms": float(np.percentile(arr, 99))}
        return out

    def _on_scaled_out(self, worker_id: int) -> None:
        """A worker exiting via scale-down frees its pinned-device slot
        for whatever scale-up (or resurrection) comes next."""
        with self._lock:
            dev = self._dev_of.pop(worker_id, None)
            if dev is not None:
                self._dev_free.append(dev)

    # --- continuous-batching drain --------------------------------------
    def _next_request(self, timeout: float) -> Optional[_Request]:
        with self._stash_lock:
            if self._stashq:
                return self._stashq.popleft()
        try:
            return self._queue.get(timeout=max(0.0, timeout))
        except queue.Empty:
            return None

    def _stash(self, req: _Request) -> None:
        """Hold a request this batch cannot take (bucket overflow or a
        non-batch-shape mismatch) for the NEXT batch — stashed requests
        outrank the queue, so nothing is starved or reordered past one
        batch."""
        with self._stash_lock:
            self._stashq.append(req)

    def _drain(self, worker_id: int) -> None:
        prof = OpProfiler.get()
        with self._lock:
            if worker_id not in self._dev_of:
                # claim a pinned-device slot: a retired worker's freed
                # slot first (the replacement takes over its chip),
                # round-robin otherwise (the startup pool)
                self._dev_of[worker_id] = (
                    self._dev_free.pop() if self._dev_free
                    else worker_id % len(self._devices))
        while not self._shutdown:
            if self._take_scale_down(worker_id):
                return     # scaled out at a batch boundary, nothing held
            first = self._next_request(0.1)
            if first is None:
                continue
            batch, rows = [first], first.n
            shape_tail = first.arr.shape[1:]
            # fill toward the LARGEST bucket under one absolute deadline
            # (continuous batching: the window caps added latency, the
            # ladder caps the fill)
            deadline = time.monotonic() + self.max_wait_s
            while rows < self.ladder.max_batch:
                nxt = self._next_request(deadline - time.monotonic())
                if nxt is None:
                    break
                if (nxt.arr.shape[1:] != shape_tail
                        or rows + nxt.n > self.ladder.max_batch):
                    self._stash(nxt)
                    break
                batch.append(nxt)
                rows += nxt.n
            with self._lock:
                self._busy += 1
            try:
                self._dispatch(worker_id, batch, rows, prof)
            except faultinject.DeadReplicaFault:
                return          # replica retired inside _dispatch
            finally:
                with self._lock:
                    self._busy -= 1
        with self._lock:
            self._alive -= 1

    def _dispatch(self, worker_id: int, batch: List[_Request], rows: int,
                  prof) -> None:
        with self._lock:
            ordinal = self._batch_seq
            self._batch_seq += 1
            self._last_dispatch_t = time.monotonic()
        # leg 2: the batch formed by continuous batching — emitted BEFORE
        # the dispatch drill site, so a killed dispatch still shows which
        # requests were aboard (the incident-reconstruction contract).
        # enabled() guard: the reqs list is per-batch hot-path allocation
        # that must not be built just to be discarded
        if flightrec.enabled():
            flightrec.event("serving/batch", batch=ordinal, rows=rows,
                            worker=worker_id,
                            reqs=[int(r.seq) for r in batch])
        try:
            faultinject.fault_point("serving/dispatch", ordinal)
        except faultinject.TransientFault:
            # one deterministic requeue-and-retry (drill for the retry
            # path); the requests keep their queue-entry timestamps
            self._requeue(batch, faultinject.TransientFault(
                "serving dispatch retry budget exhausted"))
            return
        except faultinject.DeadReplicaFault as e:
            self._retire_serving(worker_id, e, batch)
            raise
        bucket = self.ladder.bucket_batch(rows)
        merged = (batch[0].arr if len(batch) == 1
                  else np.concatenate([r.arr for r in batch], axis=0))
        padded, _w = pad_rows(merged, bucket)
        try:
            with prof.time_section("serving/dispatch"):
                result = self._run_bucket(
                    padded, self._dev_of.get(worker_id, 0),
                    worker_id=worker_id)
        except faultinject.DeadReplicaFault as e:
            self._retire_serving(worker_id, e, batch)
            raise
        except Exception as e:
            prof.count("serving/batch_errors")
            self._note_canary_result(worker_id, error=True)
            for r in batch:
                if not r.fut.done():
                    r.fut.set_exception(e)
            return
        except BaseException as e:
            # bookkeeping parity with ParallelInference._serve_batch: an
            # injected SimulatedCrash must still retire cleanly
            self._retire(worker_id, e, [r.fut for r in batch])
            raise
        # graftlint: disable=lock-discipline -- last-write-wins slot: one
        # atomic reference store of a fresh owning copy (same contract as
        # ParallelInference._serve_batch)
        self._probe_input = padded[:1].copy()
        t_done = time.monotonic()
        t_pad = padded.shape[1] if padded.ndim >= 2 else None
        off = 0
        lats = []
        for r in batch:
            out = result[off:off + r.n]
            off += r.n
            if (r.t_real is not None and out.ndim >= 2
                    and out.shape[1] == t_pad
                    and self._seq_out_per_timestep is not False):
                # per-timestep output: slice the sequence pad back off.
                # warmup probes the ladder to rule OUT pooled outputs
                # whose width merely coincides with one sequence rung
                out = out[:, :r.t_real]
            lats.append(t_done - r.t_enq)
            r.fut.set_result(NDArray(out))
            # leg 4 (leg 3, the dispatch itself, is the profiler's
            # serving/dispatch section — an X lane in the Chrome trace);
            # guarded: per-request latency math + kwargs stay off the
            # disabled hot path
            if flightrec.enabled():
                flightrec.event(
                    "serving/reply", corr=f"req{r.seq}", req=int(r.seq),
                    batch=ordinal,
                    latency_ms=round((t_done - r.t_enq) * 1e3, 3))
        with self._lat_lock:
            self._latencies.extend(lats)
            self._lat_recent.extend((t_done, lat) for lat in lats)
            for r, lat in zip(batch, lats):
                if r.slo is not None:
                    self._class_lats.setdefault(
                        r.slo, collections.deque(maxlen=2048)
                    ).append((t_done, lat))
        self._note_canary_result(worker_id, lats=lats)
        prof.count("serving/requests", len(batch))
        prof.count("serving/batches")
        prof.count("serving/rows", rows)
        prof.count("serving/pad_rows", bucket - rows)
        prof.count("serving/capacity_rows", bucket)
        if self._warm:
            traces = self._trace_cell[0]
            if traces > self._traces_seen:
                # the one thing steady-state serving must never do. Under
                # the pool lock: concurrent workers racing the unlocked
                # read-modify-write would double-count the alarm delta
                with self._lock:
                    delta = traces - self._traces_seen
                    if delta > 0:
                        prof.count("serving/traces_after_warmup", delta)
                        self._traces_seen = traces
                if delta > 0:
                    logger.warning("serving traced AFTER warmup (shape "
                                   "%s) — a bucket escaped the warmup "
                                   "set", padded.shape)

    def _requeue(self, batch: List[_Request], exhausted_exc) -> None:
        prof = OpProfiler.get()
        for r in batch:
            r.attempts += 1
            if r.attempts > self.max_requeues:
                if not r.fut.done():
                    r.fut.set_exception(exhausted_exc)
                continue
            try:
                self._queue.put_nowait(r)
            except queue.Full:
                if not r.fut.done():
                    r.fut.set_exception(TimeoutError(
                        "serving queue full while requeueing a request "
                        "from a retired replica"))
                continue
            # only a requeue that actually landed is a ride-through
            prof.count("serving/requeued")

    def _retire_serving(self, worker_id: int, exc: BaseException,
                        batch: List[_Request]) -> None:
        """Retirement TRANSPARENT to in-flight requests: requeue the
        dying replica's batch (bounded by ``max_requeues``) so surviving
        replicas serve it, then run the pool's shared retirement
        bookkeeping (which fails whatever is queued if this was the LAST
        replica — bounded latency outranks transparency — and schedules
        resurrection)."""
        flightrec.event("serving/retire", severity="warn",
                        worker=worker_id, error=repr(exc)[:200],
                        requeued=[int(r.seq) for r in batch])
        self._requeue(batch, exc)
        with self._lock:
            # free the dead worker's pinned-device slot for its
            # resurrected replacement
            dev = self._dev_of.pop(worker_id, None)
            if dev is not None:
                self._dev_free.append(dev)
        self._retire(worker_id, exc, [])      # casualties already failed

    def _probe(self) -> None:
        """Resurrection health probe on the device slot the REPLACEMENT
        will claim — the base class probes through ``_run``, which always
        dispatches on device 0 and would validate a healthy chip while
        refilling a dead one's slot."""
        faultinject.fault_point("inference/probe", self._next_probe_seq())
        probe = self._probe_input
        if probe is None:
            return
        with self._lock:
            dev = self._dev_free[-1] if self._dev_free else 0
        bucket = self.ladder.bucket_batch(probe.shape[0])
        if bucket is None:
            self._run(probe)
            return
        padded, _w = pad_rows(probe, bucket)
        self._run_bucket(padded, dev)

    # --- canaried train-to-serve handoff --------------------------------
    _CANARY_PHASES = {"idle": 0, "canary": 1, "confirm": 2}

    def _note_canary_result(self, worker_id: int, lats: Sequence[float] = (),
                            error: bool = False) -> None:
        """Feed one dispatch outcome into the live publication's SLO
        evidence: during the canary phase only the canary replica's
        samples count; after promote every replica serves the candidate
        weights, so the whole fleet's do."""
        can = self._canary
        if can is None:
            return
        with self._lock:
            can = self._canary
            if can is None:
                return
            if can["phase"] == "canary" and can.get("worker") != worker_id:
                return
            if error:
                can["errors"] += 1
            else:
                can["lats"].extend(lats)

    def _set_canary_phase(self, phase: str) -> None:
        OpProfiler.get().gauge("serving/canary_phase",
                               self._CANARY_PHASES[phase])

    def publish_checkpoint(self, path: str, canary_window_s: float = 3.0,
                           confirm_window_s: Optional[float] = None,
                           check_interval_s: float = 0.25,
                           min_samples: int = 8,
                           violation_p99_ms: Optional[float] = None
                           ) -> PublishHandle:
        """Canaried train-to-serve handoff: load retrained weights from a
        committed checkpoint and hot-swap them — zero recompiles, the AOT
        executables take params as arguments — onto ONE canary replica.
        After an SLO-clean ``canary_window_s`` the weights PROMOTE
        fleet-wide; a ``confirm_window_s`` watch follows, and any
        violation (serving errors on the new weights, p99 over
        ``violation_p99_ms`` — default: the top SLO class's budget — or
        an injected ``serving/promote`` fault) AUTO-ROLLBACKS by
        restoring the prior param set bitwise (the exact prior device
        arrays, not a re-cast copy). When a p99 budget is in force the
        promote additionally REQUIRES ``min_samples`` of canary evidence
        — a canary replica that served nothing (retired, scaled out, or
        simply idle) rolls back rather than promoting untested weights;
        budget-less publications keep the time-based promote with
        error-only violation detection. The returned handle's ``corr``
        id (``pub<N>``) chains train-commit -> canary -> promote/
        rollback in the flight recorder. One publication may be in
        flight at a time; ``refresh_params()`` during a publication is
        refused for the same reason."""
        if not self._aot:
            raise RuntimeError(
                "publish_checkpoint needs an AOT-served model (the "
                "generic-model fallback serves through model.output and "
                "owns its own weights)")
        # claim the publication slot FIRST (a refused publish must not
        # burn a pub ordinal — drills arm fault plans against
        # next_publication_ordinal() — nor pay the checkpoint read)
        with self._lock:
            if self._canary is not None:
                raise RuntimeError(
                    f"publication {self._canary['corr']} is still in "
                    f"flight (phase {self._canary['phase']!r})")
            self._canary = {"phase": "loading", "corr": "pending",
                            "worker": None, "errors": 0, "lats": []}
        try:
            from ..util.checkpoint import read_checkpoint_params

            params, states = read_checkpoint_params(
                path, self.model._params, self.model._states)
            params, states = self._cast_serving(params, states)
            new_placed = self._place_params(params, states)
            # the canary replica: any live worker that has claimed a
            # device slot (they all do on their first drain iteration)
            deadline = time.monotonic() + 5.0
            worker = None
            while time.monotonic() < deadline:
                with self._lock:
                    if self._dev_of:
                        worker = next(iter(self._dev_of))
                        break
                time.sleep(0.01)
            if worker is None:
                raise RuntimeError("no live serving worker to canary "
                                   "onto")
        except BaseException:
            with self._lock:
                self._canary = None
            raise
        prof = OpProfiler.get()
        with _pub_lock:
            ordinal = _pub_next[0]
            _pub_next[0] += 1
        with self._lock:
            corr = f"pub{ordinal}"
            handle = PublishHandle(corr, path)
            slot = self._dev_of.get(worker, 0)
            budget = violation_p99_ms
            if budget is None and self._adm is not None:
                budget = self._adm.top.p99_ms
            self._canary = {
                "ordinal": ordinal, "corr": corr,
                "file": os.path.basename(path), "phase": "canary",
                "worker": worker, "canary_params": new_placed[slot],
                "new": new_placed, "prior": dict(self._dev_params),
                "lats": [], "errors": 0, "budget_ms": budget,
                "min_samples": int(min_samples), "handle": handle,
            }
        prof.count("serving/publications")
        self._set_canary_phase("canary")
        flightrec.event("serving/canary", corr=corr,
                        file=os.path.basename(path), worker=worker,
                        window_s=canary_window_s,
                        budget_ms=budget)
        t = threading.Thread(
            target=self._canary_monitor,
            args=(canary_window_s,
                  canary_window_s if confirm_window_s is None
                  else confirm_window_s,
                  max(0.01, float(check_interval_s))),
            daemon=True, name=f"dl4j-serving-canary-{ordinal}")
        with self._lock:
            # only one publication is ever in flight — drop the finished
            # monitors so a long-lived engine with periodic publishes
            # does not accumulate dead Thread objects
            self._pub_threads = [x for x in self._pub_threads
                                 if x.is_alive()]
            self._pub_threads.append(t)
        t.start()
        return handle

    def _canary_monitor(self, canary_window_s: float,
                        confirm_window_s: float, interval_s: float) -> None:
        with self._lock:
            can = self._canary
        if can is None:
            return
        deadline = time.monotonic() + canary_window_s
        while time.monotonic() < deadline:
            if self._shutdown:
                self._rollback(can, "canary", "engine shutdown")
                return
            time.sleep(interval_s)
            v = self._canary_violation(can)
            if v:
                self._rollback(can, "canary", v)
                return
        with self._lock:
            evidence = len(can["lats"]) + can["errors"]
            budget = can["budget_ms"]
        if budget is not None and evidence < can["min_samples"]:
            # an SLO budget is in force but the canary replica produced
            # no judgeable evidence (no traffic reached it — e.g. it was
            # retired or scaled out mid-window): promoting would ship
            # UNTESTED weights, the exact failure the canary exists to
            # prevent. Roll back instead; error-only publications (no
            # budget) keep their time-based promote.
            self._rollback(can, "canary",
                           f"insufficient canary evidence: {evidence} "
                           f"sample(s), need {can['min_samples']}")
            return
        # SLO-clean canary window: PROMOTE fleet-wide (atomic dict swap —
        # in-flight batches finish on whichever complete set they read)
        with self._lock:
            self._dev_params = can["new"]
            can["phase"] = "confirm"
            can["lats"] = []         # confirm judges fresh fleet evidence
            can["errors"] = 0
        can["handle"].phase = "confirm"
        self._set_canary_phase("confirm")
        flightrec.event("serving/promote", corr=can["corr"],
                        file=can["file"], replicas=self.alive_replicas())
        # post-promote fleet verify: every slot's freshly-installed param
        # copy must digest bitwise-identical. A copy corrupted in transit
        # (device_put, HBM) would otherwise serve divergent answers from
        # one replica until the NEXT publication; the digest read is one
        # batched host readback per slot, off the request path.
        prof = OpProfiler.get()
        prof.count("integrity/publish_checks")
        digests = {slot: _integ.host_fingerprint(entry[0])
                   for slot, entry in can["new"].items()}
        counts = collections.Counter(digests.values())
        if len(counts) > 1:
            majority = counts.most_common(1)[0][0]
            bad = sorted(s for s, d in digests.items() if d != majority)
            prof.count("integrity/publish_divergences")
            self._rollback(can, "confirm",
                           f"post-promote fingerprint mismatch on "
                           f"slot(s) {bad}")
            return
        deadline = time.monotonic() + confirm_window_s
        while time.monotonic() < deadline:
            if self._shutdown:
                self._rollback(can, "confirm", "engine shutdown")
                return
            time.sleep(interval_s)
            try:
                # the forced-violation drill site: a transient here is
                # "the promoted weights are violating" (publication
                # ordinal-indexed, so drills pick their publication)
                faultinject.fault_point("serving/promote", can["ordinal"])
            except faultinject.TransientFault as e:
                self._rollback(can, "confirm", f"injected violation: {e}")
                return
            v = self._canary_violation(can)
            if v:
                self._rollback(can, "confirm", v)
                return
        with self._lock:
            self._canary = None
        prof = OpProfiler.get()
        prof.count("serving/promotions")
        self._set_canary_phase("idle")
        can["handle"]._finish("promoted")
        logger.info("serving publication %s promoted fleet-wide (%s)",
                    can["corr"], can["file"])

    def _canary_violation(self, can: Dict[str, Any]) -> Optional[str]:
        with self._lock:
            errors = can["errors"]
            lats = list(can["lats"])
            budget = can["budget_ms"]
            need = can["min_samples"]
        if errors:
            return f"{errors} serving error(s) on the candidate weights"
        if budget is not None and len(lats) >= need:
            p99 = float(np.percentile(np.asarray(lats) * 1e3, 99))
            if p99 > budget:
                return (f"p99 {p99:.1f}ms over the {budget:.0f}ms budget "
                        f"({len(lats)} samples)")
        return None

    def _rollback(self, can: Dict[str, Any], phase: str,
                  reason: str) -> None:
        """Restore the prior param set BITWISE: the rollback re-installs
        the exact prior device arrays (kept, not re-derived), so a
        post-rollback read is indistinguishable from never publishing."""
        with self._lock:
            self._dev_params = can["prior"]
            self._canary = None
        prof = OpProfiler.get()
        prof.count("serving/rollbacks")
        self._set_canary_phase("idle")
        flightrec.event("serving/rollback", severity="warn",
                        corr=can["corr"], file=can["file"], phase=phase,
                        reason=str(reason)[:200])
        logger.warning("serving publication %s rolled back during %s: %s",
                       can["corr"], phase, reason)
        can["handle"]._finish("rolled_back")

    def shutdown(self, drain_timeout_s: float = 2.0) -> None:
        super().shutdown(drain_timeout_s)
        # canary monitors observe _shutdown and resolve their handles
        for t in list(self._pub_threads):
            t.join(timeout=1.0)
        bt = self._brownout._thread if self._brownout else None
        if bt is not None:
            bt.join(timeout=1.0)
        # out of the health census: a shut-down engine must not report
        # itself (or its stale latency window) as live serving capacity
        _ENGINES.discard(self)

    def _fail_queued(self, exc) -> int:
        """The stash is queue too: a request held for the next batch must
        fail with the rest when the pool dies or shuts down — the base
        contract ('no waiter is left hanging') covers both stores."""
        n = super()._fail_queued(exc)
        while True:
            with self._stash_lock:
                if not self._stashq:
                    return n
                req = self._stashq.popleft()
            if not req.fut.done():
                req.fut.set_exception(exc)
                n += 1

    # --- stats ----------------------------------------------------------
    def latency_stats(self) -> Dict[str, float]:
        """Rolling p50/p99 over the last ≤4096 served requests, in ms."""
        with self._lat_lock:
            window = list(self._latencies)
        if not window:
            return {"window": 0}
        arr = np.asarray(window) * 1e3
        return {"window": len(window),
                "p50_ms": float(np.percentile(arr, 50)),
                "p99_ms": float(np.percentile(arr, 99)),
                "max_ms": float(arr.max())}

    def serving_stats(self) -> Dict[str, Any]:
        """This engine's census for :func:`serving_health`: pool
        live/retired/resurrected, bucket/warmup state, the windowed
        queue-depth high-water + lifetime peak, admission/brownout state,
        the canary phase, rolling latency quantiles."""
        out: Dict[str, Any] = dict(self.pool_stats())
        out.update(self.latency_stats())
        cl = self.class_latency_stats()
        if cl:
            out["class_latency"] = cl
        with self._exec_lock:
            out["buckets_compiled"] = len(self._exec)
        out["warm"] = self._warm
        out["queue_depth_hwm"] = self.queue_depth_hwm()   # windowed
        out["queue_depth_peak"] = self._q_peak            # lifetime
        out["bf16"] = self._bf16
        if self._adm is not None:
            out["admission"] = self._adm.stats()
        with self._lock:
            can = self._canary
            out["canary_phase"] = can["phase"] if can else "idle"
            if can:
                out["canary_corr"] = can["corr"]
        self._publish_queue_gauges()    # reads refresh the fleet gauges
        return out


def serving_health() -> Dict[str, Any]:
    """The ``/api/health`` "serving" section: the profiler's
    ``serving_stats()`` ledger (requests, batches, fill ratio, pad waste,
    traces-after-warmup, dispatch/warmup time) merged with a per-engine
    census and the rolling latency quantiles only the engines hold."""
    out: Dict[str, Any] = dict(OpProfiler.get().serving_stats())
    engines = list(_ENGINES)
    out["engines"] = len(engines)
    if engines:
        out["engine_stats"] = [e.serving_stats() for e in engines]
        samples: List[float] = []
        class_samples: Dict[str, List[float]] = {}
        for e in engines:
            with e._lat_lock:
                samples.extend(e._latencies)
                for name, dq in e._class_lats.items():
                    class_samples.setdefault(name, []).extend(
                        lat for _, lat in dq)
        if samples:
            arr = np.asarray(samples) * 1e3
            out["latency_p50_ms"] = float(np.percentile(arr, 50))
            out["latency_p99_ms"] = float(np.percentile(arr, 99))
        if class_samples:
            # fleet-wide per-SLO-class rolling quantiles: the signal the
            # watchtower latency SLOs and dl4j_serving_latency_ms{class=}
            # price burn rates from
            out["class_latency"] = {
                name: {"window": len(vals),
                       "p50_ms": float(np.percentile(
                           np.asarray(vals) * 1e3, 50)),
                       "p99_ms": float(np.percentile(
                           np.asarray(vals) * 1e3, 99))}
                for name, vals in class_samples.items() if vals}
    return out
