"""Closed-loop serving autoscaler (ISSUE 11; ROADMAP item 4).

PR 6 made the worker set elastic (online resize, no process restart) and
PR 7's serving tier already EXPORTS every signal a controller needs —
queue-depth high-water, rolling p99, bucket fill ratio. This module
closes the loop: a controller thread samples those signals at a fixed
cadence and drives :meth:`ServingEngine.scale_to` — replicas grow on a
traffic spike and shrink when idle, with cooldowns and min/max bounds so
the controller itself cannot oscillate the fleet. Zero process restarts:
scale-up spawns drain threads against the already-compiled AOT bucket
executables (recompiles stay at one per bucket x device slot at ANY
replica count), scale-down retires surplus workers at a batch boundary.

Control law (:meth:`Autoscaler.decide` — a pure function, so tests and
drills exercise it without threads):

- **Scale UP** when the decaying/windowed queue-depth HWM crosses
  ``up_queue_depth`` OR recent p99 crosses ``up_p99_frac`` x the top SLO
  class's budget (latency pressure before the queue visibly backs up),
  stepping ``step`` replicas toward ``max_workers``, at most once per
  ``cooldown_up_s``.
- **Scale DOWN** one replica toward ``min_workers`` when the windowed
  HWM has decayed to ``down_queue_depth`` AND the engine has been idle
  ``down_idle_s`` (or the recent bucket fill ratio sits under
  ``down_fill_frac`` — capacity provably exceeds demand), at most once
  per ``cooldown_down_s`` and never within ``cooldown_down_s`` of a
  scale-up (a spike's tail must not trigger an immediate shrink).

Every scale decision is a flight-recorder ``autoscale/decide`` span
carrying its INPUT SIGNALS as attrs (the incident-reconstruction
contract: why did the fleet grow at 14:03?) plus an ``autoscale/scale``
instant with from/to/reason; held ticks are counters only. The
``autoscale/decide`` fault site makes a failed controller evaluation a
deterministic drill — a transient there skips one tick (counted), it
never kills the loop. State is exported three ways: ``autoscale/*``
counters + the ``autoscale/replicas`` gauge (Prometheus ``/api/metrics``
via the profiler's ledger list), ``profiler.autoscale_stats()``
(``/api/health``), and :meth:`Autoscaler.stats`.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional

from ..common import faultinject, flightrec
from ..common.profiler import OpProfiler
from .mesh import serving_capacity

logger = logging.getLogger("deeplearning4j_tpu")


class AutoscalePolicy:
    """Bounds and thresholds for the control law (module docstring).
    ``max_workers`` defaults to 2x the device count
    (:func:`mesh.serving_capacity`) — beyond that, replicas only contend
    for XLA streams that are already saturated."""

    def __init__(self, min_workers: int = 1,
                 max_workers: Optional[int] = None,
                 interval_s: float = 0.25,
                 up_queue_depth: int = 8,
                 up_p99_frac: float = 0.8,
                 down_queue_depth: int = 0,
                 down_idle_s: float = 2.0,
                 down_fill_frac: float = 0.25,
                 cooldown_up_s: float = 1.0,
                 cooldown_down_s: float = 3.0,
                 step: int = 1):
        self.min_workers = max(1, int(min_workers))
        self.max_workers = (int(max_workers) if max_workers is not None
                            else 2 * serving_capacity())
        if self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers {self.max_workers} < min_workers "
                f"{self.min_workers}")
        self.interval_s = max(0.01, float(interval_s))
        self.up_queue_depth = int(up_queue_depth)
        self.up_p99_frac = float(up_p99_frac)
        self.down_queue_depth = int(down_queue_depth)
        self.down_idle_s = float(down_idle_s)
        self.down_fill_frac = float(down_fill_frac)
        self.cooldown_up_s = float(cooldown_up_s)
        self.cooldown_down_s = float(cooldown_down_s)
        self.step = max(1, int(step))


class Autoscaler:
    """The controller: samples the engine's load signals every
    ``policy.interval_s`` and actuates ``engine.scale_to``. ``start()``
    runs it on a daemon thread; ``tick()`` is public so drills and tests
    drive single deterministic evaluations."""

    def __init__(self, engine, policy: Optional[AutoscalePolicy] = None):
        self.engine = engine
        self.policy = policy or AutoscalePolicy()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ticks = 0
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None
        self._prev_rows = 0
        self._prev_cap = 0

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "Autoscaler":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="dl4j-autoscaler")
        self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        with self._lock:
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.policy.interval_s):
            if getattr(self.engine, "_shutdown", False):
                return
            try:
                self.tick()
            except Exception:
                OpProfiler.get().count("autoscale/decide_errors")
                logger.warning("autoscale tick failed", exc_info=True)

    # -- signals ---------------------------------------------------------
    def _signals(self) -> Dict[str, Any]:
        eng = self.engine
        prof = OpProfiler.get()
        rows = prof.counter_value("serving/rows")
        cap = prof.counter_value("serving/capacity_rows")
        with self._lock:
            d_rows = rows - self._prev_rows
            d_cap = cap - self._prev_cap
            self._prev_rows = rows
            self._prev_cap = cap
        top_budget = None
        adm = getattr(eng, "_adm", None)
        if adm is not None:
            top_budget = adm.top.p99_ms
        return {
            "alive": eng.alive_replicas(),
            "queue_hwm": eng.queue_depth_hwm(),
            "p99_ms": eng.recent_p99_ms(),
            "top_budget_ms": top_budget,
            "idle_s": eng.idle_seconds(),
            "fill_ratio": (d_rows / d_cap) if d_cap else None,
        }

    # -- control law -----------------------------------------------------
    def decide(self, sig: Dict[str, Any], now: Optional[float] = None
               ) -> Dict[str, Any]:
        """The pure control law: signals -> {"target", "reason"}. A
        target equal to ``sig["alive"]`` means hold. Cooldown state is
        read but not written — :meth:`tick` commits it when it actuates."""
        p = self.policy
        now = time.monotonic() if now is None else now
        alive = sig["alive"]
        with self._lock:
            last_up, last_down = self._last_up_t, self._last_down_t
        hot_queue = sig["queue_hwm"] >= p.up_queue_depth
        hot_p99 = (sig["p99_ms"] is not None
                   and sig["top_budget_ms"] is not None
                   and sig["p99_ms"] >= p.up_p99_frac
                   * sig["top_budget_ms"])
        if (hot_queue or hot_p99) and alive < p.max_workers:
            if last_up is not None and now - last_up < p.cooldown_up_s:
                return {"target": alive, "reason": "cooldown_up"}
            return {"target": min(alive + p.step, p.max_workers),
                    "reason": ("queue_hwm=%d" % sig["queue_hwm"]
                               if hot_queue else
                               "p99=%.0fms" % sig["p99_ms"])}
        cold_queue = sig["queue_hwm"] <= p.down_queue_depth
        cold = cold_queue and (
            sig["idle_s"] >= p.down_idle_s
            or (sig["fill_ratio"] is not None
                and sig["fill_ratio"] < p.down_fill_frac))
        if cold and alive > p.min_workers:
            last_any = max(t for t in (last_up, last_down, -1e18)
                           if t is not None)
            if last_any > -1e17 and now - last_any < p.cooldown_down_s:
                return {"target": alive, "reason": "cooldown_down"}
            return {"target": max(alive - 1, p.min_workers),
                    "reason": ("idle=%.1fs" % sig["idle_s"]
                               if sig["idle_s"] >= p.down_idle_s
                               else "fill=%.2f" % sig["fill_ratio"])}
        return {"target": alive, "reason": "steady"}

    # -- one evaluation --------------------------------------------------
    def tick(self) -> Optional[int]:
        """One controller evaluation: sample, decide, actuate. Returns
        the new target when a scale action was taken, None on hold. The
        ``autoscale/decide`` fault site turns a failed evaluation into a
        deterministic drill: a transient skips THIS tick (counted under
        ``autoscale/decide_errors``) and the loop carries on."""
        prof = OpProfiler.get()
        with self._lock:
            ordinal = self._ticks
            self._ticks += 1
        prof.count("autoscale/ticks")
        try:
            faultinject.fault_point("autoscale/decide", ordinal)
        except faultinject.TransientFault:
            prof.count("autoscale/decide_errors")
            return None
        sig = self._signals()
        prof.gauge("autoscale/replicas", sig["alive"])
        decision = self.decide(sig)
        target = decision["target"]
        if target == sig["alive"]:
            prof.count("autoscale/held")
            return None
        now = time.monotonic()
        attrs = {k: (round(v, 3) if isinstance(v, float) else v)
                 for k, v in sig.items() if v is not None}
        # the decision IS the span: its inputs ride as attrs so the
        # timeline answers "why did the fleet resize" without logs
        with flightrec.span("autoscale/decide", severity="warn",
                            target=target, reason=decision["reason"],
                            **attrs):
            self.engine.scale_to(target, reason=decision["reason"])
        up = target > sig["alive"]
        prof.count("autoscale/scale_ups" if up else "autoscale/scale_downs")
        prof.gauge("autoscale/replicas", target)
        with self._lock:
            if up:
                self._last_up_t = now
            else:
                self._last_down_t = now
        flightrec.event("autoscale/scale", frm=sig["alive"], to=target,
                        reason=decision["reason"])
        logger.info("autoscaled %d -> %d (%s)", sig["alive"], target,
                    decision["reason"])
        return target

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            ticks = self._ticks
        out = dict(OpProfiler.get().autoscale_stats())
        out["ticks_local"] = ticks
        out["policy"] = {"min": self.policy.min_workers,
                        "max": self.policy.max_workers,
                        "interval_s": self.policy.interval_s}
        return out
