"""Fault-tolerant multi-process cluster runtime (control plane hardening).

Reference: the dl4j-scaleout bring-up path — ``VoidConfiguration``'s
``controllerAddress`` handshake plus the Aeron transport's heartbeat /
dead-node detection (SURVEY.md §2.4, §5.8). Here the data plane is XLA
collectives compiled into the step, so this module hardens only the
CONTROL plane around ``jax.distributed``:

- **bring-up with a deadline** (:meth:`ClusterRuntime.form`): bounded
  exponential-backoff retries around ``jax.distributed.initialize``,
  each attempt's timeout clipped to the remaining init deadline. A
  coordinator that never answers fails with :class:`ClusterInitError`
  naming the address, the ranks whose heartbeats DID report, and the
  attempt/elapsed counts — never a silent hang. On the CPU backend the
  bring-up auto-selects a cross-process collectives implementation
  (gloo/mpi) when the installed jaxlib ships one, so a multi-process
  CPU cluster actually computes instead of failing at the first psum.

- **rank heartbeats** (:meth:`ClusterRuntime.start_heartbeat`): a
  sidecar file per rank (``hb-rank<k>.json`` in the shared cluster
  directory) rewritten at a fixed cadence by a daemon thread,
  independent of collectives — a wedged rank is detectable by its
  heartbeat age even while the survivors are blocked in a psum.
  :func:`read_heartbeats` is the supervisor-side consumer.

- **barrier with a deadline** (:meth:`ClusterRuntime.barrier`): a
  token-file rendezvous (no collectives) that, on timeout, names
  exactly which ranks are missing and how stale each missing rank's
  heartbeat is, emits a ``cluster/barrier`` flight-recorder event,
  dumps this rank's blackbox, and raises :class:`BarrierTimeout`.

- **group checkpoint commit** (:meth:`ClusterRuntime
  .commit_group_checkpoint`): pre-commit barrier → rank-0 commits
  through the atomic ``util.checkpoint`` machinery (fenced by the
  manifest incarnation id, so a stale incarnation's writer can never
  tear a group commit) → post-commit publish barrier → non-zero ranks
  verify the manifest actually names the new generation before
  resuming.

- **per-rank blackboxes** (:meth:`ClusterRuntime.dump_rank_blackbox` /
  :func:`merge_rank_blackboxes`): each rank dumps its flight-recorder
  ring tagged with its rank + incarnation; the supervisor process
  joins them into one watchtower incident whose chain names the lost
  rank as cause (see ``distributed.supervise_processes``).

Everything here is shared-filesystem + stdlib: the control plane must
keep working precisely when the collective data plane is wedged.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..common import faultinject, flightrec
from ..common.profiler import OpProfiler

logger = logging.getLogger("deeplearning4j_tpu")

#: per-rank heartbeat sidecar file (in the shared cluster directory)
HEARTBEAT_PREFIX = "hb-rank"
#: per-rank flight-recorder dump (tagged with rank + incarnation)
BLACKBOX_PREFIX = "blackbox-rank"


class ClusterInitError(RuntimeError):
    """Cluster bring-up failed inside its deadline — carries the full
    diagnosis (coordinator address, ranks that did report a heartbeat,
    attempts, elapsed) instead of the silent hang a raw
    ``jax.distributed.initialize`` against a dead coordinator gives."""

    def __init__(self, message: str, *, coordinator: Optional[str] = None,
                 attempts: int = 0, elapsed_s: float = 0.0,
                 reported_ranks: Optional[List[int]] = None):
        super().__init__(message)
        self.coordinator = coordinator
        self.attempts = attempts
        self.elapsed_s = elapsed_s
        self.reported_ranks = list(reported_ranks or [])


class BarrierTimeout(RuntimeError):
    """A barrier deadline expired — names the missing ranks and each
    one's heartbeat staleness (``None`` = no heartbeat ever seen)."""

    def __init__(self, message: str, *, name: str, gen: int,
                 missing: List[int],
                 staleness: Dict[int, Optional[float]]):
        super().__init__(message)
        self.name = name
        self.gen = gen
        self.missing = list(missing)
        self.staleness = dict(staleness)


class GroupCommitError(RuntimeError):
    """A non-zero rank could not verify the group commit it was told
    was published — the manifest's newest intact generation does not
    match what rank 0 was supposed to have committed."""


# ---------------------------------------------------------------------------
# heartbeat files (supervisor-readable without any live collective)
# ---------------------------------------------------------------------------

def heartbeat_path(cluster_dir: str, rank: int) -> str:
    return os.path.join(cluster_dir, f"{HEARTBEAT_PREFIX}{rank}.json")


def read_heartbeats(cluster_dir: str) -> Dict[int, Dict[str, Any]]:
    """Every rank's last heartbeat: ``{rank: {age_s, pid, incarnation,
    seq, t_wall}}``. Ranks that never beat are absent. Readable by the
    supervisor (a different process) and by survivors naming a missing
    peer — wall-clock ages, since the writers are other processes."""
    out: Dict[int, Dict[str, Any]] = {}
    try:
        names = os.listdir(cluster_dir)
    except OSError:
        return out
    now = time.time()
    for f in names:
        if not (f.startswith(HEARTBEAT_PREFIX) and f.endswith(".json")):
            continue
        try:
            rank = int(f[len(HEARTBEAT_PREFIX):-len(".json")])
            with open(os.path.join(cluster_dir, f), encoding="utf-8") as fh:
                doc = json.load(fh)
            doc["age_s"] = max(0.0, now - float(doc.get("t_wall", 0.0)))
            out[rank] = doc
        except (ValueError, OSError):
            continue   # a beat mid-replace or a torn read: next poll wins
    return out


def _staleness_text(missing: List[int],
                    staleness: Dict[int, Optional[float]]) -> str:
    parts = []
    for r in missing:
        age = staleness.get(r)
        parts.append(f"rank {r}: no heartbeat ever" if age is None
                     else f"rank {r}: heartbeat {age:.1f}s stale")
    return "; ".join(parts)


# ---------------------------------------------------------------------------
# the per-process runtime
# ---------------------------------------------------------------------------

class ClusterRuntime:
    """One process's membership in a multi-process cluster.

    ``cluster_dir`` is the shared control-plane directory (heartbeats,
    barrier tokens, per-rank blackboxes); ``rank``/``world`` are this
    process's id and the group size. ``coordinator`` enables the real
    ``jax.distributed`` bootstrap in :meth:`form`; ``None`` keeps the
    runtime file-only (heartbeats/barriers/commits without collectives
    — what the subprocess drills and a CPU backend without gloo use).
    """

    def __init__(self, cluster_dir: str, rank: int, world: int, *,
                 coordinator: Optional[str] = None,
                 heartbeat_interval_s: float = 0.25,
                 init_deadline_s: float = 60.0,
                 init_backoff_base_s: float = 0.25,
                 init_backoff_max_s: float = 4.0,
                 incarnation: int = 0,
                 poll_s: float = 0.02):
        self.cluster_dir = cluster_dir
        self.rank = int(rank)
        self.world = int(world)
        self.coordinator = coordinator
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.init_deadline_s = float(init_deadline_s)
        self.init_backoff_base_s = float(init_backoff_base_s)
        self.init_backoff_max_s = float(init_backoff_max_s)
        self.incarnation = int(incarnation)
        self.poll_s = float(poll_s)
        #: checkpoint-manifest fence id for group commits (rank 0 claims
        #: via :meth:`claim_commit_incarnation`; non-zero ranks never
        #: write, so they carry no fence)
        self.commit_incarnation: Optional[int] = None
        os.makedirs(cluster_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._hb_seq = 0
        self._commit_ordinal = 0
        self._formed = False
        self._form_attempts = 0

    # -- heartbeats -------------------------------------------------------

    def _write_beat(self) -> None:
        with self._lock:
            self._hb_seq += 1
            seq = self._hb_seq
        path = heartbeat_path(self.cluster_dir, self.rank)
        tmp = f"{path}.tmp.{os.getpid()}"
        doc = {"rank": self.rank, "pid": os.getpid(),
               "incarnation": self.incarnation, "seq": seq,
               "t_wall": time.time(),
               "cadence_s": self.heartbeat_interval_s}
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    def start_heartbeat(self) -> None:
        """Write one beat synchronously (this rank has now REPORTED —
        the bring-up diagnosis counts it) and start the cadence thread.
        Idempotent."""
        self._write_beat()
        with self._lock:
            if self._hb_thread is not None and self._hb_thread.is_alive():
                return
            self._hb_stop.clear()
            t = threading.Thread(target=self._beat_loop,
                                 name=f"cluster-heartbeat-r{self.rank}",
                                 daemon=True)
            self._hb_thread = t
        t.start()

    def _beat_loop(self) -> None:
        n = 0
        while not self._hb_stop.wait(self.heartbeat_interval_s):
            n += 1
            # slow = a late beat (the rank looks stale until it lands);
            # wedge = the heartbeat thread dies outright — exactly the
            # "process alive, making no progress" hang signature the
            # supervisor classifies as hang, not crash
            try:
                faultinject.fault_point("cluster/heartbeat", index=n)
            except faultinject.WedgeReleased:
                return   # the wedged thread is dead; the file goes stale
            try:
                self._write_beat()
            except OSError:
                logger.warning("cluster: rank %d heartbeat write failed",
                               self.rank, exc_info=True)

    def stop_heartbeat(self) -> None:
        with self._lock:
            t = self._hb_thread
            self._hb_thread = None
        self._hb_stop.set()
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    # -- bring-up ---------------------------------------------------------

    @staticmethod
    def _probe_coordinator(coordinator: str, timeout_s: float) -> None:
        """One bounded TCP connect to the coordinator. jax's distributed
        client does NOT raise on a dead coordinator — its C++ layer
        ``abort()``s the whole process once the registration deadline
        expires — so non-zero ranks probe layer-4 reachability first and
        turn "nobody listening" into a ConnectionError the retry loop
        can absorb and diagnose."""
        import socket

        host, _, port = coordinator.rpartition(":")
        with socket.create_connection((host or "127.0.0.1", int(port)),
                                      timeout=max(0.1, min(timeout_s, 5.0))):
            pass

    @staticmethod
    def _default_initialize(coordinator: str, world: int, rank: int,
                            timeout_s: float) -> None:
        """``jax.distributed.initialize`` with the attempt's timeout and
        a CPU-backend collectives auto-select: when the platform is CPU
        and jaxlib ships gloo, pick it — without it a multi-process CPU
        cluster forms but cannot run a single cross-process collective.
        Non-zero ranks probe the coordinator first (rank 0 HOSTS it, so
        it never probes): see :meth:`_probe_coordinator`."""
        import jax

        if rank != 0:
            ClusterRuntime._probe_coordinator(coordinator, timeout_s)

        if cpu_multiprocess_collectives_available() and world > 1:
            platforms = str(getattr(jax.config, "jax_platforms", "") or "")
            if platforms in ("", "cpu"):
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo")
                except Exception:
                    pass   # backend already initialized: keep its choice
        jax.distributed.initialize(
            coordinator_address=coordinator, num_processes=world,
            process_id=rank,
            initialization_timeout=max(1, int(timeout_s)))

    def form(self, initialize_fn: Optional[Callable[..., None]] = None
             ) -> "ClusterRuntime":
        """Bring this rank up: start the heartbeat, then bootstrap the
        coordination service under bounded exponential-backoff retries
        and the init deadline. Emits ``cluster/form`` on success; raises
        :class:`ClusterInitError` with the full diagnosis on failure —
        never a silent hang. ``initialize_fn(coordinator, world, rank,
        timeout_s)`` overrides the ``jax.distributed`` bootstrap (drills
        inject refused connects through it)."""
        prof = OpProfiler.get()
        self.start_heartbeat()
        t0 = time.monotonic()
        deadline = t0 + self.init_deadline_s
        attempts = 0
        last_err: Optional[BaseException] = None
        while True:
            attempts += 1
            remaining = deadline - time.monotonic()
            try:
                # transient = one refused coordinator connect (the
                # bring-up drill): the retry loop absorbs it
                faultinject.fault_point("cluster/init", index=attempts - 1)
                if self.coordinator is not None:
                    fn = initialize_fn or self._default_initialize
                    fn(self.coordinator, self.world, self.rank,
                       max(0.5, remaining))
                break
            except (faultinject.TransientFault, ConnectionError, OSError,
                    RuntimeError) as e:
                last_err = e
                prof.count("cluster/init_retries")
                elapsed = time.monotonic() - t0
                backoff = min(
                    self.init_backoff_base_s * (2 ** (attempts - 1)),
                    self.init_backoff_max_s)
                if time.monotonic() + backoff >= deadline:
                    hb = read_heartbeats(self.cluster_dir)
                    reported = sorted(hb)
                    msg = (f"cluster bring-up failed on rank {self.rank}: "
                           f"coordinator {self.coordinator!r} unreachable "
                           f"after {attempts} attempt(s) over "
                           f"{elapsed:.1f}s (deadline "
                           f"{self.init_deadline_s:.1f}s); ranks that "
                           f"reported a heartbeat: {reported}; "
                           f"last error: {e}")
                    prof.count("cluster/init_failures")
                    logger.error(msg)
                    raise ClusterInitError(
                        msg, coordinator=self.coordinator,
                        attempts=attempts, elapsed_s=elapsed,
                        reported_ranks=reported) from e
                logger.warning("cluster: rank %d bring-up attempt %d "
                               "failed (%s); retrying in %.2fs",
                               self.rank, attempts, e, backoff)
                time.sleep(backoff)
        with self._lock:
            self._formed = True
            self._form_attempts = attempts
        prof.count("cluster/formed")
        flightrec.event("cluster/form", rank=self.rank, world=self.world,
                        coordinator=self.coordinator, attempts=attempts,
                        incarnation=self.incarnation,
                        elapsed_s=round(time.monotonic() - t0, 3))
        return self

    @property
    def formed(self) -> bool:
        with self._lock:
            return self._formed

    @property
    def form_attempts(self) -> int:
        with self._lock:
            return self._form_attempts

    # -- barrier ----------------------------------------------------------

    def _token_path(self, name: str, gen: int, rank: int) -> str:
        safe = "".join(c if (c.isalnum() or c in "-_.") else "_"
                       for c in name)
        return os.path.join(self.cluster_dir, f"bar-{safe}-{gen}.r{rank}")

    def barrier(self, name: str, *, deadline_s: float = 30.0,
                gen: int = 0) -> None:
        """Deadline-diagnosed rendezvous over token files — independent
        of collectives, so it works exactly when a collective would hang.
        On timeout: emits a ``cluster/barrier`` event (missing ranks +
        per-rank heartbeat staleness as attrs), dumps this rank's
        blackbox next to the heartbeats, and raises
        :class:`BarrierTimeout` whose message names every missing rank
        with its staleness. ``gen`` disambiguates reuses of the same
        barrier name (e.g. one per commit sequence)."""
        prof = OpProfiler.get()
        # crash = a rank dying exactly at the fence (the barrier drill:
        # survivors must time out with THIS rank named missing)
        faultinject.fault_point("cluster/barrier", index=gen)
        token = self._token_path(name, gen, self.rank)
        with open(token, "w", encoding="utf-8") as f:
            f.write(str(os.getpid()))
        t0 = time.monotonic()
        deadline = t0 + float(deadline_s)
        while True:
            missing = [r for r in range(self.world)
                       if not os.path.exists(self._token_path(name, gen, r))]
            if not missing:
                prof.count("cluster/barriers")
                return
            if time.monotonic() >= deadline:
                break
            time.sleep(self.poll_s)
        hb = read_heartbeats(self.cluster_dir)
        staleness: Dict[int, Optional[float]] = {
            r: (round(hb[r]["age_s"], 3) if r in hb else None)
            for r in missing}
        msg = (f"barrier {name!r} (gen {gen}) timed out after "
               f"{deadline_s:.1f}s on rank {self.rank}: missing ranks "
               f"{missing} — {_staleness_text(missing, staleness)}")
        prof.count("cluster/barrier_timeouts")
        flightrec.event("cluster/barrier", severity="error", rank=self.rank,
                        barrier=name, gen=gen, missing=missing,
                        staleness={str(r): s for r, s in staleness.items()},
                        deadline_s=float(deadline_s))
        self.dump_rank_blackbox()
        logger.error(msg)
        raise BarrierTimeout(msg, name=name, gen=gen, missing=missing,
                             staleness=staleness)

    # -- group checkpoint commit -----------------------------------------

    def claim_commit_incarnation(self, ckpt_dir: str) -> int:
        """Rank 0 claims the checkpoint directory's incarnation fence for
        this incarnation of the group — a pre-restart writer that wakes
        up late can then never commit over its replacement."""
        from ..util import checkpoint as _ckpt

        if self.rank != 0:
            raise GroupCommitError(
                f"rank {self.rank}: only rank 0 claims the commit fence")
        self.commit_incarnation = _ckpt.claim_incarnation(ckpt_dir)
        return self.commit_incarnation

    def commit_group_checkpoint(self, ckpt_dir: str, tag: str, data: bytes,
                                iteration: int, *, keep_last: int = 4,
                                seq: Optional[int] = None,
                                barrier_deadline_s: float = 30.0) -> str:
        """The cross-process commit protocol. All ranks call it with the
        same ``tag``: pre-commit barrier (every rank's state is at the
        boundary) → rank 0 commits atomically under the incarnation
        fence → publish barrier → non-zero ranks verify the manifest's
        newest intact generation IS this commit before resuming. Returns
        the committed path. A rank killed mid-protocol leaves the
        previous generation restorable: the manifest only ever names
        fully-committed files."""
        from ..util import checkpoint as _ckpt

        with self._lock:
            self._commit_ordinal += 1
            ordinal = self._commit_ordinal
        gen = seq if seq is not None else iteration
        self.barrier(f"commit-{tag}-pre", deadline_s=barrier_deadline_s,
                     gen=gen)
        path: Optional[str] = None
        if self.rank == 0:
            os.makedirs(ckpt_dir, exist_ok=True)
            # crash = the torn-group-commit drill: rank 0 dies between
            # the fences; survivors' publish barrier must time out and
            # the PREVIOUS generation must stay restorable
            faultinject.fault_point("cluster/commit", index=ordinal - 1)
            path = _ckpt.commit_checkpoint(
                ckpt_dir, tag, data, iteration, keep_last, seq=seq,
                incarnation=self.commit_incarnation)
        self.barrier(f"commit-{tag}-pub", deadline_s=barrier_deadline_s,
                     gen=gen)
        if self.rank != 0:
            path = _ckpt.verify_group_commit(ckpt_dir, tag)
            if path is None:
                newest = _ckpt.last_checkpoint(ckpt_dir)
                raise GroupCommitError(
                    f"rank {self.rank}: group commit {tag!r} not intact "
                    f"in the manifest after the publish barrier (newest "
                    f"verified: {newest!r})")
        OpProfiler.get().count("cluster/group_commits")
        return path  # type: ignore[return-value]

    # -- blackbox ---------------------------------------------------------

    def dump_rank_blackbox(self) -> str:
        """Dump this rank's flight-recorder ring, every row tagged with
        the rank + incarnation, to ``blackbox-rank<k>.jsonl`` in the
        cluster directory (atomic replace). The supervisor merges these
        into one incident after a group failure."""
        path = os.path.join(self.cluster_dir,
                            f"{BLACKBOX_PREFIX}{self.rank}.jsonl")
        rows = flightrec.get().snapshot()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            for row in rows:
                tagged = dict(row)
                tagged["rank"] = self.rank
                tagged["incarnation"] = self.incarnation
                f.write(json.dumps(tagged, default=str) + "\n")
        os.replace(tmp, path)
        return path

    def shutdown(self) -> None:
        """Stop the heartbeat thread (the file stays — its growing age
        documents when this rank went quiet)."""
        self.stop_heartbeat()


# ---------------------------------------------------------------------------
# supervisor-side helpers (run in the supervising process)
# ---------------------------------------------------------------------------

def cpu_multiprocess_collectives_available() -> bool:
    """Does the installed jaxlib ship a CPU cross-process collectives
    implementation (gloo or MPI)? Without one a multi-process CPU
    cluster forms but every cross-process computation fails — the
    multiprocess test probes this at collection time."""
    try:
        from jax._src.lib import xla_client

        return (hasattr(xla_client._xla, "make_gloo_tcp_collectives")
                or hasattr(xla_client._xla, "make_mpi_collectives"))
    except Exception:
        return False


def stale_ranks(cluster_dir: str, stale_after_s: float,
                world: Optional[int] = None) -> List[int]:
    """Ranks whose heartbeat age exceeds ``stale_after_s`` — the
    supervisor's hang detector (a rank can be stale while its process
    is still alive: that is precisely what distinguishes a hang from a
    crash). Ranks that never beat are only reported when ``world`` says
    they should exist."""
    hb = read_heartbeats(cluster_dir)
    out = [r for r, doc in hb.items() if doc["age_s"] > stale_after_s]
    if world is not None:
        out += [r for r in range(world) if r not in hb]
    return sorted(set(out))


def merge_rank_blackboxes(cluster_dir: str) -> List[Dict[str, Any]]:
    """Join every rank's dumped blackbox into one wall-clock-ordered
    event list (rows already carry ``rank`` + ``incarnation`` tags from
    :meth:`ClusterRuntime.dump_rank_blackbox`). The supervisor attaches
    the merge to the incident report so one file tells the whole
    group's story with per-rank lanes."""
    merged: List[Dict[str, Any]] = []
    try:
        names = os.listdir(cluster_dir)
    except OSError:
        return merged
    for f in sorted(names):
        if not (f.startswith(BLACKBOX_PREFIX) and f.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(cluster_dir, f), encoding="utf-8") as fh:
                for line in fh:
                    try:
                        merged.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
    merged.sort(key=lambda e: (e.get("t", 0.0), e.get("rank", -1),
                               e.get("seq", 0)))
    return merged
