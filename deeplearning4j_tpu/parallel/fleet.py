"""Fleet training: vmapped model populations through ONE compiled step.

ROADMAP item 5(a) — the "millions of users" *training* story: a stacked
population of M same-architecture members (per-user fine-tunes,
hyperparameter sweeps, RL populations) whose params / updater state /
RNG keys carry a leading population axis, trained by one ``jax.vmap``-ed
step core under a single ``jit``. Whole-graph compilation makes batching
entire *programs* nearly free on TPU (arXiv:1810.09868); the population
axis is the third companion to the data axis (parallel/wrapper.py) and
the model axis (parallel/sharding.py) — and unlike either, it costs ONE
compile for any M.

The load-bearing contracts:

- **Bitwise member parity.** Member k of a fleet is bit-identical to the
  same model trained solo with the same RNG stream: member init replays
  ``MultiLayerNetwork.init(member_seeds[k])`` exactly, the per-member
  stream key is carried IN-GRAPH and split exactly like the solo fit
  path splits its host ``Random`` (``new_key, sub = split(key)`` per
  step), and the step body IS the solo ``_step_core`` — vmapped, never
  reimplemented. ``solo_twin(k)`` builds the comparator.
- **One compile, ever.** Telemetry, per-member hyperparameters, cull,
  spawn, and NaN isolation are all shape-stable data: the alive mask and
  hyper scalars are traced inputs, cull/spawn rewrite state slices with
  index-free ``where``/multiply forms, so nothing retraces
  (``trace/fleet_step`` stays 1; fleet-smoke arms
  ``tracecheck.steady_state`` over a cull+spawn drill to prove it).
  Known cost: the alive-freeze ``lax.cond`` keeps the pre-step state
  alive as a branch operand, so XLA cannot donate the stacked
  params/states/updater buffers into the step (the "donated buffers
  were not usable" warning at trace time) — peak memory is ~2x the
  stacked state during a dispatch, the price of bitwise member parity
  (see ``_build_fleet_step``).
- **Per-member telemetry, one sync per window.** The PR-2 aux pytree
  gains a leading member axis under vmap; the trainer buffers the device
  pytrees and drains the whole fleet's window in ONE batched
  ``jax.device_get`` (``telemetry/drain``), feeding storage sinks,
  per-member early-stop, and the NaN-cull reporter.
- **Per-member NaN isolation.** With a ``NanSentinelListener("skip")``
  the in-graph nan guard runs PER MEMBER under vmap: a poisoned member
  carries its pre-NaN state forward while the other M-1 updates land.
  Policy ``"cull"`` additionally flips that member's alive bit in-graph
  (event ``fleet/nan_cull``) — permanent isolation, zero retraces.
- **Checkpoint slicing.** ``save_member(k)`` commits member k as an
  ordinary solo checkpoint through the PR-3 atomic machinery (manifest
  entry tagged with ``fleet`` metadata); restoring it into a solo model
  is bit-exact INCLUDING the RNG stream, so the solo continuation
  reproduces the fleet member's future bit-for-bit. ``save()`` commits
  the whole stacked state (+ alive mask / keys / hyper in resume.json)
  and ``restore()`` resumes it exactly — kill+resume parity over the
  stacked state rides the same machinery as PR-4.

Serving handoff: ``export_member(best)``/``save_member(best)`` feed
PR-11's ``ServingEngine.publish_checkpoint`` — a fleet-trained member
canaries onto a live engine with zero recompiles (the AOT executables
take params as arguments).
"""

from __future__ import annotations

import copy
import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..common import flightrec, xprof
from ..common.profiler import OpProfiler
from ..data import pipeline as _pipe
from ..optimize.telemetry import config_for

logger = logging.getLogger("deeplearning4j_tpu")

#: hyperparameters sweepable per member through the one compiled step
SWEEPABLE = ("lr", "l2", "dropout")


class FleetEarlyStop:
    """Per-member early stopping driven from the telemetry bus: a member
    whose loss has not improved by ``min_delta`` for ``patience``
    consecutive TRAINED steps is culled (its slice freezes in-graph; the
    rest of the fleet keeps training, nothing retraces). Decisions run at
    drain boundaries on the batched window readback — the hot loop never
    syncs. A ``spawn`` resets the member's best/staleness
    (:meth:`member_spawned`), so a respawned member gets a fresh
    patience window instead of inheriting its dead predecessor's. The
    ``EarlyStoppingTrainer``-loop-per-model replacement."""

    wants_telemetry = True

    def __init__(self, patience: int, min_delta: float = 0.0):
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        self.patience = int(patience)
        self.min_delta = float(min_delta)
        self._best: Optional[np.ndarray] = None
        self._stale: Optional[np.ndarray] = None

    def member_spawned(self, member: int) -> None:
        """Forget a re-initialized member's history (FleetTrainer.spawn
        notifies every listener exposing this)."""
        if self._best is not None:
            self._best[int(member)] = np.inf
            self._stale[int(member)] = 0

    def decide(self, losses: np.ndarray, alive: np.ndarray) -> List[int]:
        """``losses``: [W, M] drained window; ``alive``: [M] current mask.
        Returns members to cull (alive ones whose staleness exceeded
        patience within this window)."""
        W, M = losses.shape
        if self._best is None:
            self._best = np.full(M, np.inf)
            self._stale = np.zeros(M, np.int64)
        out: List[int] = []
        for w in range(W):
            improved = losses[w] < self._best - self.min_delta
            self._best = np.where(improved, losses[w], self._best)
            self._stale = np.where(improved, 0, self._stale + 1)
        for m in range(M):
            if alive[m] and self._stale[m] > self.patience:
                out.append(m)
        return out

    # exact-resume support (rides the fleet checkpoint's listener_state)
    def state_dict(self) -> Dict[str, Any]:
        return {"best": None if self._best is None else self._best.tolist(),
                "stale": None if self._stale is None
                else self._stale.tolist()}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._best = (None if state.get("best") is None
                      else np.asarray(state["best"], np.float64))
        self._stale = (None if state.get("stale") is None
                       else np.asarray(state["stale"], np.int64))


class FleetStatsSink:
    """Drains per-member fleet telemetry into a ``StatsStorage`` backend
    (in-memory / JSONL / TensorBoard — the same SPI ``TelemetrySink``
    feeds). Emitted per drained iteration and member: ``fleet/loss/m<i>``,
    ``fleet/grad_norm/m<i>`` (the member's global gradient norm),
    ``fleet/alive/m<i>``, and ``fleet/nonfinite/m<i>`` when non-zero.
    Host cost is zero beyond the trainer's one batched window readback —
    this sink only fans the already-host values out."""

    wants_telemetry = True

    def __init__(self, storage, session_id: str = ""):
        self.storage = storage
        self.session = session_id

    def fleet_window(self, fleet: "FleetTrainer", iters: Sequence[int],
                     window: List[Dict[str, np.ndarray]]) -> None:
        put = self.storage.put_scalar
        for it, aux in zip(iters, window):
            loss = np.asarray(aux["loss"])
            gnorm = np.sqrt(np.sum(np.square(np.asarray(aux["grad_norm"],
                                                        np.float64)),
                                   axis=-1))
            alive = np.asarray(aux["alive"])
            nf = np.asarray(aux["nonfinite"])
            for m in range(fleet.n_members):
                put(self.session, f"fleet/loss/m{m}", it, float(loss[m]))
                put(self.session, f"fleet/grad_norm/m{m}", it,
                    float(gnorm[m]))
                put(self.session, f"fleet/alive/m{m}", it, int(alive[m]))
                nfm = int(np.sum(nf[m]))
                if nfm:
                    put(self.session, f"fleet/nonfinite/m{m}", it, nfm)


def _normalize_grid(grid) -> Dict[str, np.ndarray]:
    """Sweep grid → {field: float64 [M]}. Accepts a dict of equal-length
    lists (zipped — one member per row) or a list of per-member dicts
    (every dict must name the same fields)."""
    if isinstance(grid, dict):
        fields = dict(grid)
    elif isinstance(grid, (list, tuple)):
        if not grid:
            raise ValueError("empty sweep grid")
        keys = set(grid[0])
        if any(set(g) != keys for g in grid):
            raise ValueError("every sweep-grid row must name the same "
                             "hyperparameters")
        fields = {k: [g[k] for g in grid] for k in keys}
    else:
        raise TypeError(f"grid must be a dict of lists or a list of "
                        f"dicts, got {type(grid).__name__}")
    unknown = sorted(set(fields) - set(SWEEPABLE))
    if unknown:
        raise ValueError(f"unknown sweep field(s) {unknown}; sweepable: "
                         f"{list(SWEEPABLE)}")
    sizes = {len(v) for v in fields.values()}
    if len(sizes) != 1:
        raise ValueError(f"sweep-grid fields disagree on member count: "
                         f"{ {k: len(v) for k, v in fields.items()} }")
    # float64 on purpose: weak-Python-float matching under x64 — a swept
    # value equal to the baked one stays bitwise identical to solo
    return {k: np.asarray(v, np.float64) for k, v in fields.items()}


class FleetTrainer:
    """Train M stacked same-architecture members through one vmapped,
    jitted step. ``model`` is the architecture template (an init()-ed
    ``MultiLayerNetwork``); the trainer owns it for tracing — its layer
    pure functions and ``_step_core`` ARE the member step, so fleet
    numerics can never drift from solo numerics.

    Thread-shared by registry (graftlint SHARED_CLASSES): the training
    thread mutates carried state while sinks/serving read exports —
    every mutation holds ``_lock``.
    """

    def __init__(self, model, n_members: Optional[int] = None, *,
                 hyper=None, seed: Optional[int] = None,
                 member_seeds: Optional[Sequence[int]] = None,
                 drain_every_n: int = 10):
        model._check_init()
        self._lock = threading.Lock()
        self.model = model
        self._hyper_np = _normalize_grid(hyper) if hyper else None
        counts = set()
        if n_members is not None:
            counts.add(int(n_members))
        if member_seeds is not None:
            counts.add(len(member_seeds))
        if self._hyper_np:
            counts.add(len(next(iter(self._hyper_np.values()))))
        if len(counts) != 1:
            raise ValueError(
                f"member count ambiguous or missing: n_members/"
                f"member_seeds/hyper imply {sorted(counts)}")
        M = counts.pop()
        if M < 1:
            raise ValueError(f"need at least one member, got {M}")
        self.n_members = M
        self._seed = int(seed if seed is not None
                         else model.conf.global_conf.seed)
        self.member_seeds = (list(member_seeds) if member_seeds is not None
                             else [self._seed + i for i in range(M)])
        # stacked state: member i's init replays MultiLayerNetwork.init
        # with member_seeds[i] exactly (the parity contract)
        per_member = [self._init_member(s) for s in self.member_seeds]
        self._params = jax.tree.map(lambda *xs: jnp.stack(xs),
                                    *[p for p, _ in per_member])
        self._states = jax.tree.map(lambda *xs: jnp.stack(xs),
                                    *[s for _, s in per_member])
        self._updater_state = \
            model.conf.global_conf.updater.init(self._params)
        # per-member RNG streams, carried in-graph: fold_in(member) off
        # one base key; solo_twin() hands the same stream to a solo model
        base = jax.random.PRNGKey(self._seed)
        self._keys = jnp.stack([jax.random.fold_in(base, i)
                                for i in range(M)])
        self._alive = jnp.ones((M,), jnp.int32)
        self._alive_np = np.ones(M, np.int64)    # host mirror (reporting)
        self._hyper = (None if self._hyper_np is None else
                       {k: jnp.asarray(v)
                        for k, v in self._hyper_np.items()})
        self._iteration = 0
        self._epoch = 0
        self._score_dev = None
        self._listeners: List[Any] = []
        self._tele = None
        self._fit_step = None
        self._drain_every = max(1, int(drain_every_n))
        self._aux_buf: List[tuple] = []
        self._last_losses: Optional[np.ndarray] = None
        self._infer_fn = None
        OpProfiler.get().gauge("fleet/members", M)

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_sweep(cls, base_model, grid, *, seed: Optional[int] = None,
                   same_init: bool = True,
                   drain_every_n: int = 10) -> "FleetTrainer":
        """Hyperparameter-sweep constructor: one member per grid row, the
        whole sweep one trace. ``same_init=True`` (the usual sweep
        methodology) gives every member the SAME initial params — the
        sweep isolates the hyperparameter axis; False re-inits per member
        (seed+i). Sweepable fields: ``lr``, ``l2``, ``dropout``."""
        hyper = _normalize_grid(grid)
        M = len(next(iter(hyper.values())))
        seed = int(seed if seed is not None
                   else base_model.conf.global_conf.seed)
        seeds = [seed] * M if same_init else [seed + i for i in range(M)]
        return cls(base_model, M, hyper=hyper, seed=seed,
                   member_seeds=seeds, drain_every_n=drain_every_n)

    # -- plumbing ----------------------------------------------------------
    @property
    def conf(self):
        """The template's configuration — makes the trainer duck-type as
        a model for the PR-3 checkpoint machinery (snapshot /
        load_state_entries work on the stacked trees unchanged)."""
        return self.model.conf

    def _check_init(self) -> None:    # checkpoint-machinery duck-typing
        pass

    def _init_member(self, seed: int):
        """Replay MultiLayerNetwork.init(seed) for one member (host-side;
        bitwise identical to the solo init by construction)."""
        conf = self.model.conf
        key = jax.random.PRNGKey(int(seed))
        dtype = jnp.dtype(conf.global_conf.dtype)
        params, states = [], []
        for layer in self.model.layers:
            key, sub = jax.random.split(key)
            params.append(layer.init_params(sub, dtype)
                          if layer.has_params else {})
            states.append(layer.init_state())
        return params, states

    def member_stream_state(self, member: int) -> Dict[str, Any]:
        """The RNG-stream state a SOLO run must start from to replay
        member ``member``'s training stream (``Random.set_state``
        payload)."""
        base = jax.random.PRNGKey(self._seed)
        return {"seed": self._seed,
                "key": jax.random.fold_in(base, int(member))}

    def solo_twin(self, member: int):
        """A fresh solo model positioned to train bit-identically to
        member ``member``: same init seed, and the calling thread's RNG
        stream moved onto the member's fold_in key. The parity-gate
        comparator (fleet-smoke, tests)."""
        from ..ndarray.rng import get_random
        from ..nn.multilayer import MultiLayerNetwork

        net = MultiLayerNetwork(copy.deepcopy(self.model.conf))
        net.init(self.member_seeds[int(member)])
        get_random().set_state(self.member_stream_state(member))
        return net

    def set_listeners(self, *listeners) -> None:
        """Attach listeners. Telemetry-wanting listeners (TelemetrySink
        protocol attributes) switch the step to carry the per-member aux
        pytree — one rebuild, still one trace. ``NanSentinelListener``
        carries the per-member NaN policy (``"skip"`` = transient
        isolation, ``"cull"`` = permanent); :class:`FleetEarlyStop`
        culls from the drained window; objects exposing ``fleet_window``
        (:class:`FleetStatsSink`) receive every drained window."""
        cfg = config_for(list(listeners))
        with self._lock:
            self._listeners = list(listeners)
            if cfg != self._tele:
                self._tele = cfg
                self._fit_step = None

    # -- the one compiled step --------------------------------------------
    def _build_fleet_step(self):
        # the member body IS the solo step core (parity by construction);
        # telemetry is a build-time property exactly as in the solo paths.
        # The template's own telemetry flag is restored after the build —
        # _step_core reads it at build time only — so a later SOLO fit of
        # the template still carries its own listener-implied config.
        prev = self.model._telemetry
        self.model._telemetry = self._tele
        try:
            core = self.model._step_core()
        finally:
            self.model._telemetry = prev
        tele = self._tele
        member_cull = bool(tele and tele.member_cull)
        with_hyper = self._hyper is not None

        def member(p, s, u, key, x_m, y_m, hyp, it):
            new_key, sub = jax.random.split(key)
            out = core(p, s, u, x_m, y_m, None, sub, it, None, None,
                       hyper=hyp)
            if tele is None:
                new_p, new_s, new_u, loss = out
                aux = None
            else:
                new_p, new_s, new_u, loss, aux = out
            return new_p, new_s, new_u, new_key, loss, aux

        if with_hyper:
            vmapped = jax.vmap(member, in_axes=(0, 0, 0, 0, 0, 0, 0, None))
        else:
            def member_nohyp(p, s, u, key, x_m, y_m, it):
                return member(p, s, u, key, x_m, y_m, None, it)

            vmapped = jax.vmap(member_nohyp,
                               in_axes=(0, 0, 0, 0, 0, 0, None))

        def fleet_step(params, states, upd, keys, alive, x, y, hyper, it):
            OpProfiler.get().count("trace/fleet_step")
            if with_hyper:
                new_p, new_s, new_u, new_keys, losses, aux = vmapped(
                    params, states, upd, keys, x, y, hyper, it)
            else:
                new_p, new_s, new_u, new_keys, losses, aux = vmapped(
                    params, states, upd, keys, x, y, it)
            ok = alive > 0

            # The alive-mask freeze lives INSIDE a lax.cond on purpose:
            # XLA does not fuse across the conditional boundary, so the
            # all-alive path returns the vmapped core's outputs with
            # their fusion layout untouched — a bare jnp.where here gets
            # its producers DUPLICATED into the select fusion and
            # re-contracted, which cost the Adam/Nesterovs family ~1 ulp
            # per step against the solo program (measured; Sgd survived).
            # With the cond, member-vs-solo parity is bitwise for every
            # updater, culled or not.
            def frozen(args):
                (n_p, n_s, n_u, n_k), (o_p, o_s, o_u, o_k) = args

                def keep(n, o):
                    return jnp.where(
                        ok.reshape((ok.shape[0],) + (1,) * (n.ndim - 1)),
                        n, o)

                return (jax.tree.map(keep, n_p, o_p),
                        jax.tree.map(keep, n_s, o_s),
                        jax.tree.map(keep, n_u, o_u), keep(n_k, o_k))

            def live(args):
                return args[0]

            new_p, new_s, new_u, new_keys = jax.lax.cond(
                jnp.all(ok), live, frozen,
                ((new_p, new_s, new_u, new_keys),
                 (params, states, upd, keys)))
            new_alive = alive
            if aux is not None:
                if member_cull:
                    # per-member NaN isolation, permanent flavor: the nan
                    # guard already dropped the poisoned member's update
                    # in-graph (per member, under vmap); flipping its
                    # alive bit here freezes it for good
                    new_alive = alive * (1 - aux["skipped"])
                aux = dict(aux)
                aux["alive"] = new_alive
            return new_p, new_s, new_u, new_keys, new_alive, losses, aux

        # No donation on purpose: the freeze cond keeps the pre-step
        # param/state/updater buffers alive as branch operands (XLA
        # reports them unusable anyway), and the SMALL carried buffers
        # (keys, alive) WOULD donate — deleting arrays a concurrent
        # cull()/alive_mask()/_member_rng_state() may still be reading.
        return xprof.register_jit("fleet/step", jax.jit(fleet_step))

    # -- training ----------------------------------------------------------
    def step(self, x, y, per_member: bool = False):
        """One fleet step. ``per_member=True``: ``x``/``y`` carry a
        leading [M] member axis (per-user data); otherwise the one batch
        is broadcast fleet-wide (sweeps, populations on shared data).
        Returns the per-member DEVICE loss vector [M] (no host sync)."""
        xv = jnp.asarray(x)
        yv = jnp.asarray(y)
        if not per_member:
            xv = jnp.broadcast_to(xv, (self.n_members,) + xv.shape)
            yv = jnp.broadcast_to(yv, (self.n_members,) + yv.shape)
        elif xv.shape[0] != self.n_members:
            raise ValueError(
                f"per_member batch leading axis {xv.shape[0]} != fleet "
                f"size {self.n_members}")
        prof = OpProfiler.get()
        # the lock spans capture -> dispatch -> write-back: a concurrent
        # cull/spawn (the controller thread) can never interleave with an
        # in-flight step and have its state rewrite silently overwritten
        # by outputs derived from the pre-cull state. Dispatch is async
        # (the jit call returns once enqueued), so the hold is short.
        with self._lock:
            if self._fit_step is None:
                self._fit_step = self._build_fleet_step()
            with prof.time_section("pipeline/dispatch"):
                out = self._fit_step(self._params, self._states,
                                     self._updater_state, self._keys,
                                     self._alive, xv, yv, self._hyper,
                                     jnp.asarray(self._iteration))
            new_p, new_s, new_u, new_keys, new_alive, losses, aux = out
            self._params, self._states, self._updater_state = \
                new_p, new_s, new_u
            self._keys, self._alive = new_keys, new_alive
            self._iteration += 1
            self._score_dev = losses
            it_done = self._iteration
        if aux is not None:
            self._note_aux(it_done, aux)
        return losses

    def fit(self, data, epochs: int = 1,
            batch_size: Optional[int] = None) -> None:
        """Train the whole fleet on a shared data stream: every DataSet
        batch is broadcast across the member axis and dispatched as ONE
        compiled step (per-member data goes through
        ``step(..., per_member=True)``). Batch shapes must stay stable
        (use the iterator's padding knobs) — the fleet compiles once."""
        for _ in range(max(1, epochs)):
            for ds in _pipe.iter_datasets(data, batch_size):
                self.step(jnp.asarray(ds.features.value),
                          jnp.asarray(ds.labels.value))
            with self._lock:
                self._epoch += 1
            self.drain()

    # -- telemetry bus (one device_get per drain window) -------------------
    def _note_aux(self, iteration: int, aux) -> None:
        # append under the lock: drain() swaps the buffer out under the
        # same lock (possibly from another thread — save(), best_member()
        # on a controller), and an unlocked append could land on the
        # already-captured window and silently vanish
        with self._lock:
            self._aux_buf.append((iteration, aux))
            full = len(self._aux_buf) >= self._drain_every
        if full:
            self.drain()

    def drain(self) -> None:
        """Flush the buffered telemetry window: ONE batched readback for
        the whole fleet, then fan out to sinks / NaN-cull reporting /
        early-stop decisions. The only host sync telemetry pays."""
        with self._lock:
            buf, self._aux_buf = self._aux_buf, []
            listeners = list(self._listeners)
        if not buf:
            return
        prof = OpProfiler.get()
        with prof.time_section("telemetry/drain"):
            host = jax.device_get([a for _, a in buf])
        prof.count("fleet/drains")
        iters = [it for it, _ in buf]
        alive_after = np.array(host[-1]["alive"], np.int64)
        # in-graph NaN culls surface here: a member alive before the
        # window whose skipped flag coincided with its alive bit dropping
        was_alive = self._alive_np.copy()
        for (it, _), aux in zip(buf, host):
            skipped = np.array(aux.get("skipped", 0))
            alive_now = np.array(aux["alive"], np.int64)
            if skipped.ndim == 0:
                continue
            for m in np.nonzero((skipped > 0) & (was_alive > 0)
                                & (alive_now == 0))[0]:
                flightrec.event("fleet/nan_cull", severity="warn",
                                member=int(m), iteration=int(it))
                prof.count("fleet/nan_culls")
                logger.warning(
                    "fleet: member %d produced non-finite gradients at "
                    "iteration %d; its alive bit was flipped in-graph "
                    "(other members unaffected)", int(m), int(it))
            was_alive = alive_now
        with self._lock:
            self._alive_np = alive_after
            losses = np.stack([np.array(a["loss"], np.float64)
                               for a in host])
            self._last_losses = losses[-1]
        prof.gauge("fleet/members", int(alive_after.sum()))
        for lst in listeners:
            win = getattr(lst, "fleet_window", None)
            if callable(win):
                win(self, iters, host)
            if isinstance(lst, FleetEarlyStop):
                for m in lst.decide(losses, alive_after):
                    self.cull(m, reason="early_stop")

    # -- lifecycle ---------------------------------------------------------
    def alive_mask(self) -> np.ndarray:
        """Host view of the alive mask. Synced on demand — authoritative
        including in-graph NaN culls the drain has not reported yet."""
        alive = np.asarray(self._alive, np.int64)
        with self._lock:
            self._alive_np = alive
        return alive.copy()

    def cull(self, member: int, reason: str = "cull") -> None:
        """Freeze member ``member``: its alive bit drops to 0 and every
        subsequent update is zeroed IN-GRAPH (``where`` against the
        carried state) — shape-stable, no retrace. The slice keeps its
        exact pre-cull bits (export/save still work)."""
        m = int(member)
        if not 0 <= m < self.n_members:
            raise ValueError(f"member {m} out of range [0, "
                             f"{self.n_members})")
        sel = np.zeros(self.n_members, np.int32)
        sel[m] = 1
        with self._lock:
            # index-free form: one compile for ANY member, ever
            self._alive = self._alive * jnp.asarray(1 - sel)
            self._alive_np = self._alive_np * (1 - sel.astype(np.int64))
            alive_now = int(self._alive_np.sum())
        OpProfiler.get().count("fleet/culls")
        OpProfiler.get().gauge("fleet/members", alive_now)
        flightrec.event("fleet/cull", severity="warn", member=m,
                        reason=reason)

    def spawn(self, member: int, params=None,
              seed: Optional[int] = None) -> None:
        """Re-initialize member ``member`` IN PLACE: fresh params (from
        ``seed``, default its original member seed — or an explicit solo
        param tree), zeroed updater state, a fresh fold_in stream key,
        alive bit back to 1. Index-free slice rewrite — no retrace.
        The member inherits the fleet-global iteration counter (updater
        bias correction continues from it; an exact solo replay of a
        spawned member therefore needs the same starting iteration)."""
        m = int(member)
        if not 0 <= m < self.n_members:
            raise ValueError(f"member {m} out of range [0, "
                             f"{self.n_members})")
        if params is None:
            params, states = self._init_member(
                self.member_seeds[m] if seed is None else int(seed))
        else:
            states = self._init_member(self.member_seeds[m])[1]
        sel = np.zeros(self.n_members, np.int32)
        sel[m] = 1
        sel_dev = jnp.asarray(sel)

        def put(stacked, value):
            mask = sel_dev.astype(bool).reshape(
                (self.n_members,) + (1,) * (stacked.ndim - 1))
            return jnp.where(mask, jnp.asarray(value,
                                               stacked.dtype)[None],
                             stacked)

        fresh_upd = self.model.conf.global_conf.updater.init(params)
        new_key = jax.random.fold_in(
            jax.random.PRNGKey(self._seed if seed is None else int(seed)),
            m)
        with self._lock:
            self._params = jax.tree.map(put, self._params, params)
            self._states = jax.tree.map(put, self._states, states)
            self._updater_state = jax.tree.map(put, self._updater_state,
                                               fresh_upd)
            self._keys = put(self._keys, new_key)
            self._alive = jnp.maximum(self._alive, sel_dev)
            self._alive_np = np.maximum(self._alive_np,
                                        sel.astype(np.int64))
            alive_now = int(self._alive_np.sum())
            listeners = list(self._listeners)
        for lst in listeners:
            # early-stop (and anything else tracking per-member history)
            # must forget the dead predecessor, or the fresh member gets
            # culled again within one drain window
            cb = getattr(lst, "member_spawned", None)
            if callable(cb):
                cb(m)
        OpProfiler.get().count("fleet/spawns")
        OpProfiler.get().gauge("fleet/members", alive_now)
        flightrec.event("fleet/spawn", member=m,
                        seed=int(self.member_seeds[m]
                                 if seed is None else seed))

    def best_member(self) -> int:
        """The alive member with the lowest last-drained loss (requires a
        telemetry listener; drains any buffered window first)."""
        self.drain()
        with self._lock:
            losses = self._last_losses
            alive = self._alive_np.copy()
        if losses is None:
            raise RuntimeError("best_member needs telemetry: attach a "
                               "telemetry listener (set_listeners) and "
                               "train at least one step")
        masked = np.where(alive > 0, losses, np.inf)
        return int(np.argmin(masked))

    # -- member export / checkpoint slicing --------------------------------
    def export_member(self, member: int):
        """Slice member ``member`` out of the stacked state into a fresh
        SOLO ``MultiLayerNetwork`` (owning buffers — safe against the
        fleet step's donation), carrying params / layer states / updater
        state / iteration. The serving-handoff and solo-restore vehicle.
        """
        from ..nn.multilayer import MultiLayerNetwork

        m = int(member)
        if not 0 <= m < self.n_members:
            raise ValueError(f"member {m} out of range [0, "
                             f"{self.n_members})")
        net = MultiLayerNetwork(copy.deepcopy(self.model.conf))
        net.init(self.member_seeds[m])
        with self._lock:
            net._params = jax.tree.map(lambda a: jnp.array(a[m]),
                                       self._params)
            net._states = jax.tree.map(lambda a: jnp.array(a[m]),
                                       self._states)
            net._updater_state = jax.tree.map(lambda a: jnp.array(a[m]),
                                              self._updater_state)
            net._iteration = self._iteration
            net._epoch = self._epoch
        return net

    def _member_rng_state(self, member: int) -> Dict[str, Any]:
        """The member's CURRENT carried stream key as a Random state —
        what a solo continuation must resume from."""
        with self._lock:
            key = np.asarray(self._keys)[int(member)]
        return {"seed": self._seed, "key": key}

    def save_member(self, member: int, directory: str,
                    tag: Optional[str] = None, keep_last: int = 10) -> str:
        """Commit member ``member`` as an ordinary SOLO checkpoint through
        the PR-3 atomic machinery (tmp→fsync→rename→manifest), its
        manifest entry tagged with ``fleet`` metadata. The zip carries
        the member's CURRENT stream key, so
        ``restore_training_state(solo, path)`` resumes the member's
        exact future: the solo continuation is bit-identical to the
        member continuing inside the fleet."""
        from ..util.checkpoint import (commit_checkpoint,
                                       serialize_snapshot,
                                       snapshot_training_state)

        m = int(member)
        net = self.export_member(m)
        snap = snapshot_training_state(net,
                                       rng_state=self._member_rng_state(m))
        tag = tag if tag is not None else f"member{m}_it{snap['iteration']}"
        data = serialize_snapshot(snap)
        return commit_checkpoint(
            directory, tag, data, snap["iteration"], keep_last,
            state_dtype=snap.get("state_dtype"),
            fleet={"member": m, "members": self.n_members})

    def save(self, directory: str, tag: Optional[str] = None,
             keep_last: int = 3) -> str:
        """Commit the WHOLE stacked fleet atomically: the standard
        snapshot machinery over the stacked trees (the trainer
        duck-types as a model), plus the fleet extras — alive mask,
        per-member stream keys, hyper grid, member seeds — in
        resume.json. ``restore()`` resumes bit-exactly, alive mask
        included."""
        from ..util.checkpoint import (commit_checkpoint,
                                       serialize_snapshot,
                                       snapshot_training_state)

        self.drain()
        with self._lock:
            keys = np.asarray(self._keys)
            fleet_extra = {
                "members": self.n_members,
                "member_seeds": [int(s) for s in self.member_seeds],
                "seed": self._seed,
                "alive": [int(a) for a in np.asarray(self._alive)],
                "keys": keys.tolist(),
                "keys_dtype": str(keys.dtype),
                "hyper": (None if self._hyper_np is None else
                          {k: v.tolist()
                           for k, v in self._hyper_np.items()}),
            }
            listeners = list(self._listeners)
        snap = snapshot_training_state(self, listeners=listeners)
        snap["fleet"] = fleet_extra
        tag = tag if tag is not None else f"fleet_it{snap['iteration']}"
        data = serialize_snapshot(snap)
        return commit_checkpoint(
            directory, tag, data, snap["iteration"], keep_last,
            state_dtype=snap.get("state_dtype"),
            fleet={"members": self.n_members})

    def restore(self, path: str) -> None:
        """Resume a :meth:`save` checkpoint into this trainer (same
        architecture and member count): stacked params / states / updater
        state / counters through the standard restore path, then the
        fleet extras — alive mask, carried stream keys, hyper grid.
        Kill+resume is bit-exact, cull state included."""
        from ..util.checkpoint import (read_resume_state,
                                       restore_training_state)

        extra = read_resume_state(path).get("fleet")
        if not extra:
            raise ValueError(
                f"{path} is not a fleet checkpoint (no fleet extras in "
                f"resume.json); member checkpoints restore into a SOLO "
                f"model via restore_training_state")
        if int(extra["members"]) != self.n_members:
            raise ValueError(
                f"checkpoint has {extra['members']} members, trainer has "
                f"{self.n_members}")
        with self._lock:
            listeners = list(self._listeners)
        restore_training_state(self, path, listeners=listeners,
                               restore_rng=False)
        keys = np.asarray(extra["keys"],
                          dtype=extra.get("keys_dtype", "uint32"))
        with self._lock:
            self._keys = jnp.asarray(keys)
            self._alive = jnp.asarray(np.asarray(extra["alive"], np.int32))
            self._alive_np = np.asarray(extra["alive"], np.int64)
            self._seed = int(extra.get("seed", self._seed))
            self.member_seeds = [int(s) for s in extra["member_seeds"]]
            hyper = extra.get("hyper")
            self._hyper_np = (None if hyper is None else
                              {k: np.asarray(v, np.float64)
                               for k, v in hyper.items()})
            self._hyper = (None if self._hyper_np is None else
                           {k: jnp.asarray(v)
                            for k, v in self._hyper_np.items()})
            self._fit_step = None      # restored buffers replace donated
            self._aux_buf = []
        OpProfiler.get().gauge("fleet/members",
                               int(self._alive_np.sum()))

    # -- stacked inference (population hooks) ------------------------------
    def output(self, x, params=None, per_member: bool = True):
        """Vmapped inference over the fleet: ``x`` [M, B, ...] (or one
        shared batch with ``per_member=False``) → stacked outputs
        [M, B, ...]. ``params``/states default to the live fleet state;
        pass an explicit (params, states) pair for target-network-style
        frozen copies (rl.population). One trace, reused forever."""
        xv = jnp.asarray(x)
        if not per_member:
            xv = jnp.broadcast_to(xv, (self.n_members,) + xv.shape)
        with self._lock:
            if self._infer_fn is None:
                def infer(p, s, xin, key):
                    out, _ = self.model._forward(p, s, xin, False, key)
                    return out

                self._infer_fn = xprof.register_jit(
                    "fleet/infer",
                    jax.jit(jax.vmap(infer, in_axes=(0, 0, 0, None))))
            fn = self._infer_fn
            p, s = ((self._params, self._states) if params is None
                    else params)
        return fn(p, s, xv, jax.random.PRNGKey(0))

    def stacked_state(self):
        """Owning copies of the live (params, states) stacks — a frozen
        target-network snapshot for RL populations."""
        with self._lock:
            return (jax.tree.map(jnp.array, self._params),
                    jax.tree.map(jnp.array, self._states))
