"""Ring attention: sequence/context parallelism over the device mesh.

The reference has NO sequence parallelism (SURVEY.md §5.7 marks it absent —
its sequence handling tops out at TBPTT + masking); on TPU it is the natural
long-context mechanism, so the rebuild provides it natively, per the survey's
stretch plan: shard the SEQUENCE axis across devices, keep each device's Q
block resident, and rotate K/V blocks around the ring with ``ppermute`` so
every Q block attends over the full sequence while only ever holding one K/V
block — O(T/N) activation memory per device, ICI-bandwidth-friendly
neighbor-only communication (the Ring Attention construction of Liu et al.,
blockwise-parallel attention; see PAPERS.md).

Numerics: per-block online softmax (flash-attention style running max /
normalizer), so results match full attention to float tolerance — verified
against the dense ``multi_head_dot_product_attention`` op in tests on the
virtual 8-device CPU mesh.

Layout: [B, T, H, D] with T sharded over the mesh's sequence axis inside a
``shard_map``; causal masking uses global block offsets.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax < 0.5 has no varying-type system: pvary is the identity there (the
# collective-type checker it informs does not exist either)
_pvary = getattr(lax, "pvary", lambda x, axis_name: x)


def _block_attend(q, k, v, bias_fn, m_prev, l_prev, o_prev):
    """One online-softmax accumulation step over a K/V block.

    q [B, Tq, H, D]; k/v [B, Tk, H, D]; running (m, l, o) from prior
    blocks. Returns updated (m, l, o)."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
        jnp.asarray(q.shape[-1], q.dtype))
    logits = bias_fn(logits)
    m_blk = jnp.max(logits, axis=-1)                      # [B, H, Tq]
    m_new = jnp.maximum(m_prev, m_blk)
    # guard fully-masked blocks (max = -inf): exp(-inf - -inf) -> nan
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(logits - safe_m[..., None])
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    scale = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - safe_m), 0.0)
    l_new = l_prev * scale + jnp.sum(p, axis=-1)
    o_new = (o_prev * scale[..., None]
             + jnp.einsum("bhqk,bkhd->bhqd", p, v))
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: str, causal: bool = False):
    """Blockwise ring attention INSIDE a shard_map/pmap over ``axis_name``.

    q/k/v: this device's sequence block, [B, T_local, H, D]. Every device
    starts with its own K/V block and passes it to the next ring neighbor
    each step; after N steps every Q block has attended over the full
    sequence. Communication is neighbor-only ``ppermute`` (rides ICI).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = idx * t_local + jnp.arange(t_local)           # global Q rows

    def bias_for(kv_idx):
        def bias_fn(logits):
            if not causal:
                return logits
            k_pos = kv_idx * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]       # [Tq, Tk]
            neg = jnp.asarray(jnp.finfo(logits.dtype).min, logits.dtype)
            return jnp.where(mask[None, None], logits, neg)

        return bias_fn

    # mark the accumulators device-varying so shard_map's collective-type
    # checker accepts them as scan carries alongside the rotating K/V
    m0 = _pvary(jnp.full((b, h, t_local), -jnp.inf, q.dtype), axis_name)
    l0 = _pvary(jnp.zeros((b, h, t_local), q.dtype), axis_name)
    o0 = _pvary(jnp.zeros((b, h, t_local, d), q.dtype), axis_name)

    def step(carry, i):
        k_blk, v_blk, kv_idx, m, l, o = carry
        m, l, o = _block_attend(q, k_blk, v_blk, bias_for(kv_idx), m, l, o)
        # rotate K/V to the next ring neighbor (no-op payload on last step
        # still keeps the collective schedule uniform)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        kv_nxt = (kv_idx - 1) % n
        return (k_nxt, v_nxt, kv_nxt, m, l, o), None

    (_, _, _, m, l, o), _ = lax.scan(
        step, (k, v, idx, m0, l0, o0), jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-30)            # [B, H, Tq, D]
    return jnp.transpose(out, (0, 2, 1, 3))               # [B, Tq, H, D]


def ring_self_attention(x, wq, wk, wv, wo, n_heads: int, mesh: Mesh,
                        seq_axis: str = "data", causal: bool = False):
    """Driver: full multi-head self-attention with the SEQUENCE sharded
    over ``seq_axis`` — projections are local (position-wise), the
    attention core is ``ring_attention``. x: [B, T, F] (T divisible by the
    mesh axis size); returns [B, T, n_out].
    """
    from jax.experimental.shard_map import shard_map

    def local(x_blk, wq, wk, wv, wo):
        b, t, f = x_blk.shape

        def proj(w):
            p = jnp.einsum("btf,fd->btd", x_blk, w)
            return p.reshape(b, t, n_heads, -1)

        q, k, v = proj(wq), proj(wk), proj(wv)
        ctx = ring_attention(q, k, v, seq_axis, causal=causal)
        return jnp.einsum("btd,do->bto", ctx.reshape(b, t, -1), wo)

    # check_rep=False: jax-0.4's replication checker cannot type the ring
    # scan's rotating K/V carries under differentiation (newer jax resolves
    # them through pvary varying types); the collective schedule is correct
    # either way
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(None, seq_axis, None), P(), P(), P(), P()),
        out_specs=P(None, seq_axis, None), check_rep=False)
    # graftlint: disable=executable-census -- a fresh jit is constructed
    # per call (functional helper, jax's jit cache dedupes the trace);
    # the census tracks long-lived executables, not per-call wrappers
    return jax.jit(fn)(x, wq, wk, wv, wo)
