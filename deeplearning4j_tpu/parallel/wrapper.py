"""ParallelWrapper — data-parallel training over a device mesh.

Reference: dl4j-scaleout ``org.deeplearning4j.parallelism.ParallelWrapper``
(+ ``trainer/{DefaultTrainer,SymmetricTrainer}``; SURVEY.md §2.4, §3.5).

The reference clones the model per GPU, pins trainer threads to devices, and
exchanges threshold-encoded gradients through host-RAM queues. On TPU this
whole topology is ONE SPMD program: the train step runs under ``shard_map``
over the mesh's ``data`` axis with the minibatch sharded and params
replicated; the accumulator's ``reduce_gradients`` (a ``pmean`` over ICI for
the default dense accumulator) is compiled into the step. Both reference
training modes collapse to the synchronous collective:

- SHARED_GRADIENTS → psum of gradients every step (exactly this program);
- AVERAGING (params averaged every N iters) → mathematically subsumed by
  per-step gradient averaging; accepted and treated as the same program
  (documented divergence: no stale-average window exists to configure).
"""

from __future__ import annotations

from functools import partial
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..data.dataset import DataSet
from ..ndarray.rng import get_random
from .accumulator import DenseAllReduceAccumulator, GradientsAccumulator
from .mesh import make_mesh, shard_batch


class ParallelWrapper:
    class Builder:
        def __init__(self, model):
            self._model = model
            self._workers: Optional[int] = None
            self._mode = "shared_gradients"
            self._accumulator: Optional[GradientsAccumulator] = None
            self._prefetch = 2
            self._averaging_frequency = 1
            self._model_axis = 1

        def workers(self, n: int) -> "ParallelWrapper.Builder":
            self._workers = n
            return self

        def model_axis(self, m: int) -> "ParallelWrapper.Builder":
            """Devices along the mesh's ``model`` axis (workers must divide
            by it). Layers with a ``table_sharding`` config (EmbeddingLayer
            family) shard their tables over this axis — the product-API
            route into the sharded-embedding machinery (SURVEY §2.4 row 4)."""
            self._model_axis = int(m)
            return self

        def training_mode(self, mode: str) -> "ParallelWrapper.Builder":
            mode = mode.lower()
            if mode not in ("shared_gradients", "averaging"):
                raise ValueError(f"unknown training mode {mode!r}")
            self._mode = mode
            return self

        trainingMode = training_mode

        def gradients_accumulator(self, acc: GradientsAccumulator) -> "ParallelWrapper.Builder":
            self._accumulator = acc
            return self

        def averaging_frequency(self, n: int) -> "ParallelWrapper.Builder":
            self._averaging_frequency = n  # accepted for parity; see module doc
            return self

        def prefetch_buffer(self, n: int) -> "ParallelWrapper.Builder":
            self._prefetch = n
            return self

        def build(self) -> "ParallelWrapper":
            return ParallelWrapper(self._model, self._workers, self._mode,
                                   self._accumulator
                                   or DenseAllReduceAccumulator(),
                                   model_axis=self._model_axis)

    def __init__(self, model, workers: Optional[int], mode: str,
                 accumulator: GradientsAccumulator, model_axis: int = 1):
        self.model = model
        n = workers or len(jax.devices())
        if n % model_axis:
            raise ValueError(
                f"workers={n} not divisible by model_axis={model_axis}")
        self.mesh = make_mesh(data=n // model_axis, model=model_axis,
                              devices=jax.devices()[:n])
        self.workers_count = n // model_axis   # data-parallel shards
        self.model_axis = model_axis
        self.mode = mode
        self.accumulator = accumulator
        self._step = None
        self._listeners: List[Any] = []

    def set_listeners(self, *ls) -> None:
        self._listeners = list(ls)

    # ------------------------------------------------------------------
    def _build_step(self):
        model = self.model
        updater = model.conf.global_conf.updater
        acc = self.accumulator
        axis = acc.axis_name
        is_graph = hasattr(model, "conf") and hasattr(model.conf, "network_inputs")

        def local_step(params, states, upd_state, x, y, mask, w, key, it):
            idx = jax.lax.axis_index(axis)
            key = jax.random.fold_in(key, idx)

            def loss_fn(p):
                if is_graph:
                    inputs = {model.conf.network_inputs[0]: x}
                    out_name = model.conf.network_outputs[0]
                    loss, new_states = model._loss(p, states, inputs,
                                                   {out_name: y}, {out_name: mask},
                                                   True, key)
                else:
                    loss, new_states = model._loss(p, states, x, y, mask, True, key)
                # The loss mean divides by the PADDED per-shard batch; rescale
                # so remainder batches match the single-device semantics of
                # mean-over-real-examples (w: 1=real, 0=pad). Grads scale too.
                total = w.shape[0] * jax.lax.psum(1.0, axis)
                real = jax.lax.psum(jnp.sum(w), axis)
                loss = loss * total / jnp.maximum(real, 1.0)
                return loss, new_states

            (loss, new_states), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            grads = acc.reduce_gradients(grads)
            loss = jax.lax.pmean(loss, axis)
            # keep batchnorm running stats consistent across shards
            new_states = jax.tree.map(
                lambda s: jax.lax.pmean(s, axis)
                if jnp.issubdtype(s.dtype, jnp.floating) else s, new_states)
            new_params, new_upd = updater.apply(grads, upd_state, params, it)
            return new_params, new_states, new_upd, loss

        pspec = self._param_specs()
        uspec = self._upd_specs(pspec)
        sharded = shard_map(
            local_step, mesh=self.mesh,
            in_specs=(pspec, P(), uspec, P("data"), P("data"), P("data"),
                      P("data"), P(), P()),
            out_specs=(pspec, P(), uspec, P()),
            check_rep=False)
        return jax.jit(sharded, donate_argnums=(0, 1, 2))

    def _param_specs(self):
        """Per-layer partition specs: replicated except row-sharded
        embedding tables (layers carrying ``table_sharding``)."""
        model = self.model
        if not hasattr(model.conf, "layers"):    # ComputationGraph
            for name, node in getattr(model.conf, "nodes", {}).items():
                lyr = getattr(node, "layer", None)
                if getattr(lyr, "table_sharding", None):
                    raise NotImplementedError(
                        "table_sharding through ParallelWrapper is wired "
                        "for MultiLayerNetwork; ComputationGraph tables "
                        "are not routed yet")
            return P()
        specs = []
        for layer in model.conf.layers:
            ax = getattr(layer, "table_sharding", None)
            if not ax:
                specs.append(P())
                continue
            if ax not in self.mesh.shape:
                raise ValueError(f"table_sharding={ax!r} is not a mesh "
                                 f"axis of {tuple(self.mesh.shape)}")
            n_sh = self.mesh.shape[ax]
            if layer.n_in is None or layer.n_in % n_sh:
                raise ValueError(
                    f"embedding vocab {layer.n_in} must be divisible by "
                    f"the {ax!r} axis size {n_sh} (pad the vocab)")
            specs.append({"W": P(ax, None)})
        return specs

    def _upd_specs(self, pspec):
        """Updater state mirrors params per top-level key (Adam m/v,
        Nesterov v, ...) — shard those subtrees like the params."""
        upd_state = self.model._updater_state
        if not isinstance(upd_state, dict) or not upd_state:
            return P()
        pstruct = jax.tree.structure(self.model._params)
        return {k: (pspec if jax.tree.structure(v) == pstruct else P())
                for k, v in upd_state.items()}

    def fit(self, data, epochs: int = 1) -> None:
        model = self.model
        model._check_init()
        if model._updater_state is None:
            model._updater_state = model.conf.global_conf.updater.init(model._params)
        if self._step is None:
            self._step = self._build_step()
        n = self.workers_count
        for _ in range(max(1, epochs)):
            for ds in _iter(data):
                x = np.asarray(ds.features.to_numpy())
                y = np.asarray(ds.labels.to_numpy())
                mask = (np.asarray(ds.labels_mask.to_numpy(), np.float32)
                        if ds.labels_mask is not None
                        else np.ones((x.shape[0],), np.float32))
                w = np.ones((x.shape[0],), np.float32)
                if x.shape[0] % n:
                    # pad by wrapping REAL rows (keeps BatchNorm batch stats
                    # sane — zero rows would pollute them) but zero their
                    # loss-mask and example-weight so padded rows contribute
                    # nothing to loss/gradients and the loss renormalizes to
                    # mean-over-real-examples (see local_step)
                    pad = n - x.shape[0] % n
                    x = np.concatenate([x, x[:pad]])
                    y = np.concatenate([y, y[:pad]])
                    mask = np.concatenate(
                        [mask, np.zeros((pad,) + mask.shape[1:], mask.dtype)])
                    w = np.concatenate([w, np.zeros((pad,), np.float32)])
                xs, ys, ms, ws = shard_batch(self.mesh, x, y, mask, w)
                key = get_random().next_key()
                (model._params, model._states, model._updater_state, loss) = \
                    self._step(model._params, model._states, model._updater_state,
                               xs, ys, ms, ws, key, jnp.asarray(model._iteration))
                model._iteration += 1
                model._score_dev = loss
                for lst in self._listeners:
                    lst.iteration_done(model, model._iteration, loss)

    def shutdown(self) -> None:
        self._step = None


def _iter(data):
    if hasattr(data, "reset") and hasattr(data, "__iter__"):
        data.reset()
        yield from data
        return
    if isinstance(data, DataSet):
        yield data
        return
    if isinstance(data, tuple) and len(data) == 2:
        yield DataSet(data[0], data[1])
        return
    raise TypeError(f"cannot iterate {type(data)}")
