"""ParallelWrapper — data-parallel training over a device mesh.

Reference: dl4j-scaleout ``org.deeplearning4j.parallelism.ParallelWrapper``
(+ ``trainer/{DefaultTrainer,SymmetricTrainer}``; SURVEY.md §2.4, §3.5).

The reference clones the model per GPU, pins trainer threads to devices, and
exchanges threshold-encoded gradients through host-RAM queues. On TPU this
whole topology is ONE SPMD program: the train step runs under ``shard_map``
over the mesh's ``data`` axis with the minibatch sharded and params
replicated; the accumulator's ``reduce_gradients`` (a ``pmean`` over ICI for
the default dense accumulator) is compiled into the step. Both reference
training modes collapse to the synchronous collective:

- SHARED_GRADIENTS → psum of gradients every step (exactly this program);
- AVERAGING (params averaged every N iters) → mathematically subsumed by
  per-step gradient averaging; accepted and treated as the same program
  (documented divergence: no stale-average window exists to configure).
"""

from __future__ import annotations

from functools import partial
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

import logging

from ..common import faultinject, flightrec, xprof
from ..common import integrity as _integ
from ..common.profiler import OpProfiler
from ..data import pipeline as _pipe
from ..data.dataset import DataSet
from ..ndarray.rng import get_random
from ..nn.multilayer import _apply_fused_flat, _fused_flat_plan, _same_shapes
from .accumulator import DenseAllReduceAccumulator, GradientsAccumulator
from .mesh import elastic_pool, make_mesh, probe_device, shard_batch
from .sharding import Zero1Plan, is_flat_state

logger = logging.getLogger("deeplearning4j_tpu")


class ParallelWrapper:
    class Builder:
        def __init__(self, model):
            self._model = model
            self._workers: Optional[int] = None
            self._mode = "shared_gradients"
            self._accumulator: Optional[GradientsAccumulator] = None
            self._prefetch = 2
            self._averaging_frequency = 1
            self._model_axis = 1

        def workers(self, n: int) -> "ParallelWrapper.Builder":
            self._workers = n
            return self

        def model_axis(self, m: int) -> "ParallelWrapper.Builder":
            """Devices along the mesh's ``model`` axis (workers must divide
            by it). Layers with a ``table_sharding`` config (EmbeddingLayer
            family) shard their tables over this axis — the product-API
            route into the sharded-embedding machinery (SURVEY §2.4 row 4)."""
            self._model_axis = int(m)
            return self

        def training_mode(self, mode: str) -> "ParallelWrapper.Builder":
            mode = mode.lower()
            if mode not in ("shared_gradients", "averaging"):
                raise ValueError(f"unknown training mode {mode!r}")
            self._mode = mode
            return self

        trainingMode = training_mode

        def gradients_accumulator(self, acc: GradientsAccumulator) -> "ParallelWrapper.Builder":
            self._accumulator = acc
            return self

        def averaging_frequency(self, n: int) -> "ParallelWrapper.Builder":
            self._averaging_frequency = n  # accepted for parity; see module doc
            return self

        def prefetch_buffer(self, n: int) -> "ParallelWrapper.Builder":
            self._prefetch = n
            return self

        def build(self) -> "ParallelWrapper":
            return ParallelWrapper(self._model, self._workers, self._mode,
                                   self._accumulator
                                   or DenseAllReduceAccumulator(),
                                   model_axis=self._model_axis,
                                   prefetch=self._prefetch)

    def __init__(self, model, workers: Optional[int], mode: str,
                 accumulator: GradientsAccumulator, model_axis: int = 1,
                 prefetch: int = 2):
        self.model = model
        n = workers or len(jax.devices())
        if n % model_axis:
            raise ValueError(
                f"workers={n} not divisible by model_axis={model_axis}")
        self.mesh = make_mesh(data=n // model_axis, model=model_axis,
                              devices=jax.devices()[:n])
        self.workers_count = n // model_axis   # data-parallel shards
        self.model_axis = model_axis
        self.mode = mode
        self.accumulator = accumulator
        self.prefetch = prefetch
        self._step = None
        self._chunk_step = None
        self._telemetry = None
        self._listeners: List[Any] = []
        self._zero1_plan = None
        # per-worker-count compiled artifacts (step, chunk step, plan,
        # mesh), stashed/restored by resize(): growing back to a count
        # already trained at must NOT recompile — the elastic contract is
        # one compile per worker count per fit config
        self._exec_cache: dict = {}
        self._lost_devices: set = set()   # once-lost, not yet probed healthy
        self._coll_bytes: dict = {}       # static bytes per collective kind
        self._drained_encoded = (0.0, 0.0, 0)   # nnz/elems/steps last drain

    def set_listeners(self, *ls) -> None:
        self._listeners = list(ls)
        for lst in self._listeners:
            # checkpoint-style listeners snapshot their peers' state for
            # exact resume (see MultiLayerNetwork.set_listeners)
            bind = getattr(lst, "bind_group", None)
            if callable(bind):
                bind(self._listeners)
        from ..optimize.telemetry import config_for

        cfg = config_for(self._listeners)
        if cfg != self._telemetry:
            # in-graph telemetry is a build-time property of the SPMD step
            # (see MultiLayerNetwork.set_listeners); the aux statistics are
            # aggregated across shards with the same collectives as the
            # weight update
            self._telemetry = cfg
            self._step = None
            self._chunk_step = None
            self._exec_cache.clear()   # telemetry is baked into the steps

    # ------------------------------------------------------------------
    def _local_core(self):
        """The per-shard train step, shared by the per-step shard_map and
        the steps_per_dispatch scan (one definition, no drift).

        Three gradient-exchange/updater layouts, selected by the
        accumulator (see parallel/accumulator.py):

        - dense (default): pmean the grads, every replica applies the full
          updater redundantly;
        - encoded (``stateful``): threshold-encode with residual carry,
          psum the encoded update, dense updater apply — the accumulator
          state pytree threads through the step (and scan chunks);
        - ZeRO-1 (``zero1``): reduce-scatter the flat grads, apply the
          updater to this replica's 1/N flat slice against SHARDED updater
          state, all-gather the updated params. Bit-identical to dense on
          the same replica count: the flat layout is a pure permutation,
          the built-in updaters are elementwise, and psum_scatter's
          accumulation order matches psum's.
        """
        model = self.model
        updater = model.conf.global_conf.updater
        acc = self.accumulator
        axis = acc.axis_name
        zero1 = acc.zero1
        stateful = acc.stateful
        plan = self._zero1_plan if zero1 else None
        is_graph = hasattr(model, "conf") and hasattr(model.conf, "network_inputs")
        tele = self._telemetry
        from ..learning import precision as _prec
        from ..ops import pallas_update as _pupd
        from ..optimize import telemetry as _tel

        stats = tele is not None and tele.stats
        integ = tele.integrity_every if tele is not None else 0
        if integ and not zero1:
            pspec = self._param_specs()
            specs = ([] if pspec == P() else
                     jax.tree.leaves(pspec,
                                     is_leaf=lambda s: isinstance(s, P)))
            if self.model_axis != 1 or any(s != P() for s in specs):
                raise NotImplementedError(
                    "integrity fingerprints police the replicated-state "
                    "invariant — model-sharded params have no replica "
                    "copies to compare")

        # Backward-epilogue fusion (mirrors the solo _step_core): when the
        # updater consumes FLAT buckets anyway (ZeRO-1 always; dense when
        # `fused_update` is on), differentiate w.r.t. the flat params — the
        # forward unflattens them (a pure permutation), so the cotangents
        # accumulate directly into flat layout and the dense grad pytree
        # never materializes between the backward and the exchange. Gated
        # off when telemetry stats need the raw dense per-shard grads
        # (nonfinite_counts / layer_stats walk the layer tree) and for
        # stateful accumulators (residual carry is a dense-tree pytree) —
        # a stats-off aux (integrity fingerprints only) keeps it on.
        dense_fused_plan = (None if (zero1 or stateful or stats)
                            else _fused_flat_plan(model.conf, model._params))
        flat_bwd = (not stats and not stateful
                    and getattr(model.conf.global_conf, "flat_backward",
                                True)
                    and (zero1 or dense_fused_plan is not None))
        bwd_plan = plan if zero1 else dense_fused_plan

        def local_step(params, states, upd_state, acc_state, x, y, mask, w,
                       key, it):
            idx = jax.lax.axis_index(axis)
            key = jax.random.fold_in(key, idx)
            # Per-shard weighted data loss with a GLOBAL divisor: each shard
            # divides its weighted sum by global_real/num_shards, so the
            # pmean of per-shard losses (and of their grads) is exactly the
            # mean over real examples across the whole batch — pad rows
            # (w=0) contribute nothing and, unlike a whole-loss rescale,
            # the regularization term is never inflated.
            n_shards = jax.lax.psum(1.0, axis)
            real = jax.lax.psum(jnp.sum(w), axis)
            denom = jnp.maximum(real, 1.0) / n_shards

            def loss_fn(p):
                if is_graph:
                    inputs = {model.conf.network_inputs[0]: x}
                    out_name = model.conf.network_outputs[0]
                    loss, new_states = model._loss(p, states, inputs,
                                                   {out_name: y}, {out_name: mask},
                                                   True, key, w=w,
                                                   w_denom=denom)
                else:
                    loss, new_states = model._loss(p, states, x, y, mask,
                                                   True, key, w=w,
                                                   w_denom=denom)
                return loss, new_states

            if flat_bwd:
                flat_params = bwd_plan.flatten(params)
                (loss, new_states), flat_grads = jax.value_and_grad(
                    lambda fp: loss_fn(bwd_plan.unflatten_diff(fp)),
                    has_aux=True)(flat_params)
                OpProfiler.get().gauge("precision/grads_flat_in_step", 1)
                grads = None
            else:
                (loss, new_states), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
            if stats:
                # non-finite counts are taken on the RAW per-shard grads
                # (reduction would smear one shard's NaN across all of
                # them) and aggregated with the same collective family as
                # the weight update
                raw_nf = jax.lax.psum(_tel.nonfinite_counts(grads), axis)
            density = None
            if stateful:
                grads, acc_state, density = acc.exchange(grads, acc_state,
                                                         axis)
            loss = jax.lax.pmean(loss, axis)
            # keep batchnorm running stats consistent across shards
            new_states = jax.tree.map(
                lambda s: jax.lax.pmean(s, axis)
                if jnp.issubdtype(s.dtype, jnp.floating) else s, new_states)
            if zero1:
                # ZeRO-1: mean-reduce-scatter the flat grads, update only
                # this replica's even slice of params+state, gather back.
                # The update itself runs through the fused flat-bucket
                # kernel (ops/pallas_update — one launch per dtype bucket;
                # fp32 bitwise-identical to the per-leaf path) with the
                # generic elementwise fallback for updaters it doesn't
                # cover; `key` (already folded per-replica) drives the
                # bf16-state stochastic rounding when state_dtype is set.
                flat_g = flat_grads if flat_bwd else plan.flatten(grads)
                g_sh = {k: jax.lax.psum_scatter(
                    v, axis, scatter_dimension=0, tiled=True)
                    / jnp.asarray(n_shards, v.dtype)
                    for k, v in flat_g.items()}
                flat_p = flat_params if flat_bwd else plan.flatten(params)
                p_sh = plan.shard_slice(flat_p, idx)
                new_p_sh, new_upd = _pupd.apply_flat_updater(
                    updater, p_sh, g_sh, upd_state, it, key)
                gathered = {k: jax.lax.all_gather(v, axis, tiled=True)
                            for k, v in new_p_sh.items()}
                new_params = plan.unflatten(gathered)
            elif flat_bwd:
                # dense data-parallel fused epilogue: pmean the FLAT buckets
                # (elementwise — bitwise-equal to flattening the pmean'd
                # dense tree) and run the fused grad+update in the same
                # compiled step, full-width on every replica
                flat_grads = acc.reduce_gradients(flat_grads)
                new_params, new_upd = _apply_fused_flat(
                    dense_fused_plan, updater, flat_grads, upd_state,
                    params, it, key, flat_params=flat_params,
                    grads_flat=True)
            else:
                if not stateful:
                    grads = acc.reduce_gradients(grads)
                new_params, new_upd = _prec.apply_updater(
                    updater, grads, upd_state, params, it, key)
            if tele is None:
                return new_params, new_states, new_upd, acc_state, loss
            if not stats:
                # integrity-only aux: the loss plus the consistency
                # verdict below — no per-layer stats, no dense grads
                aux = {"loss": loss}
            elif zero1:
                # per-layer norms from the flat shards: segment-summed
                # locally, psum'd across the data axis (the full gradient/
                # update tensors are never materialized for telemetry)
                parts = [(plan.shard_segment_ids(b.key, idx, b.shard),
                          g_sh[b.key], new_p_sh[b.key], p_sh[b.key])  # graftlint: disable=donated-grad-escape -- in-graph read: XLA keeps the traced g_sh shards alive for the stats; donation frees only jit-boundary buffers
                         for b in plan.buckets]
                aux = _tel.sharded_layer_stats(loss, parts, plan.n_layers,
                                               axis, nonfinite=raw_nf)
            else:
                # norms on the REDUCED grads / updated params: replicated
                # values, identical on every shard
                aux = _tel.layer_stats(params, new_params, grads, loss,
                                       nonfinite=raw_nf)
            if density is not None:
                # encoded-exchange density rides the telemetry aux into
                # the metrics bus alongside the profiler ledger
                aux["exchange_density"] = density
            if tele.nan_guard:
                aux, new_params, new_states, new_upd = _tel.apply_nan_guard(
                    aux, new_params, params, new_states, states, new_upd,
                    upd_state)
            if integ:
                # Replica-consistency fingerprint (common.integrity): the
                # O(params) bitcast fold of the step's INPUT state — the
                # state every replica stored from the previous step, which
                # the data-parallel contract requires bitwise-identical —
                # runs under a lax.cond every `integrity_every` steps (the
                # alive-mask pattern: predicated fold, no retrace). Only
                # the 4-byte digest and the tile-transport bit travel:
                # their all_gather runs unconditionally so no collective
                # ever sits inside a cond arm.
                do_check = (it % integ) == 0
                zero_fp = jnp.zeros((), jnp.uint32)
                if zero1:
                    # digest the unpadded flat buckets (no dense
                    # materialization), and cross-check the tile this
                    # replica republished against what the all_gather
                    # round-tripped — a corrupt interconnect receive
                    # flags the observing replica
                    fp_p, fp_chk = jax.lax.cond(
                        do_check,
                        lambda: (lambda f: (f, f))(
                            _integ.fingerprint_flats(plan, flat_p)),
                        lambda: (zero_fp, zero_fp))
                    mism = jax.lax.cond(
                        do_check,
                        lambda: jnp.any(jnp.stack([
                            _integ.bitwise_neq(
                                plan.shard_slice(gathered, idx)[b.key],
                                new_p_sh[b.key])
                            for b in plan.buckets])).astype(jnp.int32),
                        lambda: jnp.zeros((), jnp.int32))
                else:
                    # dense: params AND the replicated updater state must
                    # match — a desynced Adam moment corrupts training
                    # just as surely as a desynced weight
                    fp_p, fp_chk = jax.lax.cond(
                        do_check,
                        lambda: (lambda f: (f, _integ.combine_fp(
                            f, _integ.fingerprint_tree(upd_state))))(
                            _integ.fingerprint_tree(params)),
                        lambda: (zero_fp, zero_fp))
                    mism = jnp.zeros((), jnp.int32)
                checked, diverged, replica = _integ.replica_verdict(
                    fp_chk, mism, axis, do_check)
                aux["integrity_checked"] = checked
                aux["integrity_diverged"] = diverged
                aux["integrity_replica"] = replica
                aux["integrity_fp"] = fp_p
                # freeze-on-divergence (the nan-guard pattern): survivors
                # carry their clean pre-step state to the quarantine
                # boundary; the corrupt replica's output stays its own
                # poisoned input, so the fault persists and re-detects
                ok = diverged == 0
                keep = lambda nw, od: jnp.where(ok, nw, od)
                new_params = jax.tree.map(keep, new_params, params)
                new_states = jax.tree.map(keep, new_states, states)
                new_upd = jax.tree.map(keep, new_upd, upd_state)
            return new_params, new_states, new_upd, acc_state, loss, aux

        return local_step

    def _build_step(self):
        local_step = self._local_core()
        pspec = self._param_specs()
        uspec = self._upd_specs(pspec)
        aspec = self.accumulator.state_specs(self.model._params)
        out_specs = (pspec, P(), uspec, aspec, P())
        if self._telemetry is not None:
            out_specs += (P(),)    # aux pytree: replicated device scalars
        sharded = shard_map(
            local_step, mesh=self.mesh,
            in_specs=(pspec, P(), uspec, aspec, P("data"), P("data"),
                      P("data"), P("data"), P(), P()),
            out_specs=out_specs,
            check_rep=False)

        def step(*args):
            OpProfiler.get().count("trace/pw_fit_step")
            return sharded(*args)

        return xprof.register_jit(
            "pw/fit_step", jax.jit(step, donate_argnums=(0, 1, 2, 3)),
            donate=(0, 1, 2, 3))

    def _build_chunk_step(self):
        """steps_per_dispatch=K: each shard scans its K local slices of the
        stacked chunk inside ONE SPMD program — the per-step collectives
        (gradient psum/reduce-scatter, loss/stats pmean) run inside the
        scan body, and Python dispatch + listener sync amortize over K
        steps. The updater-state and accumulator-state layouts (sharded
        flat buckets / residual carries) thread through the scan carry
        unchanged."""
        local_step = self._local_core()
        tele = self._telemetry

        def local_chunk(params, states, upd_state, acc_state, xs, ys, masks,
                        ws, keys, it0):
            def body(carry, inp):
                params, states, upd_state, acc_state, it = carry
                x, y, m, w, k = inp
                out = local_step(params, states, upd_state, acc_state, x, y,
                                 m, w, k, it)
                if tele is None:
                    params, states, upd_state, acc_state, loss = out
                    return (params, states, upd_state, acc_state,
                            it + 1), loss
                params, states, upd_state, acc_state, loss, aux = out
                return (params, states, upd_state, acc_state,
                        it + 1), (loss, aux)

            (params, states, upd_state, acc_state, _), ys_out = jax.lax.scan(
                body, (params, states, upd_state, acc_state, it0),
                (xs, ys, masks, ws, keys))
            if tele is None:
                return params, states, upd_state, acc_state, ys_out
            losses, auxes = ys_out
            return params, states, upd_state, acc_state, losses, auxes

        pspec = self._param_specs()
        uspec = self._upd_specs(pspec)
        aspec = self.accumulator.state_specs(self.model._params)
        batch = P(None, "data")   # [K, B, ...]: stack axis whole, B sharded
        out_specs = (pspec, P(), uspec, aspec, P())
        if tele is not None:
            out_specs += (P(),)
        sharded = shard_map(
            local_chunk, mesh=self.mesh,
            in_specs=(pspec, P(), uspec, aspec, batch, batch, batch, batch,
                      P(), P()),
            out_specs=out_specs,
            check_rep=False)

        def chunk(*args):
            OpProfiler.get().count("trace/pw_fit_chunk")
            return sharded(*args)

        return xprof.register_jit(
            "pw/fit_chunk", jax.jit(chunk, donate_argnums=(0, 1, 2, 3)),
            donate=(0, 1, 2, 3))

    def _param_specs(self):
        """Per-layer partition specs: replicated except row-sharded
        embedding tables (layers carrying ``table_sharding``)."""
        model = self.model
        if not hasattr(model.conf, "layers"):    # ComputationGraph
            for name, node in getattr(model.conf, "nodes", {}).items():
                lyr = getattr(node, "layer", None)
                if getattr(lyr, "table_sharding", None):
                    raise NotImplementedError(
                        "table_sharding through ParallelWrapper is wired "
                        "for MultiLayerNetwork; ComputationGraph tables "
                        "are not routed yet")
            return P()
        specs = []
        for layer in model.conf.layers:
            ax = getattr(layer, "table_sharding", None)
            if not ax:
                specs.append(P())
                continue
            if ax not in self.mesh.shape:
                raise ValueError(f"table_sharding={ax!r} is not a mesh "
                                 f"axis of {tuple(self.mesh.shape)}")
            n_sh = self.mesh.shape[ax]
            if layer.n_in is None or layer.n_in % n_sh:
                raise ValueError(
                    f"embedding vocab {layer.n_in} must be divisible by "
                    f"the {ax!r} axis size {n_sh} (pad the vocab)")
            specs.append({"W": P(ax, None)})
        return specs

    def _upd_specs(self, pspec):
        """Updater state mirrors params per top-level key (Adam m/v,
        Nesterov v, ...) — shard those subtrees like the params. Under
        ZeRO-1 the state is flat buckets, every leaf split evenly over the
        data axis (the whole point: 1/N of the state per replica)."""
        upd_state = self.model._updater_state
        if not isinstance(upd_state, dict) or not upd_state:
            return P()
        if self.accumulator.zero1:
            return jax.tree.map(lambda _: P("data"), upd_state)
        pstruct = jax.tree.structure(self.model._params)
        return {k: (pspec if jax.tree.structure(v) == pstruct else P())
                for k, v in upd_state.items()}

    # ------------------------------------------------------------------
    # training-state layout (ZeRO-1 sharded updater / accumulator state)
    # ------------------------------------------------------------------
    def _place(self, tree, specs):
        """Host/device tree → device arrays placed per spec. ``jnp.array``
        first: an owning copy, never a view of numpy-owned memory — the
        step DONATES these buffers (the PR-3 heap-corruption lesson)."""
        from jax.sharding import NamedSharding

        leaves, treedef = jax.tree.flatten(tree)
        spec_leaves = jax.tree.flatten(
            specs, is_leaf=lambda s: isinstance(s, P))[0]
        placed = [jax.device_put(jnp.array(l), NamedSharding(self.mesh, s))
                  for l, s in zip(leaves, spec_leaves)]
        return jax.tree.unflatten(treedef, placed)

    def _ensure_parallel_state(self) -> None:
        """Bring the model's updater/accumulator state into THIS wrapper's
        layout before the step is (re)built — fresh init, dense↔ZeRO-1
        conversion, and resharding a flat state saved under a different
        worker count (the flat layout is replica-count-independent, so
        only the zero pad tail changes: exact resume across N)."""
        import numpy as np

        model = self.model
        updater = model.conf.global_conf.updater
        acc = self.accumulator
        prof = OpProfiler.get()
        if acc.zero1:
            if not getattr(updater, "elementwise", False):
                raise NotImplementedError(
                    f"{type(updater).__name__} does not declare "
                    "elementwise=True; ZeRO-1 weight-update sharding "
                    "(ReduceScatterAccumulator) requires an elementwise "
                    "updater — use the dense accumulator instead")
            pspec = self._param_specs()
            spec_leaves = ([] if pspec == P() else jax.tree.leaves(
                pspec, is_leaf=lambda s: isinstance(s, P)))
            if self.model_axis != 1 or any(s != P() for s in spec_leaves):
                raise NotImplementedError(
                    "ZeRO-1 sharding assumes replicated params: it cannot "
                    "compose with model_axis/table_sharding yet")
            if self._zero1_plan is None \
                    or self._zero1_plan.n_shards != self.workers_count:
                self._zero1_plan = Zero1Plan(model._params,
                                             self.workers_count)
            plan = self._zero1_plan
            state = model._updater_state
            if self._flat_state_matches_plan(state, plan):
                # already this plan's device layout (a prior fit's step
                # outputs) — re-placing it would be a needless host
                # round-trip, and re-counting would inflate the gauges
                return self._finish_parallel_state(acc, model)
            if state is None:
                # init DIRECTLY in the flat layout (zeros flatten to
                # zeros, so this equals flatten(dense init) exactly).
                # np.array, not np.asarray: device_get views alias
                # donatable buffers (the PR-3 lesson; tools/graftlint
                # enforces the pattern)
                flat_p = plan.flatten(jax.tree.map(np.array,
                                                   jax.device_get(
                                                       model._params)),
                                      xp=np)
                state = updater.init(flat_p)
            elif is_flat_state(state) or isinstance(state, dict) and state:
                # dense tree or differently-padded flat state → this
                # plan's padding (host-side numpy; pure permutation)
                state = plan.reshard_state(jax.device_get(state))
            if isinstance(state, dict) and state:
                uspecs = jax.tree.map(lambda _: P("data"), state)
                state = self._place(state, uspecs)
                total = sum(l.size * l.dtype.itemsize
                            for l in jax.tree.leaves(state))
                prof.count("zero1/updater_state_bytes_total", int(total))
                prof.count("zero1/updater_state_bytes_per_replica",
                           int(total // self.workers_count))
                from ..learning.precision import note_state_bytes

                note_state_bytes(state)
            model._updater_state = state
        else:
            state = model._updater_state
            if is_flat_state(state):
                # ZeRO-1 → dense handoff (e.g. resumed under a dense
                # accumulator): unflatten on host, rematerialize owned
                from .sharding import unflatten_updater_state

                state = unflatten_updater_state(
                    jax.device_get(state),
                    jax.device_get(model._params), xp=np)
                state = jax.tree.map(lambda a: jnp.array(a), state)
                model._updater_state = state
            if model._updater_state is None:
                model._updater_state = updater.init(model._params)
            from ..learning.precision import note_state_bytes

            note_state_bytes(model._updater_state)
        self._finish_parallel_state(acc, model)

    def _flat_state_matches_plan(self, state, plan) -> bool:
        """True when ``state`` is already this plan's PLACED flat layout:
        every bucket leaf a device array of the plan's padded length. A
        flat state from a different worker count fails on shape; host
        (numpy) trees fail on the array type and go through placement."""
        if not is_flat_state(state):
            return False
        for v in state.values():
            if not (isinstance(v, dict) and v):
                continue
            for b in plan.buckets:
                arr = v.get(b.key)
                if not (isinstance(arr, jax.Array)
                        and arr.shape == (b.padded,)):
                    return False
        return True

    def _finish_parallel_state(self, acc, model) -> None:
        """Accumulator-state layout + the static collective byte ledger
        (the tail every `_ensure_parallel_state` path shares)."""
        # accumulator state (encoded exchange: residual carry + threshold)
        if acc.stateful:
            st = getattr(model, "_acc_state", None)
            if not self._acc_state_placed(st):
                aspecs = acc.state_specs(model._params)
                blob = getattr(model, "_acc_blob", None)
                if st is None and blob is not None:
                    st = self._load_acc_blob(blob, acc)
                    model._acc_blob = None
                if st is None:
                    st = acc.init_state(model._params,
                                        n_shards=self.workers_count)
                else:
                    st = self._reshape_acc_state(jax.device_get(st), acc)
                model._acc_state = self._place(st, aspecs)
        else:
            model._acc_state = {}

        # the live worker count rides checkpoints (resume.json) and the
        # elastic health gauge — an elastic run's resume metadata must
        # say how many replicas were actually training
        model._live_workers = self.workers_count
        OpProfiler.get().gauge("elastic/workers", self.workers_count)

        # static per-step collective byte ledger (gradient exchange only)
        param_bytes = int(sum(l.size * np.dtype(l.dtype).itemsize
                              for l in jax.tree.leaves(model._params)))
        if acc.zero1:
            flat = self._zero1_plan.bucket_bytes()
            self._coll_bytes = {"reduce_scatter_bytes": flat,
                                "all_gather_bytes": flat}
        else:
            self._coll_bytes = {"psum_bytes": param_bytes}
        self._coll_bytes["dense_grad_bytes"] = param_bytes

    def _acc_state_placed(self, st) -> bool:
        """True when the live accumulator state already carries this
        wrapper's layout (device arrays, residual leading axis == this
        worker count) — i.e. it came out of this wrapper's own step."""
        if not (isinstance(st, dict) and st and "residual" in st):
            return False
        leaves = jax.tree.leaves(st["residual"])
        return all(isinstance(l, jax.Array) and l.ndim >= 1
                   and l.shape[0] == self.workers_count for l in leaves)

    def _load_acc_blob(self, blob: bytes, acc):
        """Checkpointed accumulator state (raw npz bytes restored by
        util.checkpoint) → host tree against this accumulator's template."""
        from ..util.model_serializer import _load_into_tree

        template = acc.init_state(self.model._params,
                                  n_shards=self.workers_count)
        try:
            return _load_into_tree(blob, template, "accumulator state")
        except Exception:
            import logging

            logging.getLogger("deeplearning4j_tpu").warning(
                "checkpointed accumulator state does not match this "
                "accumulator; starting it fresh")
            return None

    def _reshape_acc_state(self, st, acc):
        """Validate a restored/live accumulator state against this worker
        count. Residuals are PER-REPLICA (leading replica axis): a changed
        worker count makes them meaningless — reset to zero (warned);
        replicated scalars (threshold, ledger counters) carry over."""
        import numpy as np

        res = st.get("residual") if isinstance(st, dict) else None
        if res is None:
            return st
        lead = {l.shape[0] for l in jax.tree.leaves(res)}
        if lead == {self.workers_count}:
            return st
        import logging

        logging.getLogger("deeplearning4j_tpu").warning(
            "encoded-accumulator residuals were saved for %s workers; "
            "resetting them for %d (threshold and ledger carry over)",
            sorted(lead), self.workers_count)
        st = dict(st)
        st["residual"] = jax.tree.map(
            lambda p: np.zeros((self.workers_count,) + tuple(p.shape),
                               np.dtype(p.dtype)), self.model._params)
        return st

    # ------------------------------------------------------------------
    # online elastic resize (shrink/grow the data axis, no restart)
    # ------------------------------------------------------------------
    def resize(self, workers: int, *, lost_replicas=None) -> List[Any]:
        """Online elastic resize of the data axis at a DISPATCH BOUNDARY:
        rebuild the mesh over ``workers`` devices and re-shard the
        training state in memory — no process restart, no disk.

        The state moves are exact by construction: params and layer
        states are replicated (a host-owning copy re-placed by the next
        dispatch), ZeRO-1 flat updater/param buckets reshard through
        ``Zero1Plan``'s replica-count-independent permutation layout (the
        same guarantee as checkpoint resharding — only the zero pad tail
        changes), and the encoded accumulator's per-replica residuals are
        carried through ``resize_state`` (shrink folds the lost replica's
        residual into a survivor so no gradient mass is dropped).
        Compiled steps are stashed per worker count, so a grow-back to a
        count already trained at reuses its executable — one compile per
        worker count, total.

        Consistency model: a resize can observe a partially-applied step
        NEVER. It must only run between dispatches (or after a fit
        unwound at a step boundary), where the holder's published state
        is the complete output of the last compiled step; an in-flight
        ``steps_per_dispatch`` chunk either completes or is abandoned
        wholesale, and the pipeline cursor (`epochs_done`,
        ``steps_in_epoch``) names the exact batch to continue from — pass
        it back through ``fit(resume_cursor=...)``.

        ``lost_replicas``: data-axis indices of replicas whose device is
        gone (from :class:`faultinject.DeviceLostError` or a probe);
        their devices are excluded from the new mesh and remembered
        ACROSS calls — a later resize (even to a cached worker count)
        re-probes every once-lost device and only lets it rejoin after it
        answers, so a stashed mesh can never silently reinstate a
        still-dead device. Returns the devices removed — the supervisor's
        grow-back probe targets.
        """
        n = int(workers)
        if n < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        if self.model_axis != 1:
            raise NotImplementedError(
                "online elastic resize is a data-axis operation; it does "
                "not compose with model_axis/table_sharding yet")
        old_n = self.workers_count
        lost = sorted({int(r) for r in (lost_replicas or ())})
        if any(r < 0 or r >= old_n for r in lost):
            raise ValueError(f"lost_replicas {lost} out of range for "
                             f"{old_n} workers")
        if n == old_n and not lost:
            return []
        prof = OpProfiler.get()
        model = self.model
        with flightrec.span("elastic/resize", severity="warn",
                            workers_from=old_n, workers_to=n, lost=lost), \
                prof.time_section("elastic/resize"):
            # 1) host-materialize the training state with OWNING copies —
            # the compiled steps donate their argument buffers, and on
            # the CPU backend device_get returns zero-copy views (the
            # PR-3 heap-corruption lesson). When replicas are being
            # quarantined, replicated leaves are read from a SURVIVOR's
            # shard: a plain device_get reads shard 0, which may be the
            # silently-corrupted copy the shrink exists to discard.
            live = (model._params, model._states, model._updater_state,
                    getattr(model, "_acc_state", None) or None)
            if lost:
                params, states, upd, acc = \
                    _integ.materialize_from_survivors(
                        live, list(self.mesh.devices.flat), lost)
            else:
                params, states, upd, acc = jax.tree.map(
                    np.array, jax.device_get(live))
            # 2) per-replica accumulator state rides the permutation too
            if acc is not None:
                acc = self.accumulator.resize_state(acc, old_n, n,
                                                    lost_replicas=lost)
            # 3) stash this count's compiled artifacts, then reuse or
            # rebuild the target count's mesh
            mesh_devs = list(self.mesh.devices.flat)
            lost_devs = [mesh_devs[r] for r in lost]
            if self._step is not None or self._chunk_step is not None:
                self._exec_cache[old_n] = {
                    "step": self._step, "chunk": self._chunk_step,
                    "plan": self._zero1_plan, "mesh": self.mesh}
            # once-lost devices are remembered ACROSS calls and re-probed
            # here: a later resize must not silently reinstate a
            # still-dead device from a stashed mesh; a device that
            # answers the probe again is healthy and may rejoin (keeping
            # grow-back on the zero-recompile cached path)
            self._lost_devices = {d for d in self._lost_devices
                                  if not probe_device(d)}
            self._lost_devices |= set(lost_devs)
            excl = set(lost_devs) | self._lost_devices
            cached = self._exec_cache.get(n)
            if cached is not None and not (
                    excl & set(cached["mesh"].devices.flat)):
                self.mesh = cached["mesh"]
                self._step = cached["step"]
                self._chunk_step = cached["chunk"]
                self._zero1_plan = cached["plan"]
            else:
                pool = elastic_pool(self.mesh, exclude=excl)
                if n > len(pool):
                    raise ValueError(
                        f"resize to {n} workers needs {n} devices; only "
                        f"{len(pool)} are available")
                self.mesh = make_mesh(data=n, model=1, devices=pool[:n])
                self._step = None
                self._chunk_step = None
                self._zero1_plan = None
                self._exec_cache.pop(n, None)
            # every old-mesh device NOT in the new mesh left the axis —
            # the named lost devices, plus the tail a shrink without an
            # explicit loss list drops (grow-back probes target them all)
            new_devs = set(self.mesh.devices.flat)
            removed = [d for d in mesh_devs if d not in new_devs]
            self.workers_count = n
            # 4) hand the host state back: replicated trees re-materialize
            # as owning device arrays (the next dispatch places them per
            # its in_specs); the FLAT zero1 updater state stays numpy so
            # _ensure_parallel_state reshards it through the new plan's
            # padding and places it explicitly
            model._params = jax.tree.map(jnp.array, params)
            model._states = jax.tree.map(jnp.array, states)
            if upd is not None and not is_flat_state(upd):
                upd = jax.tree.map(jnp.array, upd)
            model._updater_state = upd
            model._acc_state = acc
            # _finish_parallel_state sets _live_workers + the workers gauge
            self._ensure_parallel_state()
        prof.count("elastic/resizes")
        if n < old_n:
            prof.count("elastic/shrinks")
        elif n > old_n:
            prof.count("elastic/grows")
        logger.warning("elastic resize: data axis %d -> %d workers%s",
                       old_n, n,
                       f" (lost replicas {lost})" if lost else "")
        return removed

    def probe_replicas(self) -> List[int]:
        """Data-axis indices whose device fails a tiny round-trip — the
        ground-truth check behind shrink-and-continue when a failure did
        not name the lost replica itself."""
        return [i for i, d in enumerate(self.mesh.devices.flat)
                if not probe_device(d)]

    def _count_collectives(self, prof, k: int = 1) -> None:
        prof.count("collective/steps", k)
        for name, nbytes in self._coll_bytes.items():
            prof.count(f"collective/{name}", nbytes * k)

    def _drain_encoded_ledger(self, prof) -> None:
        """One tiny host readback per epoch: fold the in-graph encoded-
        exchange counters (elements sent / total / steps) into the
        profiler's collective ledger as deltas since the last drain."""
        st = getattr(self.model, "_acc_state", None)
        if not (self.accumulator.stateful and isinstance(st, dict)) \
                or "nnz_sum" not in st:
            return
        nnz, elems, steps = jax.device_get(
            (st["nnz_sum"], st["elems_sum"], st["steps"]))
        p_nnz, p_elems, p_steps = self._drained_encoded
        if int(steps) > p_steps:
            prof.count("collective/encoded_elems_sent",
                       int(float(nnz) - p_nnz))
            prof.count("collective/encoded_elems_total",
                       int(float(elems) - p_elems))
            prof.count("collective/encoded_steps", int(steps) - p_steps)
        self._drained_encoded = (float(nnz), float(elems), int(steps))

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None,
            *, pad_partial: Optional[bool] = None,
            drop_remainder: bool = False, prefetch: Optional[int] = None,
            steps_per_dispatch: int = 1, host_prefetch: int = 0,
            resume_from: Optional[str] = None,
            resume_cursor: Optional[tuple] = None) -> None:
        """Data-parallel training on the shared input/dispatch pipeline
        (data/pipeline.py): batches are padded BOTH to the configured batch
        size (one compile per fit config) and to a multiple of the worker
        count (shardability) — padding wraps REAL rows (keeps BatchNorm
        batch stats sane; zero rows would pollute them) while the zeroed
        loss-mask and example-weight remove their loss/gradient
        contributions exactly (see ``_local_core``'s renormalization).
        Sharded device placement is issued ``prefetch`` batches ahead
        (default: the builder's ``prefetch_buffer``), and
        ``steps_per_dispatch=K`` scans K minibatches inside one SPMD
        dispatch. ``resume_from``: exact checkpoint resume — see
        MultiLayerNetwork.fit; the restored (host) params/updater are
        re-placed by the SPMD step's sharding on first dispatch.
        ``resume_cursor=(epochs_done, steps_in_epoch)``: IN-MEMORY
        continuation — fast-forward the pipeline to the exact dispatch
        boundary the holder's live state already sits at, touching no
        disk (the supervisor's elastic shrink-and-continue path; the
        cursor is the one the interrupted fit left on the holder)."""
        model = self.model
        model._check_init()
        if not self._listeners and getattr(model, "_listeners", None):
            # listeners attached to the MODEL must not silently stop
            # firing the moment training goes through the wrapper —
            # adopt them (set_listeners also wires bind_group/telemetry)
            self.set_listeners(*model._listeners)
        from ..util.checkpoint import begin_fit_cursor

        if resume_cursor is not None:
            if resume_from is not None:
                raise ValueError(
                    "resume_from and resume_cursor are mutually exclusive")
            # in-memory continuation: the holder IS the checkpoint — no
            # restore, no step invalidation (a resize already rebuilt or
            # cache-swapped the steps; live state matches their layout)
            skip = (int(resume_cursor[0]), int(resume_cursor[1]))
            model._fit_epoch0 = model._epoch - skip[0]
            model._steps_in_epoch = skip[1]
        else:
            skip = begin_fit_cursor(model, resume_from,
                                    listeners=self._listeners,
                                    keep_flat=self.accumulator.zero1)
            if skip is not None:
                # the wrapper's own compiled steps hold donated buffers of
                # the replaced params — rebuild them too (and drop the
                # per-worker-count cache, which holds the same objects)
                self._step = None
                self._chunk_step = None
                self._exec_cache.clear()
        self._ensure_parallel_state()
        if self._step is None:
            self._step = self._build_step()
        if steps_per_dispatch > 1 and self._chunk_step is None:
            self._chunk_step = self._build_chunk_step()
        prof = OpProfiler.get()

        def on_epoch():
            model._epoch += 1
            model._steps_in_epoch = 0
            self._drain_encoded_ledger(prof)
            for lst in self._listeners:
                if hasattr(lst, "epoch_done"):
                    lst.epoch_done(model, model._epoch)

        _pipe.run_epochs(
            data, epochs, batch_size,
            pad_partial=True if pad_partial is None else pad_partial,
            drop_remainder=drop_remainder,
            prefetch=self.prefetch if prefetch is None else prefetch,
            steps_per_dispatch=steps_per_dispatch,
            bind=self._bind_batch,
            place=lambda b: shard_batch(self.mesh, *b),
            dispatch_one=lambda b: self._dispatch_one(b, prof),
            dispatch_chunk=lambda g: self._dispatch_chunk(g, prof),
            stackable=_same_shapes, on_epoch=on_epoch,
            round_to_multiple_of=self.workers_count,
            host_prefetch=host_prefetch, skip=skip)

    def _bind_batch(self, ds: DataSet, w):
        """DataSet → (x, y, mask, w) as HOST arrays. The mask is the RAW
        labels-mask (ones when absent — shard_map's in_specs need a real
        array); ``_loss``'s single ``_fold_weights`` application zeroes
        the pad rows, so w is never applied twice. Staying numpy here
        matters: the ONLY device placement is the sharded one
        (``shard_batch`` in the feed) — a jnp conversion first would
        commit every full batch to device 0 and then reshard it, doubling
        per-step H2D traffic."""
        x = ds.features.to_numpy()
        y = ds.labels.to_numpy()
        mask = (np.asarray(ds.labels_mask.to_numpy(), np.float32)
                if ds.labels_mask is not None
                else np.ones((x.shape[0],), np.float32))
        # PerformanceListener derives samples/sec from this (the holder
        # the listener bus sees is the wrapped model)
        self.model._last_batch_size = int(x.shape[0])
        return x, y, mask, np.asarray(w, np.float32)

    def _inject_faults(self, model) -> None:
        """Pre-dispatch drill hook: the ``integrity/fingerprint`` site's
        ``bitflip`` kind corrupts ONE replica's stored param copy between
        dispatches (common.integrity.apply_bitflip) — pure data, zero
        retraces — so the in-graph consistency check has something real
        to catch. Indexed by the iteration the dispatch starts at; under
        steps_per_dispatch the flip lands at the chunk boundary."""
        for spec in faultinject.fault_point("integrity/fingerprint",
                                            int(model._iteration)):
            if spec.get("kind") == "bitflip":
                _integ.apply_bitflip(model, self.mesh, spec)

    def _dispatch_one(self, b, prof) -> None:
        model = self.model
        xs, ys, ms, ws = b
        self._inject_faults(model)
        key = get_random().next_key()
        with prof.time_section("pipeline/dispatch"):
            out = self._step(model._params, model._states,
                             model._updater_state, model._acc_state, xs,
                             ys, ms, ws, key, jnp.asarray(model._iteration))
        # the accumulator state (residual carry / threshold / counters) is
        # the wrapper's own training state — peel it off before the shared
        # note_dispatch decodes the (params, states, upd, loss[, aux])
        # contract every fit path uses
        model._acc_state = out[3]
        self._count_collectives(prof)
        _pipe.note_dispatch(model, self._listeners, out[:3] + out[4:],
                            self._telemetry is not None)

    def _dispatch_chunk(self, group, prof) -> None:
        model = self.model
        # the group's arrays are already SHARDED by the feed's shard_batch:
        # jnp.stack composes shardings device-side ([K, B, ...] with B
        # still split over the data axis), matching the chunk in_specs
        stack = lambda i: jnp.stack([b[i] for b in group])  # noqa: E731
        self._inject_faults(model)
        keys = jnp.stack([get_random().next_key() for _ in group])
        with prof.time_section("pipeline/dispatch"):
            out = self._chunk_step(model._params, model._states,
                                   model._updater_state, model._acc_state,
                                   stack(0), stack(1), stack(2), stack(3),
                                   keys, jnp.asarray(model._iteration))
        model._acc_state = out[3]
        self._count_collectives(prof, len(group))
        _pipe.note_dispatch(model, self._listeners, out[:3] + out[4:],
                            self._telemetry is not None, len(group))

    def shutdown(self) -> None:
        self._step = None
        self._chunk_step = None
        self._zero1_plan = None
        self._exec_cache.clear()
