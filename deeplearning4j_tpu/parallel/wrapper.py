"""ParallelWrapper — data-parallel training over a device mesh.

Reference: dl4j-scaleout ``org.deeplearning4j.parallelism.ParallelWrapper``
(+ ``trainer/{DefaultTrainer,SymmetricTrainer}``; SURVEY.md §2.4, §3.5).

The reference clones the model per GPU, pins trainer threads to devices, and
exchanges threshold-encoded gradients through host-RAM queues. On TPU this
whole topology is ONE SPMD program: the train step runs under ``shard_map``
over the mesh's ``data`` axis with the minibatch sharded and params
replicated; the accumulator's ``reduce_gradients`` (a ``pmean`` over ICI for
the default dense accumulator) is compiled into the step. Both reference
training modes collapse to the synchronous collective:

- SHARED_GRADIENTS → psum of gradients every step (exactly this program);
- AVERAGING (params averaged every N iters) → mathematically subsumed by
  per-step gradient averaging; accepted and treated as the same program
  (documented divergence: no stale-average window exists to configure).
"""

from __future__ import annotations

from functools import partial
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..common.profiler import OpProfiler
from ..data import pipeline as _pipe
from ..data.dataset import DataSet
from ..ndarray.rng import get_random
from ..nn.multilayer import _same_shapes
from .accumulator import DenseAllReduceAccumulator, GradientsAccumulator
from .mesh import make_mesh, shard_batch


class ParallelWrapper:
    class Builder:
        def __init__(self, model):
            self._model = model
            self._workers: Optional[int] = None
            self._mode = "shared_gradients"
            self._accumulator: Optional[GradientsAccumulator] = None
            self._prefetch = 2
            self._averaging_frequency = 1
            self._model_axis = 1

        def workers(self, n: int) -> "ParallelWrapper.Builder":
            self._workers = n
            return self

        def model_axis(self, m: int) -> "ParallelWrapper.Builder":
            """Devices along the mesh's ``model`` axis (workers must divide
            by it). Layers with a ``table_sharding`` config (EmbeddingLayer
            family) shard their tables over this axis — the product-API
            route into the sharded-embedding machinery (SURVEY §2.4 row 4)."""
            self._model_axis = int(m)
            return self

        def training_mode(self, mode: str) -> "ParallelWrapper.Builder":
            mode = mode.lower()
            if mode not in ("shared_gradients", "averaging"):
                raise ValueError(f"unknown training mode {mode!r}")
            self._mode = mode
            return self

        trainingMode = training_mode

        def gradients_accumulator(self, acc: GradientsAccumulator) -> "ParallelWrapper.Builder":
            self._accumulator = acc
            return self

        def averaging_frequency(self, n: int) -> "ParallelWrapper.Builder":
            self._averaging_frequency = n  # accepted for parity; see module doc
            return self

        def prefetch_buffer(self, n: int) -> "ParallelWrapper.Builder":
            self._prefetch = n
            return self

        def build(self) -> "ParallelWrapper":
            return ParallelWrapper(self._model, self._workers, self._mode,
                                   self._accumulator
                                   or DenseAllReduceAccumulator(),
                                   model_axis=self._model_axis,
                                   prefetch=self._prefetch)

    def __init__(self, model, workers: Optional[int], mode: str,
                 accumulator: GradientsAccumulator, model_axis: int = 1,
                 prefetch: int = 2):
        self.model = model
        n = workers or len(jax.devices())
        if n % model_axis:
            raise ValueError(
                f"workers={n} not divisible by model_axis={model_axis}")
        self.mesh = make_mesh(data=n // model_axis, model=model_axis,
                              devices=jax.devices()[:n])
        self.workers_count = n // model_axis   # data-parallel shards
        self.model_axis = model_axis
        self.mode = mode
        self.accumulator = accumulator
        self.prefetch = prefetch
        self._step = None
        self._chunk_step = None
        self._telemetry = None
        self._listeners: List[Any] = []

    def set_listeners(self, *ls) -> None:
        self._listeners = list(ls)
        for lst in self._listeners:
            # checkpoint-style listeners snapshot their peers' state for
            # exact resume (see MultiLayerNetwork.set_listeners)
            bind = getattr(lst, "bind_group", None)
            if callable(bind):
                bind(self._listeners)
        from ..optimize.telemetry import config_for

        cfg = config_for(self._listeners)
        if cfg != self._telemetry:
            # in-graph telemetry is a build-time property of the SPMD step
            # (see MultiLayerNetwork.set_listeners); the aux statistics are
            # aggregated across shards with the same collectives as the
            # weight update
            self._telemetry = cfg
            self._step = None
            self._chunk_step = None

    # ------------------------------------------------------------------
    def _local_core(self):
        """The per-shard train step, shared by the per-step shard_map and
        the steps_per_dispatch scan (one definition, no drift)."""
        model = self.model
        updater = model.conf.global_conf.updater
        acc = self.accumulator
        axis = acc.axis_name
        is_graph = hasattr(model, "conf") and hasattr(model.conf, "network_inputs")
        tele = self._telemetry
        from ..optimize import telemetry as _tel

        def local_step(params, states, upd_state, x, y, mask, w, key, it):
            idx = jax.lax.axis_index(axis)
            key = jax.random.fold_in(key, idx)
            # Per-shard weighted data loss with a GLOBAL divisor: each shard
            # divides its weighted sum by global_real/num_shards, so the
            # pmean of per-shard losses (and of their grads) is exactly the
            # mean over real examples across the whole batch — pad rows
            # (w=0) contribute nothing and, unlike a whole-loss rescale,
            # the regularization term is never inflated.
            n_shards = jax.lax.psum(1.0, axis)
            real = jax.lax.psum(jnp.sum(w), axis)
            denom = jnp.maximum(real, 1.0) / n_shards

            def loss_fn(p):
                if is_graph:
                    inputs = {model.conf.network_inputs[0]: x}
                    out_name = model.conf.network_outputs[0]
                    loss, new_states = model._loss(p, states, inputs,
                                                   {out_name: y}, {out_name: mask},
                                                   True, key, w=w,
                                                   w_denom=denom)
                else:
                    loss, new_states = model._loss(p, states, x, y, mask,
                                                   True, key, w=w,
                                                   w_denom=denom)
                return loss, new_states

            (loss, new_states), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            if tele is not None:
                # non-finite counts are taken on the RAW per-shard grads
                # (reduction would smear one shard's NaN across all of
                # them) and aggregated with the same collective family as
                # the weight update
                raw_nf = jax.lax.psum(_tel.nonfinite_counts(grads), axis)
            grads = acc.reduce_gradients(grads)
            loss = jax.lax.pmean(loss, axis)
            # keep batchnorm running stats consistent across shards
            new_states = jax.tree.map(
                lambda s: jax.lax.pmean(s, axis)
                if jnp.issubdtype(s.dtype, jnp.floating) else s, new_states)
            new_params, new_upd = updater.apply(grads, upd_state, params, it)
            if tele is None:
                return new_params, new_states, new_upd, loss
            # norms on the REDUCED grads / updated params: replicated
            # values, identical on every shard
            aux = _tel.layer_stats(params, new_params, grads, loss,
                                   nonfinite=raw_nf)
            if tele.nan_guard:
                aux, new_params, new_states, new_upd = _tel.apply_nan_guard(
                    aux, new_params, params, new_states, states, new_upd,
                    upd_state)
            return new_params, new_states, new_upd, loss, aux

        return local_step

    def _build_step(self):
        local_step = self._local_core()
        pspec = self._param_specs()
        uspec = self._upd_specs(pspec)
        out_specs = (pspec, P(), uspec, P())
        if self._telemetry is not None:
            out_specs += (P(),)    # aux pytree: replicated device scalars
        sharded = shard_map(
            local_step, mesh=self.mesh,
            in_specs=(pspec, P(), uspec, P("data"), P("data"), P("data"),
                      P("data"), P(), P()),
            out_specs=out_specs,
            check_rep=False)

        def step(*args):
            OpProfiler.get().count("trace/pw_fit_step")
            return sharded(*args)

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _build_chunk_step(self):
        """steps_per_dispatch=K: each shard scans its K local slices of the
        stacked chunk inside ONE SPMD program — the per-step collectives
        (gradient psum, loss/stats pmean) run inside the scan body, and
        Python dispatch + listener sync amortize over K steps."""
        local_step = self._local_core()
        tele = self._telemetry

        def local_chunk(params, states, upd_state, xs, ys, masks, ws, keys,
                        it0):
            def body(carry, inp):
                params, states, upd_state, it = carry
                x, y, m, w, k = inp
                out = local_step(params, states, upd_state, x, y, m, w, k,
                                 it)
                if tele is None:
                    params, states, upd_state, loss = out
                    return (params, states, upd_state, it + 1), loss
                params, states, upd_state, loss, aux = out
                return (params, states, upd_state, it + 1), (loss, aux)

            (params, states, upd_state, _), ys_out = jax.lax.scan(
                body, (params, states, upd_state, it0),
                (xs, ys, masks, ws, keys))
            if tele is None:
                return params, states, upd_state, ys_out
            losses, auxes = ys_out
            return params, states, upd_state, losses, auxes

        pspec = self._param_specs()
        uspec = self._upd_specs(pspec)
        batch = P(None, "data")   # [K, B, ...]: stack axis whole, B sharded
        out_specs = (pspec, P(), uspec, P())
        if tele is not None:
            out_specs += (P(),)
        sharded = shard_map(
            local_chunk, mesh=self.mesh,
            in_specs=(pspec, P(), uspec, batch, batch, batch, batch, P(),
                      P()),
            out_specs=out_specs,
            check_rep=False)

        def chunk(*args):
            OpProfiler.get().count("trace/pw_fit_chunk")
            return sharded(*args)

        return jax.jit(chunk, donate_argnums=(0, 1, 2))

    def _param_specs(self):
        """Per-layer partition specs: replicated except row-sharded
        embedding tables (layers carrying ``table_sharding``)."""
        model = self.model
        if not hasattr(model.conf, "layers"):    # ComputationGraph
            for name, node in getattr(model.conf, "nodes", {}).items():
                lyr = getattr(node, "layer", None)
                if getattr(lyr, "table_sharding", None):
                    raise NotImplementedError(
                        "table_sharding through ParallelWrapper is wired "
                        "for MultiLayerNetwork; ComputationGraph tables "
                        "are not routed yet")
            return P()
        specs = []
        for layer in model.conf.layers:
            ax = getattr(layer, "table_sharding", None)
            if not ax:
                specs.append(P())
                continue
            if ax not in self.mesh.shape:
                raise ValueError(f"table_sharding={ax!r} is not a mesh "
                                 f"axis of {tuple(self.mesh.shape)}")
            n_sh = self.mesh.shape[ax]
            if layer.n_in is None or layer.n_in % n_sh:
                raise ValueError(
                    f"embedding vocab {layer.n_in} must be divisible by "
                    f"the {ax!r} axis size {n_sh} (pad the vocab)")
            specs.append({"W": P(ax, None)})
        return specs

    def _upd_specs(self, pspec):
        """Updater state mirrors params per top-level key (Adam m/v,
        Nesterov v, ...) — shard those subtrees like the params."""
        upd_state = self.model._updater_state
        if not isinstance(upd_state, dict) or not upd_state:
            return P()
        pstruct = jax.tree.structure(self.model._params)
        return {k: (pspec if jax.tree.structure(v) == pstruct else P())
                for k, v in upd_state.items()}

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None,
            *, pad_partial: Optional[bool] = None,
            drop_remainder: bool = False, prefetch: Optional[int] = None,
            steps_per_dispatch: int = 1, host_prefetch: int = 0,
            resume_from: Optional[str] = None) -> None:
        """Data-parallel training on the shared input/dispatch pipeline
        (data/pipeline.py): batches are padded BOTH to the configured batch
        size (one compile per fit config) and to a multiple of the worker
        count (shardability) — padding wraps REAL rows (keeps BatchNorm
        batch stats sane; zero rows would pollute them) while the zeroed
        loss-mask and example-weight remove their loss/gradient
        contributions exactly (see ``_local_core``'s renormalization).
        Sharded device placement is issued ``prefetch`` batches ahead
        (default: the builder's ``prefetch_buffer``), and
        ``steps_per_dispatch=K`` scans K minibatches inside one SPMD
        dispatch. ``resume_from``: exact checkpoint resume — see
        MultiLayerNetwork.fit; the restored (host) params/updater are
        re-placed by the SPMD step's sharding on first dispatch."""
        model = self.model
        model._check_init()
        if not self._listeners and getattr(model, "_listeners", None):
            # listeners attached to the MODEL must not silently stop
            # firing the moment training goes through the wrapper —
            # adopt them (set_listeners also wires bind_group/telemetry)
            self.set_listeners(*model._listeners)
        from ..util.checkpoint import begin_fit_cursor

        skip = begin_fit_cursor(model, resume_from,
                                listeners=self._listeners)
        if skip is not None:
            # the wrapper's own compiled steps hold donated buffers of the
            # replaced params — rebuild them too
            self._step = None
            self._chunk_step = None
        if model._updater_state is None:
            model._updater_state = model.conf.global_conf.updater.init(model._params)
        if self._step is None:
            self._step = self._build_step()
        if steps_per_dispatch > 1 and self._chunk_step is None:
            self._chunk_step = self._build_chunk_step()
        prof = OpProfiler.get()

        def on_epoch():
            model._epoch += 1
            model._steps_in_epoch = 0
            for lst in self._listeners:
                if hasattr(lst, "epoch_done"):
                    lst.epoch_done(model, model._epoch)

        _pipe.run_epochs(
            data, epochs, batch_size,
            pad_partial=True if pad_partial is None else pad_partial,
            drop_remainder=drop_remainder,
            prefetch=self.prefetch if prefetch is None else prefetch,
            steps_per_dispatch=steps_per_dispatch,
            bind=self._bind_batch,
            place=lambda b: shard_batch(self.mesh, *b),
            dispatch_one=lambda b: self._dispatch_one(b, prof),
            dispatch_chunk=lambda g: self._dispatch_chunk(g, prof),
            stackable=_same_shapes, on_epoch=on_epoch,
            round_to_multiple_of=self.workers_count,
            host_prefetch=host_prefetch, skip=skip)

    def _bind_batch(self, ds: DataSet, w):
        """DataSet → (x, y, mask, w) as HOST arrays. The mask is the RAW
        labels-mask (ones when absent — shard_map's in_specs need a real
        array); ``_loss``'s single ``_fold_weights`` application zeroes
        the pad rows, so w is never applied twice. Staying numpy here
        matters: the ONLY device placement is the sharded one
        (``shard_batch`` in the feed) — a jnp conversion first would
        commit every full batch to device 0 and then reshard it, doubling
        per-step H2D traffic."""
        x = ds.features.to_numpy()
        y = ds.labels.to_numpy()
        mask = (np.asarray(ds.labels_mask.to_numpy(), np.float32)
                if ds.labels_mask is not None
                else np.ones((x.shape[0],), np.float32))
        return x, y, mask, np.asarray(w, np.float32)

    def _dispatch_one(self, b, prof) -> None:
        model = self.model
        xs, ys, ms, ws = b
        key = get_random().next_key()
        with prof.time_section("pipeline/dispatch"):
            out = self._step(model._params, model._states,
                             model._updater_state, xs, ys, ms, ws, key,
                             jnp.asarray(model._iteration))
        _pipe.note_dispatch(model, self._listeners, out,
                            self._telemetry is not None)

    def _dispatch_chunk(self, group, prof) -> None:
        model = self.model
        # the group's arrays are already SHARDED by the feed's shard_batch:
        # jnp.stack composes shardings device-side ([K, B, ...] with B
        # still split over the data axis), matching the chunk in_specs
        stack = lambda i: jnp.stack([b[i] for b in group])  # noqa: E731
        keys = jnp.stack([get_random().next_key() for _ in group])
        with prof.time_section("pipeline/dispatch"):
            out = self._chunk_step(model._params, model._states,
                                   model._updater_state, stack(0), stack(1),
                                   stack(2), stack(3), keys,
                                   jnp.asarray(model._iteration))
        _pipe.note_dispatch(model, self._listeners, out,
                            self._telemetry is not None, len(group))

    def shutdown(self) -> None:
        self._step = None
        self._chunk_step = None
