"""Multi-host distributed training — control plane + master API.

Reference: dl4j-scaleout Spark masters + the Aeron parameter-server fabric
(``SharedTrainingMaster``, ``ModelParameterServer``, ``MeshOrganizer``,
``AeronUdpTransport``; SURVEY.md §2.4, §5.8). The TPU-native pivot:

- data plane: XLA collectives over ICI/DCN compiled into the step — no
  message library, no spanning-tree mesh, no encode/decode;
- control plane (the role Aeron's handshake/heartbeat/mesh played):
  the jax coordination service (``jax.distributed.initialize``);
- elasticity: the async mesh's node-remap is replaced by checkpoint-restart
  (orbax-style atomic checkpoints + resume; SURVEY.md §5.3) — XLA collectives
  are synchronous, so a lost host means restart-from-step-N, and that path is
  what ``SharedTrainingMaster.fit`` wires in via its CheckpointListener.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bootstrap the multi-host control plane (jax coordination service).

    Mirrors ``jax.distributed.initialize`` with env-var fallbacks
    (DL4J_TPU_COORDINATOR / _NUM_PROCS / _PROC_ID), the analog of the
    reference's VoidConfiguration(controllerAddress=...).
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get("DL4J_TPU_COORDINATOR")
    if num_processes is None and "DL4J_TPU_NUM_PROCS" in os.environ:
        num_processes = int(os.environ["DL4J_TPU_NUM_PROCS"])
    if process_id is None and "DL4J_TPU_PROC_ID" in os.environ:
        process_id = int(os.environ["DL4J_TPU_PROC_ID"])
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def shutdown() -> None:
    import jax

    jax.distributed.shutdown()


class SharedTrainingMaster:
    """Reference SharedTrainingMaster-shaped front for synchronous multi-host
    SPMD: same builder surface (workers/batch sizes/threshold config accepted),
    fit() delegates to a ParallelWrapper over ALL global devices, and a
    checkpoint listener provides the restart-based fault story."""

    class Builder:
        def __init__(self, batch_size_per_worker: int = 32):
            self._batch = batch_size_per_worker
            self._workers_per_node: Optional[int] = None
            self._threshold: Optional[Any] = None
            self._checkpoint_dir: Optional[str] = None
            self._checkpoint_every = 0

        def workers_per_node(self, n: int) -> "SharedTrainingMaster.Builder":
            self._workers_per_node = n
            return self

        def threshold_algorithm(self, alg) -> "SharedTrainingMaster.Builder":
            # Recorded and forwarded to the accumulator for config parity;
            # the exchange itself stays a dense psum (module doc / SURVEY §5.8)
            self._threshold = alg
            return self

        def checkpoint(self, directory: str, every_n_iterations: int
                       ) -> "SharedTrainingMaster.Builder":
            self._checkpoint_dir = directory
            self._checkpoint_every = every_n_iterations
            return self

        def build(self) -> "SharedTrainingMaster":
            return SharedTrainingMaster(self._batch, self._workers_per_node,
                                        self._checkpoint_dir,
                                        self._checkpoint_every, self._threshold)

    def __init__(self, batch_size_per_worker: int,
                 workers_per_node: Optional[int],
                 checkpoint_dir: Optional[str], checkpoint_every: int,
                 threshold_algorithm: Optional[Any] = None):
        self.batch_size_per_worker = batch_size_per_worker
        self.workers_per_node = workers_per_node
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.threshold_algorithm = threshold_algorithm

    def workers(self) -> int:
        """Global worker count. Single-process: workers_per_node bounds the
        device count. Multi-process SPMD requires every host's devices in the
        mesh, so a workers_per_node below local_device_count cannot be
        honored there — raise rather than build a mesh that silently excludes
        one host's devices."""
        import jax

        if self.workers_per_node is None:
            return len(jax.devices())
        if jax.process_count() > 1:
            if self.workers_per_node < jax.local_device_count():
                raise ValueError(
                    "workers_per_node < local device count is not supported "
                    "in multi-process SPMD (all addressable devices must "
                    "participate in the mesh); unset workers_per_node or set "
                    f"it to {jax.local_device_count()}")
            return len(jax.devices())
        return min(self.workers_per_node, jax.local_device_count())

    def fit(self, model, data, epochs: int = 1):
        """Train `model` over all global devices; resumes from the latest
        INTACT checkpoint in `checkpoint_dir` when one exists (kill-resume
        story, SURVEY §5.3) — the restart loop is "relaunch the same
        command": the checkpoint's cursor fast-forwards the input pipeline
        so the continuation is exact, a checkpoint torn by the kill is
        skipped by checksum, and checkpointing itself runs on the async
        atomic writer (closed — i.e. made durable — before fit returns)."""
        from ..optimize.listeners import CheckpointListener
        from .accumulator import EncodedGradientsAccumulator
        from .wrapper import ParallelWrapper

        resume = (CheckpointListener.last_checkpoint(self.checkpoint_dir)
                  if self.checkpoint_dir else None)
        builder = (ParallelWrapper.Builder(model)
                   .workers(self.workers())
                   .training_mode("shared_gradients"))
        if self.threshold_algorithm is not None:
            builder.gradients_accumulator(
                EncodedGradientsAccumulator(threshold_algorithm=self.threshold_algorithm))
        pw = builder.build()
        ckpt = None
        if self.checkpoint_dir and self.checkpoint_every:
            ckpt = CheckpointListener(
                self.checkpoint_dir,
                save_every_n_iterations=self.checkpoint_every)
            pw.set_listeners(ckpt)
        try:
            pw.fit(data, epochs=epochs, resume_from=resume)
        finally:
            if ckpt is not None:
                ckpt.close()   # durability point: all submitted writes commit
        return model
