"""Multi-host distributed training — control plane + master API.

Reference: dl4j-scaleout Spark masters + the Aeron parameter-server fabric
(``SharedTrainingMaster``, ``ModelParameterServer``, ``MeshOrganizer``,
``AeronUdpTransport``; SURVEY.md §2.4, §5.8). The TPU-native pivot:

- data plane: XLA collectives over ICI/DCN compiled into the step — no
  message library, no spanning-tree mesh, no encode/decode;
- control plane (the role Aeron's handshake/heartbeat/mesh played):
  the jax coordination service (``jax.distributed.initialize``) for
  bootstrap, and — this module's :class:`TrainingSupervisor` — the
  heartbeat / dead-node-handling half: a self-healing restart loop that
  wraps any fit path;
- elasticity: the async mesh's node-remap is replaced by checkpoint-restart
  (orbax-style atomic checkpoints + resume; SURVEY.md §5.3) — XLA collectives
  are synchronous, so a lost participant means supervised restart-from-step-N
  (the SPMD assumption of arXiv:2004.13336), not async continuation.

The supervisor stack, in-process first:

- **failure classification** (:func:`classify_failure`): transient input
  faults / poisoned numerics / device-collective failure / external
  preemption, each mapped to a policy — retry in place, raise (the
  in-graph NanSentinel *skip* already handled the recoverable numerics),
  checkpoint-restart, or clean exit with a restartable status;
- **bounded restart budget** with exponential backoff and a restart-storm
  circuit breaker; every restart resumes from the last intact checkpoint
  through the util.checkpoint machinery, so a healed run's loss sequence
  is bit-identical to an uninterrupted one;
- **progress watchdog**: heartbeat = steps completed (fed by the listener
  bus), a configurable deadline declares a hang, the wedged dispatch is
  abandoned (``faultinject.release_wedges`` for drills) and the run
  restarts;
- **preemption signals**: SIGTERM/SIGINT trigger a flush-quality
  checkpoint (async writer drained, committed synchronously) and a
  ``"preempted"``/resumable result instead of dying dirty;
- **incarnation fence**: each (re)start claims a monotonic incarnation id
  in ``checkpoint.json``; a stale pre-restart writer that wakes up late
  can never commit over its replacement's checkpoints.

Process-level, :func:`supervise_processes` is the multi-host restart loop
the reference mesh's dead-node remap becomes: launch the SPMD group, and
when ANY participant dies, terminate the survivors and relaunch the whole
group (synchronous collectives cannot continue around a hole) — each
relaunch resumes from the shared checkpoint directory.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..common import faultinject, flightrec
from ..common.profiler import OpProfiler

logger = logging.getLogger("deeplearning4j_tpu")

#: the crash black box, dumped into the checkpoint directory on every
#: failure classification and on the preemption path — the last-N flight
#: recorder events as JSONL, readable with no live process
BLACKBOX_NAME = "blackbox.jsonl"
MEMCENSUS_NAME = "memcensus.json"


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None, *,
               init_deadline_s: float = 120.0,
               cluster_dir: Optional[str] = None):
    """Bootstrap the multi-host control plane (jax coordination service).

    Mirrors ``jax.distributed.initialize`` with env-var fallbacks
    (DL4J_TPU_COORDINATOR / _NUM_PROCS / _PROC_ID), the analog of the
    reference's VoidConfiguration(controllerAddress=...) — hardened
    through :class:`cluster.ClusterRuntime`: bounded exponential-backoff
    retries under ``init_deadline_s``, a rank heartbeat sidecar, and a
    coordinator-unreachable failure that raises
    :class:`cluster.ClusterInitError` with the full diagnosis (address,
    ranks that did report, attempts, elapsed) instead of hanging. On the
    CPU backend the bring-up auto-selects a cross-process collectives
    implementation when jaxlib ships one. Returns the
    :class:`cluster.ClusterRuntime` (barriers / group commits /
    blackboxes), or None on the fully-auto-detected path.

    ``cluster_dir`` is the shared control-plane directory (heartbeats,
    barrier tokens); default ``$DL4J_TPU_CLUSTER_DIR``, else a tempdir
    keyed by the coordinator address (single-host drills)."""
    coordinator_address = coordinator_address or os.environ.get("DL4J_TPU_COORDINATOR")
    if num_processes is None and "DL4J_TPU_NUM_PROCS" in os.environ:
        num_processes = int(os.environ["DL4J_TPU_NUM_PROCS"])
    if process_id is None and "DL4J_TPU_PROC_ID" in os.environ:
        process_id = int(os.environ["DL4J_TPU_PROC_ID"])
    if coordinator_address is None or num_processes is None \
            or process_id is None:
        # the TPU-pod auto-detection path: jax reads the cluster env
        # itself; no coordinator to retry against from here
        import jax

        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        return None
    from . import cluster as _cluster

    if cluster_dir is None:
        cluster_dir = os.environ.get("DL4J_TPU_CLUSTER_DIR")
    if cluster_dir is None:
        import tempfile

        safe = "".join(c if (c.isalnum() or c in "-_.") else "_"
                       for c in coordinator_address)
        cluster_dir = os.path.join(tempfile.gettempdir(),
                                   f"dl4j_cluster_{safe}")
    rt = _cluster.ClusterRuntime(
        cluster_dir, process_id, num_processes,
        coordinator=coordinator_address, init_deadline_s=init_deadline_s,
        incarnation=int(os.environ.get("DL4J_ATTEMPT", "0") or 0))
    rt.form()
    return rt


def shutdown() -> None:
    import jax

    jax.distributed.shutdown()


# ---------------------------------------------------------------------------
# failure classification
# ---------------------------------------------------------------------------

CLASS_TRANSIENT = "transient_input"
CLASS_NUMERIC = "poisoned_numerics"
CLASS_DEVICE = "device_failure"
CLASS_PREEMPTION = "preemption"
CLASS_HANG = "hang"
CLASS_USER = "user_error"
CLASS_CORRUPTION = "silent_corruption"

#: classification → what the supervisor does about it. "retry" restarts
#: from the last intact checkpoint with FLAT backoff (a transient input
#: fault that exhausted the pipeline's own bounded retries — in-place
#: retry is the policy, the checkpoint merely anchors exactness);
#: "restart" is checkpoint-restart with exponential backoff; "raise"
#: propagates (a FloatingPointError here means the NanSentinel was in
#: raise mode — the *skip* policy for poisoned numerics is its in-graph
#: job, and user/config errors are deterministic: restarting cannot
#: help); "exit" is the preemption path — flush-quality checkpoint, then
#: a clean return with a resumable status; "shrink_and_continue" (the
#: device-failure default) resizes the elastic data axis over the
#: surviving devices and continues IN MEMORY from the exact dispatch
#: boundary — no disk, no replay — falling back to checkpoint-restart
#: whenever the target cannot resize, the lost replica cannot be
#: identified, or the holder's in-memory state is not
#: boundary-consistent. For PIPELINE targets (anything exposing
#: ``remap``/``stages_count`` — parallel.pipeline.PipelineTrainer) the
#: device-failure policy resolves to "remap_and_continue": the layer
#: partition is re-cut over the surviving stage devices at the dispatch
#: boundary and training continues in memory from the exact cursor, with
#: the same checkpoint-restart fallback whenever the remap gate refuses
#: (surviving stages < 2, unidentifiable stage, state not
#: boundary-consistent). "quarantine_and_continue" (silent corruption —
#: the in-graph replica-consistency fingerprint named a divergent
#: replica) reuses the shrink machinery: the named replica's device is
#: quarantined out of the mesh, majority-consistent state is
#: re-materialized from a SURVIVOR's shard, and training continues in
#: memory from the exact boundary — falling back to checkpoint-restart
#: from the last scrub-VERIFIED generation when the divergence is
#: un-attributable (2-way split, N=2) or the shrink gate refuses.
DEFAULT_POLICIES: Dict[str, str] = {
    CLASS_TRANSIENT: "retry",
    CLASS_NUMERIC: "raise",
    CLASS_DEVICE: "shrink_and_continue",
    CLASS_HANG: "restart",
    CLASS_PREEMPTION: "exit",
    CLASS_USER: "raise",
    CLASS_CORRUPTION: "quarantine_and_continue",
}


class Preempted(BaseException):
    """Raised inside the training thread (by the supervisor's heartbeat
    listener, at a dispatch boundary) when a preemption signal arrived.
    BaseException so user ``except Exception`` recovery cannot swallow
    the shutdown request."""


class ElasticResizeRequested(BaseException):
    """Raised inside the training thread (by the supervisor's heartbeat
    listener, at a dispatch boundary) when a grow-back probe found the
    lost device healthy again: the fit unwinds with boundary-consistent
    state, the supervisor resizes the data axis back up, and training
    continues in memory from the same cursor. BaseException for the same
    reason as :class:`Preempted` — user recovery code must not swallow
    the control transfer."""


class HangDetected(RuntimeError):
    """The watchdog's verdict on an attempt that stopped landing steps."""


class RestartBudgetExceeded(RuntimeError):
    """The supervisor gave up. ``history`` carries one record per failed
    attempt (classification, policy, exception repr, steps landed)."""

    def __init__(self, message: str, history: Optional[List[dict]] = None):
        if history:
            tail = "; ".join(
                f"attempt {h['attempt']}: {h['class']} ({h['error']})"
                for h in history[-3:])
            message = f"{message} — failure history ({len(history)}): {tail}"
        super().__init__(message)
        self.history = list(history or [])


class RestartStorm(RestartBudgetExceeded):
    """Circuit breaker: consecutive restarts with ZERO forward progress —
    something is deterministically broken; backing off harder won't fix
    it, so stop burning the budget."""


def classify_failure(exc: Optional[BaseException]) -> str:
    """Map an exception that escaped a fit attempt to a failure class.
    Unknown exceptions classify as device failure (restartable with a
    bounded budget — the budget is the safety net for misclassification);
    deterministic config/user errors classify as ``user_error`` so the
    supervisor surfaces them immediately instead of retrying a bug."""
    if exc is None:
        return CLASS_HANG
    if isinstance(exc, Preempted):
        return CLASS_PREEMPTION
    if faultinject.is_transient(exc):
        return CLASS_TRANSIENT
    # lazy: common.integrity pulls in jax, which this module defers to
    # function scope (the multiprocess launcher imports us pre-env)
    from ..common.integrity import ReplicaCorruptionError

    if isinstance(exc, ReplicaCorruptionError):
        return CLASS_CORRUPTION
    if isinstance(exc, FloatingPointError):
        return CLASS_NUMERIC
    if isinstance(exc, (faultinject.SimulatedCrash,
                        faultinject.WedgeReleased,
                        faultinject.DeviceLostError)):
        return CLASS_DEVICE
    if isinstance(exc, (TypeError, ValueError, KeyError, AttributeError,
                        IndexError, NotImplementedError, AssertionError)):
        return CLASS_USER
    return CLASS_DEVICE


class SupervisedFitResult:
    """What a supervised fit ended as. ``status`` is ``"completed"`` or
    ``"preempted"`` (every other ending raises); a preempted result is
    ``resumable`` from ``resume_from`` — exit with ``resumable_exit_code``
    and an outer :func:`supervise_processes` (or scheduler) relaunches."""

    resumable_exit_code = 75      # EX_TEMPFAIL

    def __init__(self, status: str, resume_from: Optional[str],
                 restarts: int, attempts: int, history: List[dict]):
        self.status = status
        self.resumable = status == "preempted"
        self.resume_from = resume_from
        self.restarts = restarts
        self.attempts = attempts
        self.history = history

    def __repr__(self) -> str:
        return (f"SupervisedFitResult(status={self.status!r}, "
                f"attempts={self.attempts}, restarts={self.restarts}, "
                f"resume_from={self.resume_from!r})")


class AbandonedAttempt(BaseException):
    """Raised in a ZOMBIE attempt thread — one the watchdog abandoned
    that later woke up — at its next listener boundary, so it dies
    instead of training (and checkpointing) concurrently with its
    replacement. BaseException: recovery code must not resurrect it."""


class _AttemptFence:
    """First listener in the supervised arrangement: only the CURRENT
    attempt's thread may pass. A zombie thread (abandoned by the
    watchdog, woken later) is killed at its next step/epoch boundary
    BEFORE any downstream listener sees the callback — its beats can't
    mask a replacement's hang, its scores can't corrupt restored listener
    state, and its checkpoint cadence never fires."""

    def __init__(self):
        self.thread: Optional[threading.Thread] = None

    def _check(self) -> None:
        if threading.current_thread() is not self.thread:
            raise AbandonedAttempt(
                "attempt thread was abandoned by the supervisor; "
                "unwinding instead of racing its replacement")

    def iteration_done(self, model, iteration: int, score) -> None:
        self._check()

    def epoch_done(self, model, epoch: int) -> None:
        self._check()


class _Heartbeat:
    """The progress pulse, fed by the listener bus: every completed step
    beats; the watchdog compares the beat's age to the hang deadline. At
    dispatch boundaries it also surfaces a pending preemption signal as
    :class:`Preempted` — the training thread unwinds at a step boundary,
    where the holder's published state is checkpoint-consistent. One
    instance per attempt (a zombie's beats must not vouch for its
    replacement; the fence kills zombies before they reach this anyway)."""

    def __init__(self, supervisor: "TrainingSupervisor"):
        self._sup = supervisor
        self.steps = 0
        self.last_beat = time.monotonic()

    def iteration_done(self, model, iteration: int, score) -> None:
        # graftlint: disable=lock-discipline -- single-writer: only the
        # training thread beats; the watchdog reads monotonic values
        # racily by design (a torn read is at worst one stale poll)
        self.steps += 1
        # graftlint: disable=lock-discipline -- same single-writer pulse
        self.last_beat = time.monotonic()
        sup = self._sup
        boundary = getattr(model, "_at_dispatch_boundary", True)
        if sup._preempt.is_set() and boundary:
            raise Preempted(
                f"preemption signal {sup._preempt_signal} received")
        if sup._resize_request is not None and boundary:
            # a returning device rejoins HERE — the next dispatch
            # boundary after the probe succeeded (the fit unwinds with
            # published state complete; the supervisor resizes and
            # continues in memory from this exact cursor)
            raise ElasticResizeRequested(
                f"grow data axis back to {sup._resize_request} workers")

    def epoch_done(self, model, epoch: int) -> None:
        # graftlint: disable=lock-discipline -- same single-writer pulse
        self.last_beat = time.monotonic()


class _Attempt:
    """One supervised try of the wrapped fit, on its own daemon thread.
    The thread seeds its per-thread RNG stream from the supervisor's
    entry state (so attempt 1 draws exactly what an unsupervised fit on
    the calling thread would have drawn; resumed attempts overwrite it
    from the checkpoint anyway) and reports its FINAL stream state back
    for preemption flushes and caller-stream transparency."""

    def __init__(self, supervisor: "TrainingSupervisor", index: int,
                 data: Any, epochs: int, resume_from: Optional[str],
                 fit_kwargs: dict, entry_rng: dict,
                 heartbeat: _Heartbeat):
        self._sup = supervisor
        self.index = index
        self._data = data
        self._epochs = epochs
        self._resume_from = resume_from
        self._fit_kwargs = fit_kwargs
        self._entry_rng = entry_rng
        self.heartbeat = heartbeat
        self.error: Optional[BaseException] = None
        self.rng_state: Optional[dict] = None
        self.abandoned = False
        self.done = threading.Event()
        self.thread = threading.Thread(
            target=self._main, daemon=True,
            name=f"dl4j-supervised-fit-{index}")

    def start(self) -> None:
        self.thread.start()

    def _main(self) -> None:
        from ..ndarray.rng import get_random

        try:
            get_random().set_state(self._entry_rng)
            # drill site: a "wedge" here hangs the attempt BEFORE its
            # first heartbeat — the watchdog must catch that too
            faultinject.fault_point("supervisor/hang", self.index - 1)
            self._sup.target.fit(self._data, epochs=self._epochs,
                                 resume_from=self._resume_from,
                                 **self._fit_kwargs)
        except BaseException as e:          # incl. SimulatedCrash/Preempted
            # graftlint: disable=lock-discipline -- happens-before via
            # done.set(): written by the attempt thread, read only after
            # done.wait() returns
            self.error = e
        finally:
            try:
                # graftlint: disable=lock-discipline -- same done.set()
                # happens-before edge as error above
                self.rng_state = get_random().get_state()
            finally:
                self.done.set()


class TrainingSupervisor:
    """Self-healing wrapper around any fit path (``MultiLayerNetwork``,
    ``ComputationGraph``, ``ParallelWrapper`` — anything exposing
    ``fit(data, epochs=..., resume_from=...)``, ``set_listeners`` and the
    holder internals the checkpoint layer snapshots).

    The supervised loop: claim an incarnation, anchor an initial
    checkpoint (so even a step-0 crash replays exactly), run the fit on a
    worker thread, and monitor it — classify every failure, restart from
    the last intact checkpoint within a bounded budget (exponential
    backoff, restart-storm circuit breaker), declare a hang when no step
    lands within ``hang_deadline_s``, and turn SIGTERM/SIGINT into a
    flush-quality checkpoint plus a resumable result. Because every
    restart resumes through the PR-3 exact-resume machinery (params,
    updater, RNG stream, listener state, pipeline cursor — and the data
    source rewound via the ``source_state`` protocol or a fresh factory
    call), the healed run's loss sequence is bit-identical to an
    uninterrupted one.

    ``data`` may be a zero-arg factory (recommended for stateful
    sources): it is called once per attempt, giving every restart a
    pristine source. A plain source is reused; cross-epoch state is
    rewound through ``source_state``/``restore_source_state`` when the
    source implements them.

    In-process hang abandonment leaves the wedged daemon thread behind.
    Two fences bound the damage if it later wakes: each attempt claims a
    FRESH incarnation with its own checkpoint listener, so the zombie's
    still-queued writer commits are refused at the manifest
    (:class:`util.checkpoint.StaleIncarnationError`), and the
    :class:`_AttemptFence` — first in the listener arrangement — kills
    the zombie at its next step boundary before any listener (score
    collection, checkpoint cadence) sees its callbacks. The narrow
    residue — a zombie publishing one in-flight step's params onto the
    shared holder while the replacement trains — is inherent to
    same-process threads; a thread truly stuck inside native code
    likewise keeps its OS thread until process exit. For both terminal
    cases run under :func:`supervise_processes`, which replaces the
    whole process.
    """

    def __init__(self, target, checkpoint_dir: str, *,
                 save_every_n_iterations: Optional[int] = None,
                 save_every_n_epochs: Optional[int] = None,
                 keep_last: int = 3,
                 max_total_bytes: Optional[int] = None,
                 max_restarts: int = 5,
                 backoff_base_s: float = 0.25,
                 backoff_max_s: float = 30.0,
                 storm_threshold: int = 3,
                 hang_deadline_s: Optional[float] = None,
                 hang_startup_grace_s: Optional[float] = None,
                 poll_s: float = 0.05,
                 preempt_grace_s: float = 10.0,
                 handle_signals: Optional[bool] = None,
                 policies: Optional[Dict[str, str]] = None,
                 elastic_grow: bool = True,
                 grow_probe_base_s: float = 2.0,
                 grow_probe_max_s: float = 60.0,
                 grow_failure_limit: int = 5):
        self.target = target
        self.holder = target if hasattr(target, "_params") else target.model
        self.dir = checkpoint_dir
        self.every_iter = save_every_n_iterations
        self.every_epoch = save_every_n_epochs
        self.keep_last = keep_last
        self.max_total_bytes = max_total_bytes
        self.max_restarts = max_restarts
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.storm_threshold = storm_threshold
        self.hang_deadline_s = hang_deadline_s
        # before an attempt's FIRST heartbeat, restore + retrace/compile
        # legitimately stall for longer than a steady-state step — give
        # startup its own (longer) deadline so a healthy resume is not
        # declared hung mid-compile
        self.hang_startup_grace_s = (
            hang_startup_grace_s if hang_startup_grace_s is not None
            else (max(5.0 * hang_deadline_s, 10.0)
                  if hang_deadline_s is not None else None))
        self.poll_s = poll_s
        self.preempt_grace_s = preempt_grace_s
        self.handle_signals = handle_signals
        self.policies = dict(DEFAULT_POLICIES)
        self.policies.update(policies or {})
        # elastic grow-back: after a shrink-and-continue, probe the lost
        # device(s) with exponential backoff (mirroring the inference
        # replica resurrection machinery) and rejoin them at the next
        # dispatch boundary when healthy
        self.elastic_grow = elastic_grow
        self.grow_probe_base_s = grow_probe_base_s
        self.grow_probe_max_s = grow_probe_max_s
        # consecutive failed grow RESIZES (probe-healthy device, resize
        # raises) before abandoning grow-back and staying shrunk — each
        # failed grow unwinds training, so it cannot retry forever
        self.grow_failure_limit = grow_failure_limit
        self.incarnation: Optional[int] = None
        self._preempt = threading.Event()
        self._preempt_signal: Optional[int] = None
        self._fence = _AttemptFence()
        self._old_handlers: Dict[int, Any] = {}
        self._grow: Optional[Dict[str, Any]] = None
        self._resize_request: Optional[int] = None
        self._probe_ordinal = 0

    # --- signals --------------------------------------------------------
    def _install_signals(self) -> None:
        if self.handle_signals is False:
            return
        if threading.current_thread() is not threading.main_thread():
            if self.handle_signals:
                logger.warning("supervisor: signal handlers need the main "
                               "thread; preemption signals will not be "
                               "caught in this run")
            return
        import signal as _signal

        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                self._old_handlers[sig] = _signal.signal(sig, self._on_signal)
            except (ValueError, OSError):          # exotic embeddings
                pass

    def _on_signal(self, signum, frame) -> None:
        logger.warning("supervisor: signal %s received — flush checkpoint "
                       "at the next step boundary, then exit resumable",
                       signum)
        self._preempt_signal = signum
        self._preempt.set()

    def _restore_signals(self) -> None:
        import signal as _signal

        for sig, old in self._old_handlers.items():
            try:
                _signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old_handlers = {}

    # --- elastic shrink / grow ------------------------------------------
    def _cursor_of(self) -> tuple:
        """The holder's live pipeline cursor — the exact dispatch
        boundary an in-memory continuation resumes from."""
        h = self.holder
        e0 = int(getattr(h, "_fit_epoch0", getattr(h, "_epoch", 0)))
        return (int(getattr(h, "_epoch", 0)) - e0,
                int(getattr(h, "_steps_in_epoch", 0)))

    def _holder_state_intact(self) -> bool:
        """True when the holder's published state is usable for an
        in-memory continuation: it sits at a dispatch boundary and no
        leaf was donated away (a failure INSIDE a dispatch leaves the
        pre-step buffers deleted — checkpoint-restart owns that case)."""
        import jax

        h = self.holder
        if not getattr(h, "_at_dispatch_boundary", True):
            return False
        try:
            leaves = jax.tree.leaves(
                (h._params, h._states, h._updater_state,
                 getattr(h, "_acc_state", None)))
        except Exception:
            return False
        return not any(isinstance(l, jax.Array) and l.is_deleted()
                       for l in leaves)

    def _shrink_plan(self, exc: BaseException) -> Optional[List[int]]:
        """Which replicas to drop for shrink-and-continue, or None to
        fall back to checkpoint-restart. A :class:`DeviceLostError`
        names its replica; any other device-class failure is
        ground-truthed by probing the mesh — an exception that merely
        LOOKS like a device failure must not shrink a healthy axis."""
        t = self.target
        if not callable(getattr(t, "resize", None)) \
                or getattr(t, "model_axis", 1) != 1:
            return None
        n = int(getattr(t, "workers_count", 0))
        if n <= 1 or not self._holder_state_intact():
            return None
        if isinstance(exc, faultinject.DeviceLostError) \
                and exc.replica is not None:
            lost = [int(exc.replica)]
        else:
            # a DeviceLostError without a replica id (real XLA failures
            # usually don't carry one) is ground-truthed the same way as
            # any other device-class failure: probe the mesh — guessing
            # could evict a healthy replica and keep the dead device
            probe = getattr(t, "probe_replicas", None)
            lost = list(probe()) if callable(probe) else []
        lost = sorted({r for r in lost if 0 <= r < n})
        if not lost or len(lost) >= n:
            return None
        return lost

    def _quarantine_plan(self, exc: BaseException) -> Optional[List[int]]:
        """Which replica to quarantine for a silent-corruption failure,
        or None to fall back to checkpoint-restart. Same gate as
        :meth:`_shrink_plan` minus the device probe — the device is
        HEALTHY, its *state* diverged, so the only admissible
        attribution is the in-graph majority vote the exception carries.
        ``exc.replica is None`` (2-way split, N=2) is un-attributable by
        construction: evicting a guess could quarantine the clean copy
        and keep the poisoned one."""
        t = self.target
        if not callable(getattr(t, "resize", None)) \
                or getattr(t, "model_axis", 1) != 1:
            return None
        n = int(getattr(t, "workers_count", 0))
        if n <= 1 or not self._holder_state_intact():
            return None
        rep = getattr(exc, "replica", None)
        if rep is None or not 0 <= int(rep) < n:
            return None
        return [int(rep)]

    def _apply_shrink(self, lost: List[int]) -> Optional[List[Any]]:
        """Resize the target's data axis over the survivors; arm the
        grow-back probe. Returns the removed devices, or None when the
        resize itself failed (caller falls back to checkpoint-restart)."""
        t = self.target
        old = int(t.workers_count)
        new = old - len(lost)
        try:
            removed = t.resize(new, lost_replicas=lost)
        except Exception:
            logger.warning("supervisor: online shrink to %d workers "
                           "failed; falling back to checkpoint-restart",
                           new, exc_info=True)
            return None
        logger.warning("supervisor: device loss — shrank the data axis "
                       "%d -> %d (lost replicas %s); continuing in "
                       "memory from the dispatch boundary", old, new, lost)
        self._arm_grow(old, removed)
        return removed

    def _arm_grow(self, old: int, removed) -> None:
        """Arm (or merge into) the grow-back probe after a successful
        online shrink/remap. A grow-back armed BEFORE this loss must not
        fire now: growing would reinstate a cached mesh that contains the
        newly-dead device — the merged probe re-verifies EVERY lost
        device before any grow happens."""
        self._resize_request = None
        if not (self.elastic_grow and removed):
            return
        g = self._grow
        if g is None:
            self._grow = {"target": old, "devices": list(removed),
                          "delay": self.grow_probe_base_s,
                          "next": (time.monotonic()
                                   + self.grow_probe_base_s)}
        else:
            # a SECOND loss while the first grow-back is pending:
            # merge — probe every lost device, keep the original full
            # count as the target (growing back means all the way)
            g["devices"].extend(d for d in removed
                                if d not in g["devices"])
            g["target"] = max(int(g["target"]), old)
            g["failures"] = 0
            g["delay"] = self.grow_probe_base_s
            g["next"] = time.monotonic() + self.grow_probe_base_s

    # --- elastic pipeline remap (stage axis) -----------------------------
    def _remap_plan(self, exc: BaseException) -> Optional[List[int]]:
        """Which pipeline stages to drop for remap-and-continue, or None
        to fall back to checkpoint-restart. The remap GATE: the target
        must expose the remap surface, the holder's published state must
        be boundary-consistent, the lost stage must be identifiable
        (named by :class:`faultinject.DeviceLostError` or found by
        probing the stage columns), and >= 2 stages must survive — a
        1-stage 'pipeline' is a plain fit, which checkpoint-restart
        owns."""
        t = self.target
        if not callable(getattr(t, "remap", None)):
            return None
        n = int(getattr(t, "stages_count", 0))
        if n < 2 or not self._holder_state_intact():
            return None
        if isinstance(exc, faultinject.DeviceLostError) \
                and getattr(exc, "stage", None) is not None:
            lost = [int(exc.stage)]
        else:
            probe = getattr(t, "probe_stages", None)
            lost = list(probe()) if callable(probe) else []
        lost = sorted({s for s in lost if 0 <= s < n})
        if not lost or n - len(lost) < 2:
            return None
        return lost

    def _apply_remap(self, lost: List[int]) -> Optional[List[Any]]:
        """Re-cut the pipeline over the surviving stage devices; arm the
        grow-back probe (growing back = remapping to the full stage
        count, through the per-stage-count executable cache). Returns
        the removed devices, or None when the remap itself failed
        (caller falls back to checkpoint-restart)."""
        t = self.target
        old = int(t.stages_count)
        new = old - len(lost)
        try:
            removed = t.remap(new, lost_stages=lost)
        except Exception:
            logger.warning("supervisor: online remap to %d stages "
                           "failed; falling back to checkpoint-restart",
                           new, exc_info=True)
            return None
        logger.warning("supervisor: stage loss — remapped the pipeline "
                       "%d -> %d stages (lost stages %s); continuing in "
                       "memory from the dispatch boundary", old, new, lost)
        self._arm_grow(old, removed)
        return removed

    def _maybe_probe_grow(self) -> None:
        """Grow-back probe with exponential backoff, run from the monitor
        loop. Success arms ``_resize_request``; the heartbeat turns it
        into an :class:`ElasticResizeRequested` at the next dispatch
        boundary. The ``elastic/probe`` fault site makes drills
        deterministic (a raising spec = the device is still dead)."""
        g = self._grow
        if g is None or self._resize_request is not None:
            return
        now = time.monotonic()
        if now < g["next"]:
            return
        prof = OpProfiler.get()
        prof.count("elastic/probes")
        ordinal = self._probe_ordinal
        self._probe_ordinal += 1
        try:
            faultinject.fault_point("elastic/probe", ordinal)
            healthy = self._devices_healthy(g["devices"])
        except Exception:
            healthy = False
        if healthy:
            logger.warning("supervisor: lost device(s) answer probes "
                           "again — growing the data axis back to %d at "
                           "the next dispatch boundary", g["target"])
            self._resize_request = int(g["target"])
        else:
            prof.count("elastic/probe_failures")
            g["delay"] = min(g["delay"] * 2.0, self.grow_probe_max_s)
            g["next"] = now + g["delay"]

    @staticmethod
    def _devices_healthy(devices) -> bool:
        from .mesh import probe_device

        return all(probe_device(d) for d in devices)

    # --- crash black box -------------------------------------------------
    def blackbox_path(self) -> str:
        return os.path.join(self.dir, BLACKBOX_NAME)

    def memcensus_path(self) -> str:
        return os.path.join(self.dir, MEMCENSUS_NAME)

    def _dump_blackbox(self) -> Optional[str]:
        """Dump the flight recorder's tail beside the checkpoints —
        called on every failure classification, restart, preemption and
        give-up, so the newest dump always tells the latest story (and a
        process killed right after still leaves the previous one). The
        memory census (per-phase HBM watermarks + a fresh live-buffer
        walk) rides along as ``memcensus.json``, so OOM-class failures
        carry the memory picture beside the event tail."""
        try:
            os.makedirs(self.dir, exist_ok=True)
            path = flightrec.dump_blackbox(self.blackbox_path())
        except OSError:
            logger.warning("supervisor: black-box dump to %s failed",
                           self.blackbox_path(), exc_info=True)
            return None
        try:
            # incident reports and /api/health's last_incident pointer
            # find the newest blackbox through the watchtower module
            from ..common import watchtower
            watchtower.note_blackbox(path)
        except Exception:
            pass
        try:
            from ..common import xprof

            xprof.dump_memory_census(self.memcensus_path())
        except Exception:   # census failure must not mask the blackbox
            logger.warning("supervisor: memory-census dump to %s failed",
                           self.memcensus_path(), exc_info=True)
        return path

    def _attach_blackbox(self, exc: "RestartBudgetExceeded",
                         reason: str) -> None:
        """Give-up path: record the verdict, dump the black box, and
        attach its tail to the exception — the caller's stack trace
        alone then carries the timeline that led here."""
        flightrec.event("supervisor/give_up", severity="error",
                        reason=reason)
        exc.blackbox_path = self._dump_blackbox()
        exc.blackbox_tail = flightrec.tail(64)

    # --- monitoring -----------------------------------------------------
    def _monitor(self, run: _Attempt) -> str:
        """Watch one attempt: returns ``"done"`` (thread finished, clean
        or with ``run.error``), ``"hang"`` (watchdog fired, attempt
        abandoned) or ``"preempt_timeout"`` (signal arrived but the
        thread would not reach a step boundary within the grace window —
        abandoned, best-effort recovery from the last committed
        checkpoint)."""
        prof = OpProfiler.get()
        heartbeat = run.heartbeat
        grace_deadline: Optional[float] = None
        while True:
            if run.done.wait(self.poll_s):
                return "done"
            self._maybe_probe_grow()
            now = time.monotonic()
            if self._preempt.is_set():
                if grace_deadline is None:
                    grace_deadline = now + self.preempt_grace_s
                elif now > grace_deadline:
                    run.abandoned = True
                    faultinject.release_wedges()
                    run.done.wait(2.0)
                    return "preempt_timeout"
            deadline = (self.hang_deadline_s if heartbeat.steps > 0
                        else self.hang_startup_grace_s)
            if deadline is not None and \
                    now - heartbeat.last_beat > deadline:
                prof.count("supervisor/watchdog_fires")
                flightrec.event("supervisor/watchdog_fire", severity="warn",
                                deadline_s=deadline, steps=heartbeat.steps)
                logger.warning(
                    "supervisor: watchdog — no step within %.2fs (last "
                    "heartbeat %d steps in); abandoning the wedged "
                    "dispatch and restarting from the last checkpoint",
                    deadline, heartbeat.steps)
                run.abandoned = True
                faultinject.release_wedges()
                if not run.done.wait(5.0):
                    logger.warning("supervisor: hung attempt thread did "
                                   "not unwind; abandoning it (daemon)")
                elif run.error is None:
                    # the "hung" attempt was merely slow and finished
                    # cleanly while being abandoned — that is a
                    # completion, not a failure
                    run.abandoned = False
                    return "done"
                return "hang"

    # --- the self-healing loop -----------------------------------------
    def fit(self, data, epochs: int = 1, resume: str = "auto",
            **fit_kwargs) -> SupervisedFitResult:
        """Run the wrapped fit to completion under supervision.

        ``resume="auto"`` (default): a first attempt picks up the newest
        intact checkpoint already in the directory — the relaunched-
        process story. ``resume="never"``: the first attempt starts
        fresh; checkpoints only serve restarts within THIS call."""
        from ..ndarray.rng import get_random
        from ..optimize.listeners import CheckpointListener
        from ..util import checkpoint as _ckpt

        if resume not in ("auto", "never"):
            raise ValueError(f"resume must be 'auto' or 'never', "
                             f"got {resume!r}")
        prof = OpProfiler.get()
        make_data: Optional[Callable[[], Any]] = \
            data if callable(data) else None
        src = make_data() if make_data else data
        source_state = None
        if make_data is None:
            state_fn = getattr(src, "source_state", None)
            if callable(state_fn):
                source_state = state_fn()
        user_listeners = list(getattr(self.target, "_listeners", []))
        if not user_listeners and self.holder is not self.target:
            # wrapper target with listeners attached to the MODEL: they
            # must ride the supervised arrangement (and its checkpoints),
            # not be silently displaced by it
            user_listeners = list(getattr(self.holder, "_listeners", []))
        target_restore = list(getattr(self.target, "_listeners", []))
        entry_rng = get_random().get_state()
        self._preempt.clear()
        self._preempt_signal = None
        self._grow = None
        self._resize_request = None
        self._probe_ordinal = 0
        self._install_signals()
        history: List[dict] = []
        restarts = 0
        consec_no_progress = 0
        # armed by a successful shrink/grow: (pipeline cursor, rng state)
        # for an IN-MEMORY continuation — the next attempt resumes from
        # the holder's live state instead of a checkpoint
        mem_resume: Optional[tuple] = None
        # set when a silent-corruption failure falls back to restart:
        # the live holder state is poisoned, so the resume point must be
        # a generation the background scrubber has re-verified
        prefer_scrubbed = False
        status = "completed"
        resume_path: Optional[str] = None
        final_exc: Optional[BaseException] = None
        run: Optional[_Attempt] = None
        ckpt = None

        def new_attempt_listener():
            # one incarnation + checkpoint listener PER attempt: a zombie
            # attempt's still-queued writer holds a now-stale incarnation
            # and its commits are refused at the manifest
            self.incarnation = _ckpt.claim_incarnation(self.dir)
            return CheckpointListener(
                self.dir, save_every_n_iterations=self.every_iter,
                save_every_n_epochs=self.every_epoch,
                keep_last=self.keep_last,
                max_total_bytes=self.max_total_bytes,
                incarnation=self.incarnation)

        try:
            ckpt = new_attempt_listener()
            resume_from = (_ckpt.last_checkpoint(self.dir)
                           if resume == "auto" else None)
            if resume_from is None:
                # anchor checkpoint: even a crash before the first
                # periodic save restarts bit-exactly (initial params,
                # updater, the entry RNG key the first attempt seeds,
                # and the PRE-FIT listener state — a restart from the
                # anchor must also rewind score histories). The group is
                # bound in the supervised arrangement's positions so the
                # position+class restore keys line up.
                self.holder._fit_epoch0 = self.holder._epoch
                self.holder._steps_in_epoch = 0
                ckpt.bind_group([self._fence, *user_listeners])
                ckpt.save_now(
                    self.holder,
                    f"init_{int(getattr(self.holder, '_iteration', 0))}",
                    rng_state=entry_rng)
            attempt = 0
            while True:
                attempt += 1
                prof.count("supervisor/attempts")
                faultinject.reset_wedges()
                if attempt > 1:
                    # drain the failed attempt's async writer BEFORE
                    # choosing the resume point: a checkpoint submitted
                    # just before the crash should not be replayed past
                    ckpt.close()
                    ckpt = new_attempt_listener()
                    # in-memory continuation (post-shrink/grow): the
                    # holder IS the resume point — no checkpoint restore
                    resume_from = (None if mem_resume is not None
                                   else _ckpt.last_checkpoint(
                                       self.dir,
                                       require_scrubbed=prefer_scrubbed))
                    if make_data:
                        src = make_data()
                    elif source_state is not None:
                        src.restore_source_state(source_state)
                attempt_kwargs = fit_kwargs
                attempt_rng = entry_rng
                if mem_resume is not None:
                    cursor, rng_state = mem_resume
                    mem_resume = None
                    attempt_kwargs = dict(fit_kwargs, resume_cursor=cursor)
                    attempt_rng = rng_state
                # the incarnation.attempt correlation id every event
                # emitted during this attempt inherits — checkpoint
                # commits (writer thread), fault firings, pipeline
                # epochs, elastic resizes: one grep reconstructs the
                # whole kill-restart-resume incident
                flightrec.set_correlation(
                    f"inc{self.incarnation}.a{attempt}")
                flightrec.event(
                    "supervisor/attempt_start", attempt=attempt,
                    resume=("cursor%s" % (attempt_kwargs["resume_cursor"],)
                            if "resume_cursor" in attempt_kwargs
                            else resume_from))
                if attempt > 1:
                    # the black box now holds the full
                    # fault → classify → restart → resume chain
                    self._dump_blackbox()
                heartbeat = _Heartbeat(self)
                # arrangement: the fence first (kills zombie threads
                # before ANY listener sees their callbacks), user
                # listeners next (their state rides the checkpoint), the
                # checkpoint listener (a due save still lands at
                # iteration boundaries), the heartbeat last (a preempted
                # step is recorded by everything before it unwinds)
                self.target.set_listeners(self._fence, *user_listeners,
                                          ckpt, heartbeat)
                run = _Attempt(self, attempt, src, epochs, resume_from,
                               attempt_kwargs, attempt_rng, heartbeat)
                self._fence.thread = run.thread
                with flightrec.span("supervisor/attempt", attempt=attempt):
                    run.start()
                    outcome = self._monitor(run)
                if outcome == "done" and run.error is None:
                    break
                if outcome == "done" and \
                        isinstance(run.error, ElasticResizeRequested):
                    # grow-back: the probe found the lost device healthy
                    # and the attempt unwound at a dispatch boundary —
                    # resize up and continue in memory from that cursor
                    target_n = self._resize_request
                    self._resize_request = None
                    grown = False
                    if target_n:
                        try:
                            self.target.resize(int(target_n))
                            grown = True
                            self._grow = None
                            prof.count("supervisor/grows")
                            logger.warning("supervisor: data axis grown "
                                           "back to %d workers", target_n)
                        except Exception:
                            g = self._grow
                            fails = (g.get("failures", 0) + 1
                                     if g is not None else 1)
                            if g is not None and \
                                    fails >= self.grow_failure_limit:
                                # the device answers probes but the grow
                                # resize keeps failing (e.g. it returned
                                # degraded, placement OOMs): give up and
                                # stay shrunk rather than unwinding
                                # training every backoff period forever
                                logger.warning(
                                    "supervisor: grow-back resize to %s "
                                    "failed %d times; giving up — "
                                    "staying shrunk", target_n, fails,
                                    exc_info=True)
                                self._grow = None
                                prof.count("elastic/grow_abandoned")
                            else:
                                logger.warning(
                                    "supervisor: grow-back resize to %s "
                                    "failed; staying shrunk and "
                                    "re-arming the probe", target_n,
                                    exc_info=True)
                                if g is not None:
                                    g["failures"] = fails
                                    g["delay"] = min(
                                        g["delay"] * 2.0,
                                        self.grow_probe_max_s)
                                    g["next"] = (time.monotonic()
                                                 + g["delay"])
                    history.append({
                        "attempt": attempt, "class": "elastic_grow",
                        "policy": ("grow_and_continue" if grown
                                   else "grow_failed"),
                        "error": repr(run.error),
                        "steps": run.heartbeat.steps,
                        "iteration": int(getattr(self.holder,
                                                 "_iteration", 0)),
                    })
                    consec_no_progress = 0
                    mem_resume = (self._cursor_of(),
                                  run.rng_state or entry_rng)
                    continue
                watchdogged = outcome == "hang"
                if watchdogged:
                    exc: BaseException = HangDetected(
                        f"no step within {self.hang_deadline_s}s "
                        f"({run.heartbeat.steps} steps landed this "
                        f"attempt); thread error: {run.error!r}")
                else:
                    exc = run.error or HangDetected(
                        f"attempt abandoned ({outcome})")
                cls = CLASS_HANG if watchdogged else classify_failure(exc)
                policy = self.policies.get(cls, "restart")
                if policy == "shrink_and_continue" \
                        and callable(getattr(self.target, "remap", None)):
                    # pipeline targets heal the STAGE axis: the
                    # device-failure default resolves to elastic remap
                    policy = "remap_and_continue"
                shrink_lost: Optional[List[int]] = None
                remap_lost: Optional[List[int]] = None
                if policy == "shrink_and_continue":
                    # only a finished (non-abandoned) attempt left a
                    # trustworthy dispatch-boundary state behind; a
                    # wedged zombie might still be mutating the holder
                    if outcome == "done" and not run.abandoned:
                        shrink_lost = self._shrink_plan(exc)
                    if shrink_lost is None:
                        policy = "restart"   # the documented fallback
                if policy == "remap_and_continue":
                    # same boundary-trust rule as shrink; the remap gate
                    # (_remap_plan) refusing = checkpoint-restart fallback
                    if outcome == "done" and not run.abandoned:
                        remap_lost = self._remap_plan(exc)
                    if remap_lost is None:
                        policy = "restart"
                quarantine_lost: Optional[List[int]] = None
                if policy == "quarantine_and_continue":
                    # same boundary-trust rule; an un-attributable
                    # divergence (exc.replica None — 2-way split, N=2)
                    # or a refused gate falls back to checkpoint-restart
                    # from a scrub-VERIFIED generation: the live state
                    # is poisoned and majority vote cannot say where
                    if outcome == "done" and not run.abandoned:
                        quarantine_lost = self._quarantine_plan(exc)
                    if quarantine_lost is None:
                        policy = "restart"
                        prefer_scrubbed = True
                history.append({
                    "attempt": attempt, "class": cls, "policy": policy,
                    "error": repr(exc), "steps": run.heartbeat.steps,
                    "iteration": int(getattr(self.holder, "_iteration", 0)),
                })
                logger.warning("supervisor: attempt %d failed [%s → %s]: "
                               "%r", attempt, cls, policy, exc)
                # classification on the record, then the black box: a
                # postmortem reads fault site, class and restart decision
                # from the JSONL alone
                flightrec.event("supervisor/attempt_failed",
                                severity="error", attempt=attempt,
                                failure_class=cls, policy=policy,
                                error=repr(exc)[:300],
                                steps=run.heartbeat.steps)
                self._dump_blackbox()
                # every failure classification triggers incident
                # assembly on the installed watchtower (no-op when none
                # is installed — supervision owes observability nothing)
                try:
                    from ..common import watchtower
                    watchtower.note_supervisor_failure(
                        failure_class=cls, policy=policy,
                        error=repr(exc)[:200])
                except Exception:
                    logger.warning("supervisor: watchtower incident hook "
                                   "failed", exc_info=True)
                # the POLICY decides (so a policies={"preemption":
                # "restart"} override is honored); a grace-window timeout
                # always exits — the environment is reclaiming us
                if policy == "exit" or outcome == "preempt_timeout":
                    prof.count("supervisor/preemptions")
                    status = "preempted"
                    if run.done.is_set() and not run.abandoned and \
                            run.rng_state is not None:
                        resume_path = ckpt.save_now(
                            self.holder,
                            f"preempt_{int(self.holder._iteration)}",
                            rng_state=run.rng_state)
                    else:
                        # thread abandoned mid-dispatch: its state is not
                        # boundary-consistent — fall back to what already
                        # committed
                        ckpt.flush()
                        resume_path = _ckpt.last_checkpoint(self.dir)
                    flightrec.event("supervisor/preempted", severity="warn",
                                    signal=self._preempt_signal,
                                    resume_from=resume_path)
                    self._dump_blackbox()
                    break
                if policy == "raise":
                    final_exc = exc
                    break
                if policy == "remap_and_continue":
                    removed = self._apply_remap(remap_lost)
                    if removed is None:
                        # the remap itself failed mid-flight — rare (the
                        # plan vetted the gate); checkpoint-restart owns it
                        history[-1]["policy"] = "remap_failed_restart"
                        policy = "restart"
                    else:
                        prof.count("supervisor/remaps")
                        # same budget accounting as shrink: a successful
                        # online remap IS progress — no restart consumed,
                        # storm breaker reset
                        consec_no_progress = 0
                        mem_resume = (self._cursor_of(),
                                      run.rng_state or entry_rng)
                        continue
                if policy == "shrink_and_continue":
                    removed = self._apply_shrink(shrink_lost)
                    if removed is None:
                        # the resize itself failed — the documented
                        # fallback (the plan already vetted everything
                        # else, so this is rare: e.g. a survivor died
                        # between plan and resize)
                        history[-1]["policy"] = "shrink_failed_restart"
                        policy = "restart"
                    else:
                        prof.count("supervisor/shrinks")
                        # restart-budget accounting: a successful online
                        # shrink IS progress — the axis is healthy again
                        # and training continues from the same boundary —
                        # so it consumes no restart and resets the storm
                        # breaker: a single device loss can never
                        # contribute to a RestartStorm trip
                        consec_no_progress = 0
                        mem_resume = (self._cursor_of(),
                                      run.rng_state or entry_rng)
                        continue
                if policy == "quarantine_and_continue":
                    # the mitigation anchor BEFORE the resize: the
                    # incident chain reads decision → elastic/resize →
                    # next attempt_start, with the cause (fault/fired)
                    # and detection (integrity/divergence) already on
                    # the record naming the replica
                    flightrec.event(
                        "integrity/quarantine", severity="warn",
                        replica=quarantine_lost[0],
                        iteration=int(getattr(self.holder,
                                              "_iteration", 0)))
                    removed = self._apply_shrink(quarantine_lost)
                    if removed is None:
                        # the resize itself failed mid-flight — rare
                        # (the plan vetted the gate); restart from a
                        # scrub-verified generation owns it
                        history[-1]["policy"] = "quarantine_failed_restart"
                        policy = "restart"
                        prefer_scrubbed = True
                    else:
                        prof.count("supervisor/quarantines")
                        # same budget accounting as shrink: quarantining
                        # the divergent replica IS progress — survivors
                        # carry majority-consistent state from the exact
                        # boundary — so no restart is consumed and the
                        # storm breaker resets; the quarantined device
                        # gets the same grow-back probe (it must prove
                        # itself before rejoining)
                        consec_no_progress = 0
                        mem_resume = (self._cursor_of(),
                                      run.rng_state or entry_rng)
                        continue
                # checkpoint-restart
                if cls == CLASS_PREEMPTION:
                    # a preemption override routed here: consume the
                    # signal, or the next attempt preempts instantly
                    self._preempt.clear()
                    self._preempt_signal = None
                if run.heartbeat.steps > 0:
                    consec_no_progress = 0
                else:
                    consec_no_progress += 1
                if consec_no_progress >= self.storm_threshold:
                    prof.count("supervisor/storm_trips")
                    final_exc = RestartStorm(
                        f"restart storm: {consec_no_progress} consecutive "
                        f"restarts with zero steps of progress", history)
                    self._attach_blackbox(final_exc, "storm")
                    break
                if restarts >= self.max_restarts:
                    prof.count("supervisor/giveups")
                    final_exc = RestartBudgetExceeded(
                        f"restart budget ({self.max_restarts}) exhausted",
                        history)
                    self._attach_blackbox(final_exc, "budget")
                    break
                restarts += 1
                prof.count("supervisor/restarts")
                delay = (self.backoff_base_s if policy == "retry" else
                         min(self.backoff_base_s * (2 ** (restarts - 1)),
                             self.backoff_max_s))
                flightrec.event("supervisor/restart", severity="warn",
                                restarts=restarts, policy=policy,
                                backoff_s=delay)
                with prof.time_section("supervisor/backoff"):
                    # interruptible: a preemption signal during backoff
                    # must not wait the backoff out
                    self._preempt.wait(delay)
                if self._preempt.is_set():
                    prof.count("supervisor/preemptions")
                    status = "preempted"
                    ckpt.flush()
                    resume_path = _ckpt.last_checkpoint(self.dir)
                    flightrec.event("supervisor/preempted", severity="warn",
                                    signal=self._preempt_signal,
                                    resume_from=resume_path)
                    self._dump_blackbox()
                    break
        finally:
            self._restore_signals()
            self._fence.thread = None
            try:
                if ckpt is not None:
                    # drains the async writer — its final commits still
                    # belong to the last attempt, so the ambient
                    # correlation is cleared only AFTER they land
                    ckpt.close()
            finally:
                self.target.set_listeners(*target_restore)
                flightrec.set_correlation(None)
        if final_exc is not None:
            raise final_exc
        if status == "completed":
            flightrec.event("supervisor/completed",
                            corr=f"inc{self.incarnation}.a{attempt}",
                            attempts=attempt, restarts=restarts)
            self._dump_blackbox()
        if status == "completed" and run is not None \
                and run.rng_state is not None:
            # RNG transparency: the caller's stream ends where a plain
            # (unsupervised) fit would have left it
            get_random().set_state(run.rng_state)
        return SupervisedFitResult(status, resume_path, restarts,
                                   attempt, history)


# ---------------------------------------------------------------------------
# process-level supervision (the multi-host restart loop)
# ---------------------------------------------------------------------------

def supervise_processes(commands: List[List[str]], *,
                        max_restarts: int = 5,
                        backoff_base_s: float = 1.0,
                        backoff_max_s: float = 60.0,
                        storm_threshold: int = 3,
                        storm_min_uptime_s: float = 1.0,
                        env: Optional[Dict[str, str]] = None,
                        make_env: Optional[Callable[[int],
                                                    Optional[dict]]] = None,
                        poll_s: float = 0.05,
                        kill_grace_s: float = 5.0,
                        resumable_code: int =
                        SupervisedFitResult.resumable_exit_code,
                        cluster_dir: Optional[str] = None,
                        heartbeat_stale_s: float = 10.0,
                        make_commands: Optional[Callable[[int, int],
                                                         List[List[str]]]]
                        = None,
                        shrink_to_survivors: bool = False,
                        min_world: int = 1) -> dict:
    """Supervised restart loop for a synchronous SPMD process group — the
    in-framework replacement for "relaunch the same command" runbooks and
    the reference mesh's dead-node remap. All ``commands`` launch
    together (command ``i`` is rank ``i``); if ANY participant dies, the
    survivors are terminated cleanly (SIGTERM → ``kill_grace_s`` →
    SIGKILL, zero orphans — synchronous collectives cannot continue
    around a lost host; SURVEY §5.8, arXiv:2004.13336) and the group
    relaunches after exponential backoff, resuming from its checkpoint
    directory.

    Per-rank exit CLASSIFICATION: ``resumable_code`` (75/EX_TEMPFAIL,
    what a supervised fit's preempted status maps to) → ``"preempted"``
    — the loop returns ``status="preempted"`` instead of burning
    restarts (the cluster scheduler owns the relaunch); a rank whose
    heartbeat in ``cluster_dir`` goes staler than ``heartbeat_stale_s``
    while its process is still alive → ``"hang"`` (the group is killed
    and restarted — a wedged collective never exits on its own); any
    other nonzero exit → ``"crash"``. History rows carry the class per
    rank.

    With ``cluster_dir`` set the supervisor is also the INCIDENT
    assembler: on a lost rank it merges every rank's dumped blackbox
    (``cluster.merge_rank_blackboxes``), emits ``cluster/rank_lost``
    (the chain cause, ``rank`` attr) + ``cluster/group_restart``, and —
    when a watchtower is installed — opens one incident carrying the
    merged per-rank events; the relaunched group's fresh heartbeats emit
    ``cluster/form`` (the chain recovery).

    ELASTIC restart: with ``shrink_to_survivors=True`` and a
    ``make_commands(world, attempt)`` factory, a crashed/hung rank
    SHRINKS the group — the relaunch runs ``world-1`` commands (down to
    ``min_world``) and the workers re-form the smaller mesh, resharding
    updater state through the checkpoint's replica-count-independent
    layout (``Zero1Plan``). Without it the full-count group relaunches.

    ``make_env(attempt)`` layers per-attempt environment on top of
    ``env`` (e.g. a fault plan for the first incarnation only). The
    restart-storm breaker trips on ``storm_threshold`` consecutive
    groups that died within ``storm_min_uptime_s``."""
    import subprocess

    prof = OpProfiler.get()
    history: List[dict] = []
    restarts = 0
    consec_fast = 0
    attempt = 0
    world = len(commands)

    def _classify(code: Optional[int], hung: bool) -> str:
        if hung:
            return CLASS_HANG
        if code == resumable_code:
            return "preempted"
        return "crash" if code not in (0, None) else "ok"

    def _reap(procs: List[Any]) -> None:
        """SIGTERM every survivor, grace, SIGKILL the stragglers — the
        zero-orphans contract the cluster-smoke process-table sweep
        asserts."""
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + kill_grace_s
        for p in procs:
            try:
                p.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(5.0)

    while True:
        attempt += 1
        prof.count("supervisor/proc_attempts")
        e = dict(os.environ)
        e.update(env or {})
        if make_env is not None:
            e.update(make_env(attempt - 1) or {})
        cmds = (make_commands(world, attempt - 1)
                if make_commands is not None else commands)
        if cluster_dir is not None:
            # the previous incarnation's heartbeat files must not count
            # toward (or against) the relaunched group's formation
            from . import cluster as _cluster

            for r in range(max(world, len(cmds)) + 8):
                try:
                    os.remove(_cluster.heartbeat_path(cluster_dir, r))
                except OSError:
                    pass
        t0 = time.monotonic()
        procs: List[Any] = []
        try:
            for c in cmds:
                procs.append(subprocess.Popen(list(c), env=e))
        except Exception:
            # a rank that cannot even launch must not orphan the ranks
            # already running (they would hold the checkpoint dir)
            _reap(procs)
            raise
        failed_rank: Optional[int] = None
        hung = False
        formed_seen = cluster_dir is None
        while True:
            codes = [p.poll() for p in procs]
            failed_rank = next((i for i, c in enumerate(codes)
                                if c not in (None, 0)), None)
            if failed_rank is not None or all(c == 0 for c in codes):
                break
            if cluster_dir is not None:
                from . import cluster as _cluster

                hb = _cluster.read_heartbeats(cluster_dir)
                if not formed_seen and all(
                        r in hb and hb[r]["age_s"] <= heartbeat_stale_s
                        for r in range(len(procs))):
                    formed_seen = True
                    prof.count("cluster/groups_formed")
                    # the supervisor's own recovery anchor: every rank
                    # of the (re)launched group is heartbeating
                    flightrec.event("cluster/form", rank=-1,
                                    world=len(procs), attempts=attempt,
                                    observer="supervisor")
                stale = [r for r in range(len(procs))
                         if codes[r] is None and r in hb
                         and hb[r]["age_s"] > heartbeat_stale_s]
                if stale:
                    failed_rank, hung = stale[0], True
                    break
            time.sleep(poll_s)
        uptime = time.monotonic() - t0
        if failed_rank is None:
            return {"status": "completed", "attempts": attempt,
                    "restarts": restarts, "world": world,
                    "history": history}
        failed_code = procs[failed_rank].poll()
        pre_codes = list(codes)   # the detection-time snapshot
        _reap(procs)
        codes = [p.poll() for p in procs]
        classes = {r: ("terminated" if pre_codes[r] is None
                       and r != failed_rank
                       else _classify(codes[r], hung and r == failed_rank))
                   for r in range(len(procs))}
        cls = classes[failed_rank]
        history.append({"attempt": attempt, "codes": codes,
                        "failed_rank": failed_rank, "classes": classes,
                        "world": world, "uptime_s": round(uptime, 3)})
        logger.warning("supervise_processes: rank %d %s (exit %s) after "
                       "%.2fs", failed_rank, cls,
                       failed_code if not hung else "none/heartbeat-stale",
                       uptime)
        merged: List[dict] = []
        if cluster_dir is not None:
            from . import cluster as _cluster

            merged = _cluster.merge_rank_blackboxes(cluster_dir)
        prof.count(f"cluster/rank_{cls}")
        flightrec.event("cluster/rank_lost", severity="error",
                        rank=failed_rank, code=failed_code,
                        world=world, hung=hung, **{"class": cls})
        flightrec.event("supervisor/attempt_failed", severity="error",
                        rank=failed_rank, error=f"rank {failed_rank} "
                        f"{cls}", **{"class": cls})
        if cls == "preempted":
            return {"status": "preempted", "resumable": True,
                    "attempts": attempt, "restarts": restarts,
                    "world": world, "history": history}
        from ..common import watchtower as _watchtower

        tower = _watchtower.get()
        if tower is not None:
            tower.assemble_incident(
                "rank_lost",
                f"rank {failed_rank} {cls} "
                f"(exit {'heartbeat-stale' if hung else failed_code})",
                attachments={"lost_rank": failed_rank, "class": cls,
                             "world": world,
                             "rank_blackboxes": merged} if merged else
                {"lost_rank": failed_rank, "class": cls, "world": world})
        consec_fast = consec_fast + 1 if uptime < storm_min_uptime_s else 0
        if consec_fast >= storm_threshold:
            prof.count("supervisor/storm_trips")
            raise RestartStorm(
                f"process group died {consec_fast} consecutive times "
                f"within {storm_min_uptime_s}s", history)
        if restarts >= max_restarts:
            prof.count("supervisor/giveups")
            raise RestartBudgetExceeded(
                f"process-group restart budget ({max_restarts}) exhausted",
                history)
        world_to = world
        if shrink_to_survivors and make_commands is not None \
                and world - 1 >= min_world:
            world_to = world - 1
            prof.count("cluster/shrinks")
        flightrec.event("cluster/group_restart", severity="warn",
                        rank=failed_rank, world_from=world,
                        world_to=world_to, attempt=attempt, **{"class": cls})
        world = world_to
        restarts += 1
        prof.count("supervisor/proc_restarts")
        delay = min(backoff_base_s * (2 ** (restarts - 1)), backoff_max_s)
        with prof.time_section("supervisor/backoff"):
            time.sleep(delay)


class SharedTrainingMaster:
    """Reference SharedTrainingMaster-shaped front for synchronous multi-host
    SPMD: same builder surface (workers/batch sizes/threshold config accepted),
    fit() delegates to a ParallelWrapper over ALL global devices, and a
    checkpoint listener provides the restart-based fault story."""

    class Builder:
        def __init__(self, batch_size_per_worker: int = 32):
            self._batch = batch_size_per_worker
            self._workers_per_node: Optional[int] = None
            self._threshold: Optional[Any] = None
            self._accumulator: Optional[Any] = None
            self._checkpoint_dir: Optional[str] = None
            self._checkpoint_every = 0

        def workers_per_node(self, n: int) -> "SharedTrainingMaster.Builder":
            self._workers_per_node = n
            return self

        def threshold_algorithm(self, alg) -> "SharedTrainingMaster.Builder":
            # Selects the REAL threshold-encoded exchange (residual carry +
            # adaptive threshold compiled into the step — the DCN/host-
            # boundary path; over ICI the dense default is faster, see
            # parallel/accumulator.py)
            self._threshold = alg
            return self

        def gradients_accumulator(self, acc) -> "SharedTrainingMaster.Builder":
            """Explicit exchange strategy — e.g.
            :class:`ReduceScatterAccumulator` for ZeRO-1 weight-update
            sharding (sharded updater state, 1/N per replica). Takes
            precedence over ``threshold_algorithm``."""
            self._accumulator = acc
            return self

        def checkpoint(self, directory: str, every_n_iterations: int
                       ) -> "SharedTrainingMaster.Builder":
            self._checkpoint_dir = directory
            self._checkpoint_every = every_n_iterations
            return self

        def build(self) -> "SharedTrainingMaster":
            return SharedTrainingMaster(self._batch, self._workers_per_node,
                                        self._checkpoint_dir,
                                        self._checkpoint_every,
                                        self._threshold, self._accumulator)

    def __init__(self, batch_size_per_worker: int,
                 workers_per_node: Optional[int],
                 checkpoint_dir: Optional[str], checkpoint_every: int,
                 threshold_algorithm: Optional[Any] = None,
                 accumulator: Optional[Any] = None):
        self.batch_size_per_worker = batch_size_per_worker
        self.workers_per_node = workers_per_node
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.threshold_algorithm = threshold_algorithm
        self.accumulator = accumulator
        # the last supervised run's SupervisedFitResult (status/restarts/
        # failure history); None before any supervised fit
        self.last_result: Optional["SupervisedFitResult"] = None

    def workers(self) -> int:
        """Global worker count. Single-process: workers_per_node bounds the
        device count. Multi-process SPMD requires every host's devices in the
        mesh, so a workers_per_node below local_device_count cannot be
        honored there — raise rather than build a mesh that silently excludes
        one host's devices."""
        import jax

        if self.workers_per_node is None:
            return len(jax.devices())
        if jax.process_count() > 1:
            if self.workers_per_node < jax.local_device_count():
                raise ValueError(
                    "workers_per_node < local device count is not supported "
                    "in multi-process SPMD (all addressable devices must "
                    "participate in the mesh); unset workers_per_node or set "
                    f"it to {jax.local_device_count()}")
            return len(jax.devices())
        return min(self.workers_per_node, jax.local_device_count())

    def fit(self, model, data, epochs: int = 1, *,
            supervise: bool = True,
            supervisor_opts: Optional[Dict[str, Any]] = None):
        """Train `model` over all global devices. With a checkpoint
        directory configured the run is SELF-HEALING by default: a
        :class:`TrainingSupervisor` wraps the wrapper's fit — failure
        classification, bounded checkpoint-restart, hang watchdog,
        preemption-signal flush, incarnation fence — and a relaunched
        process resumes from the newest INTACT checkpoint automatically
        (the checkpoint's cursor fast-forwards the input pipeline so the
        continuation is bit-exact; a checkpoint torn by the kill is
        skipped by checksum). Listeners already attached to ``model`` are
        preserved and forwarded, their state riding the checkpoints. The
        supervised result lands on ``self.last_result`` (status /
        restarts / failure history); ``supervise=False`` keeps the plain
        single-attempt behavior, and ``supervisor_opts`` forwards to the
        :class:`TrainingSupervisor` constructor (budget, backoff,
        ``hang_deadline_s``, policies...)."""
        from ..optimize.listeners import CheckpointListener
        from .accumulator import EncodedGradientsAccumulator
        from .wrapper import ParallelWrapper

        builder = (ParallelWrapper.Builder(model)
                   .workers(self.workers())
                   .training_mode("shared_gradients"))
        if self.accumulator is not None:
            builder.gradients_accumulator(self.accumulator)
        elif self.threshold_algorithm is not None:
            builder.gradients_accumulator(
                EncodedGradientsAccumulator(threshold_algorithm=self.threshold_algorithm))
        pw = builder.build()
        # the reference master forwards the model's listeners to its
        # trainers; dropping them silently (pre-supervisor behavior) lost
        # user score/eval hooks the moment training went distributed
        user_listeners = list(getattr(model, "_listeners", []))
        if user_listeners:
            pw.set_listeners(*user_listeners)
        if self.checkpoint_dir and supervise:
            # a configured directory is enough to supervise: with no
            # periodic cadence the anchor checkpoint still makes restarts
            # and preemption flushes exact (restarts just replay more)
            sup = TrainingSupervisor(
                pw, self.checkpoint_dir,
                save_every_n_iterations=self.checkpoint_every or None,
                **(supervisor_opts or {}))
            self.last_result = sup.fit(data, epochs=epochs)
            return model
        resume = (CheckpointListener.last_checkpoint(self.checkpoint_dir)
                  if self.checkpoint_dir else None)
        ckpt = None
        if self.checkpoint_dir and self.checkpoint_every:
            ckpt = CheckpointListener(
                self.checkpoint_dir,
                save_every_n_iterations=self.checkpoint_every)
            pw.set_listeners(*user_listeners, ckpt)
        try:
            pw.fit(data, epochs=epochs, resume_from=resume)
        finally:
            if ckpt is not None:
                ckpt.close()   # durability point: all submitted writes commit
        return model
