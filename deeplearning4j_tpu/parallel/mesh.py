"""Device mesh utilities.

TPU-native replacement for the reference's device topology handling
(``CudaAffinityManager`` thread→device pinning, SURVEY.md §2.4): on TPU,
topology is a ``jax.sharding.Mesh`` over ICI and replication/sharding is a
compiler annotation, not a trainer-thread layout. Axis convention follows the
scaling-book recipe: ``data`` (batch), ``model`` (tensor parallel).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(data: Optional[int] = None, model: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a (data, model) mesh. data=None uses all remaining devices."""
    devs = list(devices) if devices is not None else jax.devices()
    if data is None:
        if len(devs) % model:
            raise ValueError(f"{len(devs)} devices not divisible by model={model}")
        data = len(devs) // model
    n = data * model
    if n > len(devs):
        raise ValueError(f"need {n} devices, have {len(devs)}")
    arr = np.asarray(devs[:n]).reshape(data, model)
    return Mesh(arr, ("data", "model"))


def make_pipeline_mesh(data: int, stages: int,
                       devices: Optional[Sequence] = None) -> Mesh:
    """Build a (data, stage) mesh for pipeline-parallel training: stage
    columns hold the layer-partition rows, the data axis replicates the
    pipeline over batch shards. The third mesh axis of the scaling
    recipe (data × model × pipeline); kept as its own constructor
    because the stage axis resizes by REMAP (parallel.pipeline), not by
    the data-axis elastic path."""
    devs = list(devices) if devices is not None else jax.devices()
    data, stages = int(data), int(stages)
    if data < 1 or stages < 1:
        raise ValueError(f"need data >= 1 and stages >= 1, got "
                         f"({data}, {stages})")
    n = data * stages
    if n > len(devs):
        raise ValueError(f"need {n} devices for a ({data} x {stages}) "
                         f"pipeline mesh, have {len(devs)}")
    arr = np.asarray(devs[:n]).reshape(data, stages)
    return Mesh(arr, ("data", "stage"))


def elastic_pool(mesh: Mesh, exclude: Sequence = (),
                 devices: Optional[Sequence] = None) -> list:
    """Device pool for an online elastic resize: the current mesh's
    SURVIVING devices first (growing back reuses the positions — and the
    per-worker-count compiled executables — the survivors already hold),
    then every other available device (hot spares, a returning device),
    with ``exclude`` (the lost devices) filtered throughout."""
    excl = set(exclude)
    pool = [d for d in mesh.devices.flat if d not in excl]
    for d in (devices if devices is not None else jax.devices()):
        if d not in excl and d not in pool:
            pool.append(d)
    return pool


def serving_devices(workers: int,
                    devices: Optional[Sequence] = None) -> list:
    """Round-robin device assignment for an inference replica pool: the
    serving analog of the training mesh (one coalescing replica per chip
    when there are enough chips; replicas time-share otherwise). The
    serving tier uses it to pin each replica's AOT executable arguments —
    a replica's params live on its device, so concurrent replicas run on
    DIFFERENT chips instead of contending for one XLA stream."""
    devs = list(devices) if devices is not None else jax.devices()
    if not devs:
        raise ValueError("no devices available for serving replicas")
    return [devs[i % len(devs)] for i in range(max(1, int(workers)))]


def serving_capacity(devices: Optional[Sequence] = None) -> int:
    """How many serving replicas the topology supports before they only
    time-share chips: the device count. The autoscaler's default
    ``max_workers`` is a small multiple of this — replicas beyond it add
    queueing, not throughput."""
    devs = list(devices) if devices is not None else jax.devices()
    return max(1, len(devs))


def probe_device(device) -> bool:
    """Tiny host→device→host round-trip health probe: True when the
    device accepts a placement and hands back finite data. The single
    ground-truth check behind both the wrapper's ``probe_replicas`` and
    the supervisor's grow-back probe."""
    try:
        x = jax.device_put(np.ones((2,), np.float32), device)
        return bool(np.isfinite(float(np.asarray(x).sum())))
    except Exception:
        return False


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, batch_axis: int = 0) -> NamedSharding:
    spec = [None] * (batch_axis + 1)
    spec[batch_axis] = "data"
    return NamedSharding(mesh, P(*spec))


def shard_batch(mesh: Mesh, *arrays):
    """Place arrays with the leading axis split over the data axis."""
    sh = data_sharded(mesh)
    out = tuple(jax.device_put(a, sh) for a in arrays)
    return out if len(out) > 1 else out[0]
