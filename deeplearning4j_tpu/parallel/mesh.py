"""Device mesh utilities.

TPU-native replacement for the reference's device topology handling
(``CudaAffinityManager`` thread→device pinning, SURVEY.md §2.4): on TPU,
topology is a ``jax.sharding.Mesh`` over ICI and replication/sharding is a
compiler annotation, not a trainer-thread layout. Axis convention follows the
scaling-book recipe: ``data`` (batch), ``model`` (tensor parallel).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(data: Optional[int] = None, model: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a (data, model) mesh. data=None uses all remaining devices."""
    devs = list(devices) if devices is not None else jax.devices()
    if data is None:
        if len(devs) % model:
            raise ValueError(f"{len(devs)} devices not divisible by model={model}")
        data = len(devs) // model
    n = data * model
    if n > len(devs):
        raise ValueError(f"need {n} devices, have {len(devs)}")
    arr = np.asarray(devs[:n]).reshape(data, model)
    return Mesh(arr, ("data", "model"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, batch_axis: int = 0) -> NamedSharding:
    spec = [None] * (batch_axis + 1)
    spec[batch_axis] = "data"
    return NamedSharding(mesh, P(*spec))


def shard_batch(mesh: Mesh, *arrays):
    """Place arrays with the leading axis split over the data axis."""
    sh = data_sharded(mesh)
    out = tuple(jax.device_put(a, sh) for a in arrays)
    return out if len(out) > 1 else out[0]
