"""ParallelInference — request batching for serving.

Reference: dl4j-scaleout ``org.deeplearning4j.parallelism.ParallelInference``
(SURVEY.md §2.4, §3.7): requests queue up, a batching observer coalesces up to
``batch_limit`` of them, a worker runs the model, results scatter back to
futures. On TPU one jitted apply replaces the per-device replica pool — the
chip is time-shared by the XLA queue — so the host-side micro-batcher is the
part worth keeping.

Modes (reference InferenceMode): SEQUENTIAL (run immediately, no batching),
BATCHED (coalesce); INPLACE maps to SEQUENTIAL.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import Any, List, Optional

import numpy as np

from ..ndarray.ndarray import NDArray


class ParallelInference:
    class Builder:
        def __init__(self, model):
            self._model = model
            self._mode = "batched"
            self._batch_limit = 32
            self._queue_limit = 64
            self._max_wait_ms = 5.0

        def inference_mode(self, mode: str) -> "ParallelInference.Builder":
            self._mode = mode.lower()
            return self

        inferenceMode = inference_mode

        def batch_limit(self, n: int) -> "ParallelInference.Builder":
            self._batch_limit = n
            return self

        batchLimit = batch_limit

        def queue_limit(self, n: int) -> "ParallelInference.Builder":
            self._queue_limit = n
            return self

        def max_wait_ms(self, ms: float) -> "ParallelInference.Builder":
            self._max_wait_ms = ms
            return self

        def build(self) -> "ParallelInference":
            return ParallelInference(self._model, self._mode, self._batch_limit,
                                     self._queue_limit, self._max_wait_ms)

    def __init__(self, model, mode: str = "batched", batch_limit: int = 32,
                 queue_limit: int = 64, max_wait_ms: float = 5.0):
        self.model = model
        self.mode = "sequential" if mode in ("sequential", "inplace") else "batched"
        self.batch_limit = batch_limit
        self.max_wait_s = max_wait_ms / 1000.0
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_limit)
        self._shutdown = False
        self._worker: Optional[threading.Thread] = None
        if self.mode == "batched":
            self._worker = threading.Thread(target=self._drain, daemon=True)
            self._worker.start()

    # ------------------------------------------------------------------
    def output(self, x) -> NDArray:
        """Synchronous single-request API (reference output())."""
        return self.output_async(x).result()

    def output_async(self, x) -> Future:
        arr = np.asarray(x.value if isinstance(x, NDArray) else x)
        fut: Future = Future()
        if self.mode == "sequential" or self._shutdown:
            fut.set_result(self._run(arr))
            return fut
        self._queue.put((arr, fut))
        return fut

    def _run(self, batch: np.ndarray) -> NDArray:
        out = self.model.output(batch)
        return out[0] if isinstance(out, list) else out

    def _drain(self) -> None:
        while not self._shutdown:
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            deadline = self.max_wait_s
            while len(batch) < self.batch_limit:
                try:
                    batch.append(self._queue.get(timeout=deadline))
                except queue.Empty:
                    break
            arrays = [b[0] for b in batch]
            futures = [b[1] for b in batch]
            sizes = [a.shape[0] for a in arrays]
            try:
                merged = np.concatenate(arrays, axis=0)
                result = self._run(merged).to_numpy()
                off = 0
                for size, fut in zip(sizes, futures):
                    fut.set_result(NDArray(result[off:off + size]))
                    off += size
            except Exception as e:  # scatter failure to every waiter
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(e)

    def shutdown(self) -> None:
        self._shutdown = True
        if self._worker is not None:
            self._worker.join(timeout=1.0)
