"""ParallelInference — request batching for serving.

Reference: dl4j-scaleout ``org.deeplearning4j.parallelism.ParallelInference``
(SURVEY.md §2.4, §3.7): requests queue up, a batching observer coalesces up to
``batch_limit`` of them, a worker runs the model, results scatter back to
futures. On TPU one jitted apply replaces the per-device replica pool — the
chip is time-shared by the XLA queue — so the host-side micro-batcher is the
part worth keeping: ``workers`` coalescing threads ("replicas") share one
request queue.

Modes (reference InferenceMode): SEQUENTIAL (run immediately, no batching),
BATCHED (coalesce); INPLACE maps to SEQUENTIAL.

Failure contract (the §5.3 serving story):

- **Per-request timeouts**: :meth:`output` bounds its wait with a
  ``max_wait_ms``-derived deadline (override:
  ``Builder.request_timeout_ms``) and raises a ``TimeoutError`` naming the
  queue depth and live-replica count instead of blocking forever on a
  wedged replica.
- **Failed-replica retirement**: a worker whose model dies fatally
  (:class:`faultinject.DeadReplicaFault` — e.g. a wedged device) fails its
  in-flight batch, retires itself, and leaves the remaining replicas
  serving; when the LAST replica retires, queued and future requests fail
  fast instead of queueing into a void. Ordinary per-batch exceptions
  scatter to that batch's futures and the replica keeps serving (a bad
  request must not kill the worker).
- **Replica resurrection** (on by default): a retired replica is REPLACED
  instead of permanently shrinking the pool — after an exponential
  backoff a health probe (the model re-run on a one-row slice of the last
  successfully-served batch; drillable via the ``inference/probe`` fault
  site) must pass, then a fresh worker thread joins the queue. Pool
  capacity recovers; ``pool_stats()`` / ``/api/health`` report
  live/retired/resurrected counts.
- **Shutdown drains, then fails queued futures**: :meth:`shutdown` stops
  the workers, waits (bounded) for in-flight batches to resolve normally,
  then resolves every still-queued future with an error — no waiter is
  left hanging on a future nobody will fulfil, and no request a replica
  already picked up is failed spuriously.
- **True time-in-queue**: every ``output_async`` future carries its
  queue-entry timestamp (``fut.enqueued_at``), so deadline errors report
  how long the request actually sat, not a figure derived from
  ``max_wait_ms`` at dispatch.

The production SERVING tier — shape-bucketed continuous batching over
AOT-compiled executables, an HTTP endpoint, and the SLO load bench — is
:mod:`parallel.serving`'s :class:`ServingEngine`, a subclass of this pool
(same retirement/resurrection machinery; bucket-aware coalescing).
"""

from __future__ import annotations

import concurrent.futures
import logging
import queue
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from ..common import faultinject, flightrec
from ..common.profiler import OpProfiler
from ..ndarray.ndarray import NDArray

logger = logging.getLogger("deeplearning4j_tpu")

# live pools, for the /api/health census (weak: a dropped pool vanishes)
_POOLS: "weakref.WeakSet" = weakref.WeakSet()


def pool_health() -> Dict[str, int]:
    """Aggregate live/retired/resurrected counts over every live
    ParallelInference pool — the /api/health serving-capacity line."""
    agg = {"pools": 0, "workers": 0, "alive": 0, "retired": 0,
           "resurrected": 0}
    for pool in list(_POOLS):
        stats = pool.pool_stats()
        agg["pools"] += 1
        for k in ("workers", "alive", "retired", "resurrected"):
            agg[k] += stats[k]
    return agg


class _Request:
    """One queued inference request. Carries its queue-entry timestamp so
    deadline errors can report TRUE time-in-queue (not a figure derived
    from ``max_wait_ms`` at dispatch), and a requeue ``attempts`` counter
    so a serving tier can re-enqueue the in-flight batch of a dying
    replica a bounded number of times instead of failing it."""

    __slots__ = ("arr", "fut", "seq", "t_enq", "attempts", "t_real", "slo")

    def __init__(self, arr: np.ndarray, fut: Future, seq: int,
                 t_enq: float, attempts: int = 0,
                 t_real: Optional[int] = None,
                 slo: Optional[str] = None):
        self.arr = arr
        self.fut = fut
        self.seq = seq
        self.t_enq = t_enq          # time.monotonic() at queue entry
        self.attempts = attempts
        self.t_real = t_real        # real sequence length before seq-pad
        self.slo = slo              # SLO class name (admission-controlled)

    @property
    def n(self) -> int:
        return int(self.arr.shape[0])


class ParallelInference:
    class Builder:
        def __init__(self, model):
            self._model = model
            self._mode = "batched"
            self._batch_limit = 32
            self._queue_limit = 64
            self._max_wait_ms = 5.0
            self._workers = 1
            self._request_timeout_ms: Optional[float] = None
            self._resurrect = True
            self._resurrect_backoff_ms = 250.0
            self._max_resurrections = 16

        def inference_mode(self, mode: str) -> "ParallelInference.Builder":
            self._mode = mode.lower()
            return self

        inferenceMode = inference_mode

        def batch_limit(self, n: int) -> "ParallelInference.Builder":
            self._batch_limit = n
            return self

        batchLimit = batch_limit

        def queue_limit(self, n: int) -> "ParallelInference.Builder":
            self._queue_limit = n
            return self

        def max_wait_ms(self, ms: float) -> "ParallelInference.Builder":
            self._max_wait_ms = ms
            return self

        def workers(self, n: int) -> "ParallelInference.Builder":
            """Coalescing worker threads sharing the request queue (the
            replica-pool analog; reference ``workers(int)``)."""
            self._workers = max(1, int(n))
            return self

        def request_timeout_ms(self, ms: float) -> "ParallelInference.Builder":
            """Hard deadline for :meth:`output`. Default: derived from
            ``max_wait_ms`` (see ParallelInference.__init__)."""
            self._request_timeout_ms = ms
            return self

        def resurrect_dead_replicas(self, enabled: bool = True,
                                    backoff_ms: Optional[float] = None,
                                    max_resurrections: Optional[int] = None
                                    ) -> "ParallelInference.Builder":
            """Replica resurrection policy (default ON): a retired
            replica is replaced after health-probe + backoff instead of
            permanently shrinking the pool."""
            self._resurrect = enabled
            if backoff_ms is not None:
                self._resurrect_backoff_ms = backoff_ms
            if max_resurrections is not None:
                self._max_resurrections = max_resurrections
            return self

        def build(self) -> "ParallelInference":
            return ParallelInference(self._model, self._mode, self._batch_limit,
                                     self._queue_limit, self._max_wait_ms,
                                     workers=self._workers,
                                     request_timeout_ms=self._request_timeout_ms,
                                     resurrect=self._resurrect,
                                     resurrect_backoff_ms=self._resurrect_backoff_ms,
                                     max_resurrections=self._max_resurrections)

    def __init__(self, model, mode: str = "batched", batch_limit: int = 32,
                 queue_limit: int = 64, max_wait_ms: float = 5.0,
                 workers: int = 1,
                 request_timeout_ms: Optional[float] = None,
                 resurrect: bool = True,
                 resurrect_backoff_ms: float = 250.0,
                 max_resurrections: int = 16):
        self.model = model
        self.mode = "sequential" if mode in ("sequential", "inplace") else "batched"
        self.batch_limit = batch_limit
        self.max_wait_s = max_wait_ms / 1000.0
        # a healthy replica turns a batch around in ~max_wait_s; 1000x that
        # (floor 10s) only ever fires on a genuinely wedged pipeline
        self.request_timeout_s = (request_timeout_ms / 1000.0
                                  if request_timeout_ms is not None
                                  else max(1000.0 * self.max_wait_s, 10.0))
        self.resurrect = resurrect
        self.resurrect_backoff_s = resurrect_backoff_ms / 1000.0
        self.max_resurrections = max_resurrections
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_limit)
        self._shutdown = False
        self._lock = threading.Lock()
        self._req_seq = 0
        self._workers: List[threading.Thread] = []
        self._resurrectors: List[threading.Thread] = []
        self._alive = 0
        self._busy = 0               # workers mid-batch (shutdown drains)
        self._pool_size = 0          # configured capacity (drain threads)
        self._target_alive = 0       # scale_to target (autoscaler-driven)
        self._scale_down_pending = 0  # workers asked to exit at a boundary
        self._scaled_down_total = 0
        self._retired_total = 0
        self._resurrected_total = 0
        self._resurrections_started = 0
        self._probe_seq = 0
        self._probe_input: Optional[np.ndarray] = None
        if self.mode == "batched":
            self._alive = max(1, int(workers))
            self._pool_size = self._alive
            self._target_alive = self._alive
            for i in range(self._alive):
                t = threading.Thread(target=self._drain, args=(i,),
                                     daemon=True,
                                     name=f"dl4j-inference-{i}")
                self._workers.append(t)
                t.start()
        _POOLS.add(self)

    # ------------------------------------------------------------------
    def alive_replicas(self) -> int:
        with self._lock:
            return self._alive

    def pool_stats(self) -> Dict[str, int]:
        """Live/retired/resurrected census (the /api/health line)."""
        with self._lock:
            return {"workers": self._pool_size, "alive": self._alive,
                    "target": self._target_alive,
                    "scaled_down": self._scaled_down_total,
                    "retired": self._retired_total,
                    "resurrected": self._resurrected_total}

    # --- online scaling -------------------------------------------------
    def scale_to(self, n: int, reason: str = "manual") -> int:
        """Resize the worker pool ONLINE, no process restart: scale UP
        spawns fresh drain threads against the shared queue (on the
        serving tier they reuse the already-compiled bucket executables,
        so a grow never traces); scale DOWN marks the excess and each
        surplus worker exits at its next batch boundary — a worker never
        abandons a batch it already picked up. The closed-loop autoscaler
        (:mod:`parallel.autoscale`) drives this from queue/latency
        signals; it is also the manual capacity knob. Returns the new
        target."""
        if self.mode != "batched":
            raise RuntimeError("scale_to needs a batched worker pool "
                               "(sequential mode has no workers)")
        n = max(1, int(n))
        started: List[threading.Thread] = []
        with self._lock:
            if self._shutdown:
                return self._alive
            self._target_alive = n
            pending = self._scale_down_pending
            effective = self._alive - pending
            if n > effective:
                # cancel queued scale-downs before spawning new threads
                cancel = min(pending, n - effective)
                self._scale_down_pending -= cancel
                effective += cancel
                for _ in range(n - effective):
                    worker_id = len(self._workers)
                    t = threading.Thread(target=self._drain,
                                         args=(worker_id,), daemon=True,
                                         name=f"dl4j-inference-{worker_id}")
                    self._workers.append(t)
                    self._alive += 1
                    started.append(t)
            elif n < effective:
                self._scale_down_pending += effective - n
            self._pool_size = n
        for t in started:
            t.start()
        prof = OpProfiler.get()
        if started:
            prof.count("inference/workers_started", len(started))
        logger.info("inference pool scaled to %d workers (%s)", n, reason)
        return n

    def _take_scale_down(self, worker_id: int) -> bool:
        """Boundary check a drain worker runs between batches: True means
        THIS worker absorbs one pending scale-down and must exit. The
        lock-free fast read keeps the no-scaling hot path at one attribute
        check; the decision itself is taken under the pool lock."""
        if not self._scale_down_pending:
            return False
        with self._lock:
            if self._scale_down_pending <= 0:
                return False
            if self._alive <= self._target_alive:
                # a retirement already shrank the pool to (or below) the
                # target since this scale-down was queued — absorbing it
                # too would underflow the fleet (down to zero workers)
                self._scale_down_pending = 0
                return False
            self._scale_down_pending -= 1
            self._alive -= 1
            self._scaled_down_total += 1
            alive = self._alive
        self._on_scaled_out(worker_id)
        OpProfiler.get().count("inference/workers_stopped")
        logger.info("inference replica %d scaled out; %d workers remain",
                    worker_id, alive)
        return True

    def _on_scaled_out(self, worker_id: int) -> None:
        """Subclass hook: bookkeeping when a worker exits via scale-down
        (the serving tier frees the worker's pinned-device slot here)."""

    def output(self, x, **kwargs) -> NDArray:
        """Synchronous single-request API (reference output()), bounded by
        the per-request deadline. A timeout reports the request's TRUE
        time-in-queue (from the queue-entry timestamp the future carries),
        not a figure derived from ``max_wait_ms`` at dispatch. Keyword
        arguments pass through to ``output_async`` (the serving tier's
        ``slo_class``)."""
        fut = self.output_async(x, **kwargs)
        try:
            return fut.result(timeout=self.request_timeout_s)
        except concurrent.futures.TimeoutError:
            t_enq = getattr(fut, "enqueued_at", None)
            waited = (f"{time.monotonic() - t_enq:.1f}s in queue"
                      if t_enq is not None
                      else f"{self.request_timeout_s:.1f}s")
            raise TimeoutError(
                f"inference request timed out after {waited} (deadline "
                f"{self.request_timeout_s:.1f}s, queue depth "
                f"{self._queue.qsize()}, {self.alive_replicas()}/"
                f"{len(self._workers) or 1} replicas alive); a wedged "
                f"replica or an overloaded queue — raise "
                f"request_timeout_ms or add workers") from None

    def output_async(self, x) -> Future:
        arr = np.asarray(x.value if isinstance(x, NDArray) else x)
        fut: Future = Future()
        if self._shutdown:
            fut.set_exception(RuntimeError(
                "ParallelInference is shut down; no replicas will serve "
                "this request"))
            return fut
        if self.mode == "sequential":
            try:
                fut.set_result(self._run(arr))
            except Exception as e:
                fut.set_exception(e)
            return fut
        if self.alive_replicas() == 0:
            fut.set_exception(RuntimeError(
                "all inference replicas have been retired (fatal replica "
                "failures); a resurrection may be pending — retry, or "
                "restart the ParallelInference"))
            return fut
        with self._lock:
            seq = self._req_seq
            self._req_seq += 1
        self._enqueue(_Request(arr, fut, seq, time.monotonic()))
        return fut

    def _enqueue(self, req: _Request) -> None:
        """Queue one request. The future carries the queue-entry timestamp
        (``fut.enqueued_at``) so deadline errors report true time-in-queue."""
        req.fut.enqueued_at = req.t_enq
        try:
            # the enqueue itself is bounded by the request deadline too:
            # a full queue behind a wedged replica must not turn the
            # "timeout instead of hang" contract into an untimed block
            self._queue.put(req, timeout=self.request_timeout_s)
        except queue.Full:
            req.fut.set_exception(TimeoutError(
                f"inference queue stayed full (depth "
                f"{self._queue.qsize()}) for {self.request_timeout_s:.1f}s "
                f"({self.alive_replicas()}/{len(self._workers) or 1} "
                f"replicas alive)"))
            return
        # re-check AFTER enqueueing: the last replica may have retired
        # between the alive check above and the put, in which case nobody
        # will ever drain this request — fail it now rather than hang
        if self.alive_replicas() == 0:
            self._fail_queued(RuntimeError(
                "all inference replicas have been retired (fatal replica "
                "failures); a resurrection may be pending — retry, or "
                "restart the ParallelInference"))

    def _run(self, batch: np.ndarray) -> NDArray:
        out = self.model.output(batch)
        return out[0] if isinstance(out, list) else out

    def _retire(self, worker_id: int, exc: BaseException, futures) -> None:
        """Fatal-failure bookkeeping shared by every way a worker dies:
        fail the in-flight batch, drop the replica from the pool, and —
        when it was the last one — fail everything still queued. With
        resurrection enabled a replacement is scheduled (health-probe +
        exponential backoff) so the pool's capacity recovers."""
        for fut in futures:
            if not fut.done():
                fut.set_exception(exc if isinstance(exc, Exception)
                                  else RuntimeError(f"inference replica "
                                                    f"died: {exc}"))
        OpProfiler.get().count("inference/replica_retired")
        with self._lock:
            self._alive -= 1
            self._retired_total += 1
            last = self._alive == 0
        logger.warning("inference replica %d retired (%s); %d replicas "
                       "remain", worker_id, exc, self.alive_replicas())
        if last:
            # bounded-latency contract first: nobody waits out a backoff
            # on a request already queued; the resurrected replica serves
            # NEW requests
            self._fail_queued(RuntimeError(
                "all inference replicas retired"))
        self._schedule_resurrection()

    # --- resurrection --------------------------------------------------
    def _schedule_resurrection(self) -> None:
        if not self.resurrect or self._shutdown or self.mode != "batched":
            return
        with self._lock:
            if self._resurrections_started >= self.max_resurrections:
                logger.warning("inference pool resurrection budget (%d) "
                               "exhausted; pool stays at %d/%d",
                               self.max_resurrections, self._alive,
                               self._pool_size)
                return
            self._resurrections_started += 1
        t = threading.Thread(target=self._resurrector, daemon=True,
                             name="dl4j-inference-resurrector")
        self._resurrectors.append(t)
        t.start()

    def _probe(self) -> None:
        """Health probe before a replacement worker joins: re-run the
        model on a one-row slice of the last successfully served batch
        (nothing served yet → model assumed healthy). The
        ``inference/probe`` fault site makes probe failure drillable."""
        faultinject.fault_point("inference/probe", self._next_probe_seq())
        probe = self._probe_input
        if probe is not None:
            self._run(probe)

    def _next_probe_seq(self) -> int:
        with self._lock:
            seq = self._probe_seq
            self._probe_seq += 1
        return seq

    _PROBE_ATTEMPT_LIMIT = 10

    def _resurrector(self) -> None:
        backoff = self.resurrect_backoff_s
        probes = 0
        while not self._shutdown:
            # interruptible sleep so shutdown() is not held up
            deadline = time.monotonic() + backoff
            while not self._shutdown and time.monotonic() < deadline:
                time.sleep(min(0.05, backoff))
            if self._shutdown:
                return
            try:
                self._probe()
            except Exception as e:
                OpProfiler.get().count("inference/probe_failures")
                probes += 1
                if probes >= self._PROBE_ATTEMPT_LIMIT:
                    # a probe that NEVER passes means the model itself is
                    # broken — stop burning a daemon thread on it
                    OpProfiler.get().count("inference/resurrection_abandoned")
                    logger.warning(
                        "inference resurrection abandoned after %d failed "
                        "health probes (last: %s); pool stays at %d/%d",
                        probes, e, self.alive_replicas(), self._pool_size)
                    return
                logger.warning("inference resurrection probe failed (%s); "
                               "backing off %.2fs", e, backoff * 2)
                backoff = min(backoff * 2, 30.0)
                continue
            with self._lock:
                if self._shutdown:
                    return
                superseded = (self._alive - self._scale_down_pending
                              >= self._target_alive)
            if superseded:
                # the pool has since been scaled down past this
                # resurrection — a replacement would only be asked to
                # exit again at its first boundary
                OpProfiler.get().count("inference/resurrection_superseded")
                return
            with self._lock:
                if self._shutdown:
                    return
                # id + append under ONE lock: two resurrectors racing
                # (two near-simultaneous retirements) must not mint the
                # same replica id
                worker_id = len(self._workers)
                t = threading.Thread(target=self._drain, args=(worker_id,),
                                     daemon=True,
                                     name=f"dl4j-inference-{worker_id}")
                self._workers.append(t)
                self._alive += 1
                self._resurrected_total += 1
            t.start()
            OpProfiler.get().count("inference/replica_resurrected")
            flightrec.event("inference/resurrected", worker=worker_id,
                            alive=self.alive_replicas())
            logger.warning("inference replica %d resurrected; %d/%d "
                           "replicas alive", worker_id,
                           self.alive_replicas(), self._pool_size)
            return

    def _drain(self, worker_id: int) -> None:
        prof = OpProfiler.get()
        while not self._shutdown:
            if self._take_scale_down(worker_id):
                return            # scaled out at a batch boundary
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            # ONE coalescing window for the whole batch (an absolute
            # deadline): a per-get timeout would reset with every
            # trickling request and hold the first waiter up to
            # batch_limit x max_wait_s
            deadline = time.monotonic() + self.max_wait_s
            while len(batch) < self.batch_limit:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            with self._lock:
                self._busy += 1
            try:
                self._serve_batch(worker_id, batch, prof)
            except faultinject.DeadReplicaFault:
                return          # replica retired inside _serve_batch
            finally:
                with self._lock:
                    self._busy -= 1
        with self._lock:
            self._alive -= 1

    def _serve_batch(self, worker_id: int, batch: List[_Request],
                     prof) -> None:
        """Run one coalesced batch and scatter results. Raises
        DeadReplicaFault after retiring the worker so ``_drain`` exits."""
        futures = [r.fut for r in batch]
        try:
            for r in batch:
                faultinject.fault_point("inference/worker", r.seq)
            merged = np.concatenate([r.arr for r in batch], axis=0)
            result = self._run(merged).to_numpy()
            # one-row sample of a known-good input: what the
            # resurrection health probe replays (copy — a view would
            # pin the whole merged batch in memory between requests)
            # graftlint: disable=lock-discipline -- last-write-wins slot:
            # one atomic reference store of a fresh owning copy; probes
            # read whichever sample is newest
            self._probe_input = merged[:1].copy()
            off = 0
            for r in batch:
                r.fut.set_result(NDArray(result[off:off + r.n]))
                off += r.n
        except faultinject.DeadReplicaFault as e:
            # fatal: this replica is gone — fail its batch, retire
            self._retire(worker_id, e, futures)
            raise
        except Exception as e:  # scatter failure to every waiter
            prof.count("inference/batch_errors")
            for fut in futures:
                if not fut.done():
                    fut.set_exception(e)
        except BaseException as e:
            # a BaseException (e.g. an injected SimulatedCrash) must
            # not skip the bookkeeping: waiters would hang and the
            # pool would over-report live replicas
            self._retire(worker_id, e, futures)
            raise

    def _fail_queued(self, exc: Exception) -> int:
        n = 0
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return n
            if not req.fut.done():
                req.fut.set_exception(exc)
                n += 1

    def shutdown(self, drain_timeout_s: float = 2.0) -> None:
        """Stop the workers, DRAIN in-flight batches, then FAIL anything
        still queued. The order is the contract: a request a replica has
        already picked up gets up to ``drain_timeout_s`` to finish and
        resolve normally (its waiter sees a result, not a spurious
        shutdown error), and only then does every still-QUEUED future get
        an immediate error instead of hanging on a future no worker will
        ever fulfil. A worker wedged past the drain window is abandoned
        (daemon thread); its batch resolves whenever it does."""
        # graftlint: disable=lock-discipline -- stop flag: one False->True
        # transition; workers poll it racily by design (a lock would only
        # delay the observation, not change it)
        self._shutdown = True
        deadline = time.monotonic() + max(0.0, drain_timeout_s)
        for t in self._workers + self._resurrectors:
            t.join(timeout=max(0.05, deadline - time.monotonic()))
        with self._lock:
            still_busy = self._busy
            self._alive = 0      # pool_health must not count the dead
        if still_busy:
            logger.warning("ParallelInference.shutdown: %d in-flight "
                           "batch(es) did not drain within %.1fs",
                           still_busy, drain_timeout_s)
        _POOLS.discard(self)
        n = self._fail_queued(RuntimeError(
            "ParallelInference shut down with this request still queued"))
        if n:
            logger.warning("ParallelInference.shutdown failed %d queued "
                           "request(s)", n)
