"""Gradient accumulator SPI.

Reference: dl4j ``org.deeplearning4j.optimize.solvers.accumulation.{
GradientsAccumulator, EncodedGradientsAccumulator}`` + threshold encoding
(``EncodingHandler``, ``ThresholdCompression``) (SURVEY.md §2.3, §2.4).

Three exchange strategies, all compiled INTO the SPMD train step:

- ``DenseAllReduceAccumulator`` (default): mean-psum over the ``data`` mesh
  axis — the right call over ICI, where dense all-reduce beats any
  encode/decode round-trip (SURVEY §5.8).
- ``ReduceScatterAccumulator``: ZeRO-1 weight-update sharding
  (arXiv:2004.13336, "Automatic Cross-Replica Sharding of Weight Update in
  Data-Parallel Training"): gradients are reduce-scattered so each replica
  owns an even 1/N flat slice, the updater runs on that slice only (its
  state lives sharded — ~1/N of the dense footprint per replica, and the
  N−1 redundant updater applies disappear), and the updated params are
  all-gathered back. ``ParallelWrapper`` switches its step to the sharded
  path when it sees ``zero1 = True``.
- ``EncodedGradientsAccumulator``: the reference's threshold-encoded
  exchange, now REAL: per-replica residual carry (error feedback), in-step
  {-t, 0, +t} threshold encoding with the threshold driven by a
  :class:`ThresholdAlgorithm`, and the exchanged tensor being the encoded
  update. Intended for DCN / host-boundary links where sparse messages pay
  off; over ICI keep the dense default. Density and (estimated) encoded
  message bytes feed the profiler's ``collective_stats()`` ledger.

Deliberate divergence from the reference: dl4j encodes in UPDATE space
(each worker runs its own local updater, then shares encoded updates).
Here the updater is a single global pytree transform fused into the step,
so encoding happens in GRADIENT space with the same residual-feedback
semantics — the exchanged message is the thresholded gradient, and the
global updater consumes the decoded mean.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


class GradientsAccumulator:
    """SPI: transforms per-shard gradients into the globally-reduced update
    inside the compiled step (traced; must be pure).

    Stateless accumulators implement ``reduce_gradients``. Stateful ones
    (``stateful = True``) additionally implement ``init_state`` /
    ``state_specs`` / ``exchange`` — the state pytree is threaded through
    the compiled step (and ``lax.scan`` chunks) by ``ParallelWrapper`` and
    rides checkpoints for exact resume."""

    axis_name: str = "data"
    stateful: bool = False
    zero1: bool = False

    def reduce_gradients(self, grads):
        raise NotImplementedError

    # --- stateful SPI (no-ops for stateless accumulators) ---------------
    def init_state(self, params, n_shards: int = 1) -> Dict[str, Any]:
        """Host-side state template (numpy/jnp arrays, UNPLACED — the
        wrapper places it with ``state_specs``)."""
        return {}

    def state_specs(self, params):
        """PartitionSpec tree matching ``init_state``'s structure."""
        return {}

    def exchange(self, grads, state, axis_name: str):
        """(grads, state) -> (reduced_grads, new_state, density) — traced
        inside the step. ``density`` is the global fraction of elements
        actually encoded this step (1.0 for dense exchanges)."""
        return (self.reduce_gradients(grads), state,
                jnp.asarray(1.0, jnp.float32))

    def resize_state(self, state, old_n: int, new_n: int,
                     lost_replicas=()):
        """Carry accumulator state through an ONLINE elastic resize of
        the data axis (host-side, dispatch boundary). Stateless
        accumulators pass through; stateful ones override to remap their
        per-replica leaves (see ``EncodedGradientsAccumulator``)."""
        return state


class DenseAllReduceAccumulator(GradientsAccumulator):
    """Mean all-reduce over the data axis (ICI collective)."""

    def __init__(self, axis_name: str = "data"):
        self.axis_name = axis_name

    def reduce_gradients(self, grads):
        return jax.tree.map(lambda g: jax.lax.pmean(g, self.axis_name), grads)


class ReduceScatterAccumulator(DenseAllReduceAccumulator):
    """ZeRO-1 weight-update sharding marker (see module doc).

    The actual reduce-scatter / sharded-apply / all-gather sequence lives
    in ``ParallelWrapper._local_core`` (it needs the flat param plan and
    the updater); this class selects that path and still answers the
    legacy ``reduce_gradients`` SPI with the dense mean for callers that
    use the accumulator outside the wrapper."""

    zero1 = True


@dataclass
class ThresholdAlgorithm:
    """Reference ``encoding.threshold.ThresholdAlgorithm``: owns the
    encoding threshold and adapts it from the observed encode density
    (fraction of elements ≥ threshold). ``update`` is traced into the
    compiled step — pure jnp math on (threshold, density) scalars. The
    base class is fixed: the threshold never moves."""

    initial_threshold: float = 1e-3

    def initial(self) -> float:
        return float(self.initial_threshold)

    def update(self, threshold, density):
        return threshold


class FixedThresholdAlgorithm(ThresholdAlgorithm):
    pass


@dataclass
class AdaptiveThresholdAlgorithm(ThresholdAlgorithm):
    """Reference AdaptiveThresholdAlgorithm semantics: keep the encode
    density inside a target band by moving the threshold multiplicatively
    — density above the band means too much traffic (raise the threshold),
    below means the updates are starving (lower it), inside means leave it
    alone. ``decay`` < 1 is the per-step multiplicative step size; the
    threshold is clipped to [min_threshold, max_threshold] so one
    pathological step can never drive it to 0 or ∞."""

    initial_threshold: float = 1e-3
    min_density: float = 1e-4
    max_density: float = 1e-2
    decay: float = 0.95
    min_threshold: float = 1e-6
    max_threshold: float = 1.0

    def update(self, threshold, density):
        up = density > self.max_density
        down = density < self.min_density
        new = jnp.where(up, threshold / self.decay,
                        jnp.where(down, threshold * self.decay, threshold))
        return jnp.clip(new, self.min_threshold, self.max_threshold)


@dataclass
class TargetSparsityThresholdAlgorithm(ThresholdAlgorithm):
    """Reference TargetSparsityThresholdAlgorithm semantics: proportional
    multiplicative control driving the encode density toward
    ``sparsity_target`` — threshold ← threshold · (density/target)^gain,
    so density above target raises the threshold and below lowers it,
    with the step size shrinking as density approaches the target."""

    initial_threshold: float = 1e-3
    sparsity_target: float = 1e-3
    gain: float = 0.25
    min_threshold: float = 1e-6
    max_threshold: float = 1.0

    def update(self, threshold, density):
        eps = jnp.asarray(1e-12, jnp.float32)
        ratio = (density + eps) / (self.sparsity_target + eps)
        new = threshold * jnp.power(ratio, self.gain)
        return jnp.clip(new, self.min_threshold, self.max_threshold)


class EncodedGradientsAccumulator(DenseAllReduceAccumulator):
    """The reference EncodedGradientsAccumulator, implemented for real
    (module doc): per-replica residual carry + in-step threshold encoding.

    Per step, per replica:  u = grad + residual;  elements with |u| ≥ t
    are encoded as sign(u)·t, the rest as 0;  residual ← u − encoded
    (error feedback — unsent mass is carried, and sent elements carry
    their overshoot);  the encoded tensors are mean-reduced across
    replicas and handed to the updater.  The threshold algorithm then
    adapts t from the GLOBAL density (psum'd), so every replica holds the
    same threshold and checkpoints reshard trivially.

    This is the DCN / host-boundary exchange path: the {-t,0,+t} message
    is what would cross the slow link (sparse int32 indices, bitmap
    fallback above 1/16 density — the ledger's byte estimate). Over ICI
    the dense default is strictly faster; the wrapper runs this path with
    a dense psum of the thresholded tensor, which is mathematically the
    decoded exchange. Residuals are PER-REPLICA state: an ONLINE elastic
    resize carries them (survivors keep theirs, lost rows fold into a
    survivor — see :meth:`resize_state` for the numerics), while a
    cross-worker-count checkpoint RESTORE still resets them (warned);
    everything else — threshold, ledger counters — carries over exactly
    in both cases.
    """

    stateful = True

    def __init__(self, parties: int = 1,
                 threshold_algorithm: Optional[ThresholdAlgorithm] = None,
                 residual_post_processor: Any = None,
                 axis_name: str = "data"):
        super().__init__(axis_name)
        self.parties = parties
        self.threshold_algorithm = threshold_algorithm or AdaptiveThresholdAlgorithm()
        self.residual_post_processor = residual_post_processor

    # --- stateful SPI ----------------------------------------------------
    def init_state(self, params, n_shards: int = 1) -> Dict[str, Any]:
        import numpy as np

        # residual leaves carry a leading replica axis: [n, *shape],
        # sharded over the data axis (each replica sees its own slice)
        residual = jax.tree.map(
            lambda p: np.zeros((n_shards,) + tuple(p.shape),
                               np.dtype(p.dtype)), params)
        return {
            "residual": residual,
            "threshold": np.asarray(self.threshold_algorithm.initial(),
                                    np.float32),
            "nnz_sum": np.asarray(0.0, np.float32),
            "elems_sum": np.asarray(0.0, np.float32),
            "steps": np.asarray(0, np.int32),
        }

    def state_specs(self, params):
        from jax.sharding import PartitionSpec as P

        return {
            "residual": jax.tree.map(lambda _: P("data"), params),
            "threshold": P(),
            "nnz_sum": P(),
            "elems_sum": P(),
            "steps": P(),
        }

    def resize_state(self, state, old_n: int, new_n: int,
                     lost_replicas=()):
        """Carry the residual error-feedback state through an online
        elastic resize (host-side numpy, dispatch boundary).

        Shrink: the surviving replicas keep their residuals (compacted to
        the new leading axis) and every LOST replica's residual is FOLDED
        into the first survivor — one elementwise add per lost row, so no
        gradient mass is silently dropped (the pre-elastic behavior reset
        residuals, discarding it). Numerics: the total pending mass
        ``Σᵢ rᵢ`` is preserved exactly (the fold is a plain float add of
        the lost rows onto survivor 0); what changes is its *distribution*
        across replicas, which only affects WHICH elements of survivor
        0's next update cross the encode threshold — the same class of
        per-replica perturbation a reshuffled data order produces, and
        bounded by the threshold like any other residual. Grow: survivors
        keep their rows, joining replicas start with a zero residual
        (exactly a fresh replica's state). Threshold and ledger counters
        are replicated scalars and carry over bit-exactly either way.

        Cross-worker-count CHECKPOINT restores (no resize — a different
        process picked different N) still reset residuals with a warning:
        there the lost rows' owners never existed in the new run, so a
        fold would mis-attribute mass with no continuity argument."""
        import numpy as np

        if not (isinstance(state, dict) and "residual" in state):
            return state
        lost = sorted({int(r) for r in (lost_replicas or ())})
        old_n, new_n = int(old_n), int(new_n)

        def remap(r):
            r = np.asarray(r)
            if r.ndim < 1 or r.shape[0] != old_n:
                return np.zeros((new_n,) + tuple(r.shape[1:]), r.dtype)
            keep = [i for i in range(old_n) if i not in lost]
            # shrink below the survivor count (no explicit loss list, or
            # an n smaller than old_n - len(lost)): fold the tail too
            fold = lost + keep[new_n:]
            keep = keep[:new_n]
            out = np.zeros((new_n,) + tuple(r.shape[1:]), r.dtype)
            if keep:
                out[:len(keep)] = r[keep]
            if fold:
                # row 0 is the first survivor — or, when every old row
                # was lost (all replicas replaced by spares), the first
                # JOINING replica: either way the total pending mass
                # Σᵢ rᵢ is preserved, never silently dropped
                out[0] = out[0] + r[fold].sum(axis=0)
            return out

        st = dict(state)
        st["residual"] = jax.tree.map(remap, state["residual"])
        return st

    def exchange(self, grads, state, axis_name: str):
        thr = state["threshold"]
        res = jax.tree.map(lambda r: r[0], state["residual"])
        u = jax.tree.map(lambda g, r: g + r.astype(g.dtype), grads, res)
        enc = jax.tree.map(
            lambda x: jnp.where(jnp.abs(x) >= thr.astype(x.dtype),
                                jnp.sign(x) * thr.astype(x.dtype),
                                jnp.zeros((), x.dtype)), u)
        new_res = jax.tree.map(lambda x, e: x - e, u, enc)
        if self.residual_post_processor is not None:
            new_res = self.residual_post_processor(new_res)
        reduced = jax.tree.map(lambda e: jax.lax.pmean(e, axis_name), enc)
        nnz_local = sum(jnp.sum(e != 0).astype(jnp.float32)
                        for e in jax.tree.leaves(enc))
        elems_local = jnp.asarray(
            float(sum(int(e.size) for e in jax.tree.leaves(enc))),
            jnp.float32)
        nnz = jax.lax.psum(nnz_local, axis_name)
        elems = jax.lax.psum(elems_local, axis_name)
        density = nnz / jnp.maximum(elems, 1.0)
        new_state = {
            "residual": jax.tree.map(lambda r: r[None], new_res),
            "threshold": jnp.asarray(
                self.threshold_algorithm.update(thr, density), jnp.float32),
            "nnz_sum": state["nnz_sum"] + nnz,
            "elems_sum": state["elems_sum"] + elems,
            "steps": state["steps"] + 1,
        }
        return reduced, new_state, density
