"""Gradient accumulator SPI.

Reference: dl4j ``org.deeplearning4j.optimize.solvers.accumulation.{
GradientsAccumulator, EncodedGradientsAccumulator}`` + threshold encoding
(``EncodingHandler``, ``ThresholdCompression``) (SURVEY.md §2.3, §2.4).

Design pivot (SURVEY.md §5.8): the reference threshold-encodes gradients
because its multi-GPU exchange crosses host RAM over PCIe. On TPU the
exchange is an XLA ``psum`` over ICI compiled INTO the train step — dense
all-reduce is faster than any encode/decode round-trip. The SPI is preserved
so user code ports cleanly:

- ``DenseAllReduceAccumulator`` (default): mean-psum over the ``data`` mesh
  axis.
- ``EncodedGradientsAccumulator``: API-compatible shell; threshold/residual
  machinery reduces to the dense path (documented deliberate divergence —
  kept so ported configs construct, with the threshold params recorded).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp


class GradientsAccumulator:
    """SPI: transforms per-shard gradients into the globally-reduced update
    inside the compiled step (traced; must be pure)."""

    axis_name: str = "data"

    def reduce_gradients(self, grads):
        raise NotImplementedError


class DenseAllReduceAccumulator(GradientsAccumulator):
    """Mean all-reduce over the data axis (ICI collective)."""

    def __init__(self, axis_name: str = "data"):
        self.axis_name = axis_name

    def reduce_gradients(self, grads):
        return jax.tree.map(lambda g: jax.lax.pmean(g, self.axis_name), grads)


@dataclass
class ThresholdAlgorithm:
    """Reference encoding.threshold.* config carrier (recorded, not applied)."""

    initial_threshold: float = 1e-3


class AdaptiveThresholdAlgorithm(ThresholdAlgorithm):
    pass


class FixedThresholdAlgorithm(ThresholdAlgorithm):
    pass


@dataclass
class TargetSparsityThresholdAlgorithm(ThresholdAlgorithm):
    sparsity_target: float = 1e-3


class EncodedGradientsAccumulator(DenseAllReduceAccumulator):
    """API shell of the reference EncodedGradientsAccumulator.

    The reference encodes updates as sparse {-t, 0, +t} indices (bitmap
    fallback >1/16 density) with per-worker residuals, because updates cross
    PCIe + host queues. Over ICI the dense psum is strictly faster, so this
    class reduces densely; the threshold config is retained for config-file
    compatibility and introspection. See SURVEY.md §2.4 'Gradient
    compression'.
    """

    def __init__(self, parties: int = 1,
                 threshold_algorithm: Optional[ThresholdAlgorithm] = None,
                 residual_post_processor: Any = None,
                 axis_name: str = "data"):
        super().__init__(axis_name)
        self.parties = parties
        self.threshold_algorithm = threshold_algorithm or AdaptiveThresholdAlgorithm()
        self.residual_post_processor = residual_post_processor
