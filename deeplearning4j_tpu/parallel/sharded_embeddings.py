"""Sharded embedding tables: the parameter-server row, TPU-style.

Reference: ``nd4j-parameter-server-parent`` ``VoidParameterServer`` v1
(SURVEY §2.4 "Parameter-server sharded embeddings") — Word2Vec syn0/syn1
ROWS sharded across "Shard" nodes, workers sending
``SkipGramRequestMessage``s, ``SkipGramTrainer`` applying updates
shard-side. The survey's prescribed TPU translation is exactly this
module: the table lives row-sharded over a mesh axis, lookups and
scatter-updates run inside ``shard_map`` with one ``psum`` per lookup —
the collective IS the parameter-server round-trip, compiled onto ICI
instead of Aeron UDP.

Mechanics per device (table shard [V/N, D]):
- ``lookup(ids)``: global ids → local offsets; out-of-shard rows gather a
  clipped row masked to zero; ``psum`` over the axis assembles the full
  [B, D] batch on every device.
- ``apply_gradients(ids, grads)``: every device scatter-adds only the
  rows it owns (duplicate ids sum, as the reference's serialized per-pair
  updates do). No host round-trip, no gradient for foreign rows.

Tables whose row count does not divide the axis size are zero-padded; the
padding rows are unreachable by construction (ids < vocab_size).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ShardedEmbedding:
    def __init__(self, vocab_size: int, dim: int, mesh: Mesh,
                 axis: str = "model", seed: int = 0,
                 scale: Optional[float] = None):
        self.vocab_size = vocab_size
        self.dim = dim
        self.mesh = mesh
        self.axis = axis
        n_shards = mesh.shape[axis]
        self._padded = -(-vocab_size // n_shards) * n_shards
        rng = np.random.default_rng(seed)
        scale = scale if scale is not None else 1.0 / dim
        host = (rng.random((self._padded, dim)) - 0.5).astype(np.float32) \
            * (2 * scale)
        host[vocab_size:] = 0.0
        self._sharding = NamedSharding(mesh, P(axis, None))
        self.table = jax.device_put(host, self._sharding)
        self._build()

    def _build(self) -> None:
        from jax.experimental.shard_map import shard_map

        from ..ops.embeddings import (sharded_local_offsets,
                                      sharded_rows_add, sharded_rows_lookup)

        axis = self.axis

        def local_lookup(table_l, ids):
            rows, _ = sharded_rows_lookup(table_l, ids, axis)
            return rows

        def local_update(table_l, ids, grads):
            aux = sharded_local_offsets(table_l, ids, axis)
            return sharded_rows_add(table_l, aux, grads)

        repl = P()
        from ..common import xprof

        self._lookup = xprof.register_jit(
            "embeddings/lookup",
            jax.jit(shard_map(
                local_lookup, mesh=self.mesh,
                in_specs=(P(axis, None), repl), out_specs=repl)))
        self._update = xprof.register_jit(
            "embeddings/update",
            jax.jit(shard_map(
                local_update, mesh=self.mesh,
                in_specs=(P(axis, None), repl, repl),
                out_specs=P(axis, None)), donate_argnums=(0,)),
            donate=(0,))

    # -- API ---------------------------------------------------------------
    def lookup(self, ids) -> jnp.ndarray:
        """[B] int32 global ids → [B, D] rows (replicated)."""
        return self._lookup(self.table, jnp.asarray(ids, jnp.int32))

    def apply_gradients(self, ids, grads) -> None:
        """Scatter-add ``grads`` [B, D] into rows ``ids`` (duplicates
        sum); only the owning shard of each row is touched."""
        self.table = self._update(self.table,
                                  jnp.asarray(ids, jnp.int32),
                                  jnp.asarray(grads, jnp.float32))

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.table)[:self.vocab_size]

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[self.axis]
