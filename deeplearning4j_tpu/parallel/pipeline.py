"""Pipeline parallelism: microbatched stage execution over a mesh axis.

Reference status: the reference has NO pipeline parallelism (SURVEY §2.4
marks the row absent; "optional later via shard_map stages"). On TPU it is
a natural mesh dimension, so the rebuild provides the canonical GPipe-style
construction natively (same spirit as the ring-attention and tensor-parallel
additions):

- S homogeneous stages live one-per-device along a mesh ``stage`` axis
  (stage parameters stacked on a leading [S, ...] axis and sharded over it);
- the global batch splits into M microbatches; a ``lax.scan`` runs
  M + S - 1 ticks in which every device applies its stage to the activation
  it holds and passes the result to the next stage with neighbor-only
  ``ppermute`` (rides ICI);
- stage 0 injects microbatch t at tick t; the last stage's outputs are
  collected tick-aligned and reassembled, then ``psum``-broadcast.

The whole pipeline is one jitted module and is DIFFERENTIABLE (scan +
ppermute both have transpose rules), so ``jax.grad`` through
``pipeline_apply`` yields per-stage parameter gradients — enough to train.
Bubble fraction is the textbook (S-1)/(M+S-1); pick M >> S.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(params_list):
    """[per-stage pytree, ...] → one pytree with leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_apply(stage_fn: Callable, stacked_params, x: jnp.ndarray,
                   mesh: Mesh, n_micro: int, axis: str = "stage"):
    """Run ``stage_fn(params, x) -> y`` (same shape in/out) as an S-stage
    pipeline over ``axis``. x: [B, ...] with B divisible by ``n_micro``.
    Returns [B, ...] replicated."""
    from jax.experimental.shard_map import shard_map

    S = mesh.shape[axis]
    B = x.shape[0]
    assert B % n_micro == 0, "batch must divide into microbatches"
    mb = B // n_micro

    def local(params_l, x_full):
        me = lax.axis_index(axis)
        p = jax.tree.map(lambda a: a[0], params_l)     # my stage's slice
        micro = x_full.reshape((n_micro, mb) + x_full.shape[1:])
        T = n_micro + S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            act = carry
            # stage 0 injects microbatch t (clipped; late ticks are
            # pipeline-drain bubbles masked out at collection)
            inj = micro[jnp.clip(t, 0, n_micro - 1)]
            inp = jnp.where(me == 0, inj, act)
            out = stage_fn(p, inp)
            nxt = lax.ppermute(out, axis, perm)
            return nxt, out

        act0 = lax.pvary(jnp.zeros((mb,) + x_full.shape[1:], x_full.dtype),
                         axis)
        _, outs = lax.scan(tick, act0, jnp.arange(T))   # [T, mb, ...]
        # microbatch m exits the LAST stage at tick m + S - 1
        final = lax.dynamic_slice_in_dim(outs, S - 1, n_micro, axis=0)
        final = final * (me == S - 1).astype(final.dtype)
        final = lax.psum(final, axis)                   # replicate
        return final.reshape((B,) + x_full.shape[1:])

    # P(axis) is a prefix spec: leading (stage) dim sharded, the rest
    # replicated, for every leaf of the params pytree
    fn = shard_map(local, mesh=mesh, in_specs=(P(axis), P()),
                   out_specs=P())
    return fn(stacked_params, x)


class PipelineParallel:
    """Convenience wrapper: holds stacked stage params sharded over the
    mesh axis and exposes jitted forward / train_step."""

    def __init__(self, stage_fn: Callable, params_list, mesh: Mesh,
                 n_micro: int, axis: str = "stage"):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.axis = axis
        self.n_micro = n_micro
        stacked = stack_stage_params(params_list)
        self.params = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(
                mesh, P(*(axis,) + (None,) * (a.ndim - 1)))), stacked)

        @jax.jit
        def fwd(params, x):
            return pipeline_apply(self.stage_fn, params, x, self.mesh,
                                  self.n_micro, self.axis)

        self._fwd = fwd

        @jax.jit
        def step(params, x, y, lr):
            def loss_fn(p):
                out = pipeline_apply(self.stage_fn, p, x, self.mesh,
                                     self.n_micro, self.axis)
                return jnp.mean((out - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
            return new, loss

        self._step = step

    def forward(self, x) -> jnp.ndarray:
        return self._fwd(self.params, jnp.asarray(x))

    def train_step(self, x, y, lr: float = 1e-2) -> float:
        self.params, loss = self._step(self.params, jnp.asarray(x),
                                       jnp.asarray(y), jnp.float32(lr))
        return loss
