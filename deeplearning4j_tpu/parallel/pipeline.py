"""Pipeline parallelism: microbatched stage execution over a mesh axis.

Reference status: the reference has NO pipeline parallelism (SURVEY §2.4
marks the row absent; "optional later via shard_map stages"). On TPU it is
a natural mesh dimension, so the rebuild provides the canonical GPipe-style
construction natively (same spirit as the ring-attention and tensor-parallel
additions):

- S stages live one-per-device along a mesh ``stage`` axis — HOMOGENEOUS
  repeated blocks as [S, ...]-stacked params (``pipeline_apply``), or
  HETEROGENEOUS per-stage programs/shapes via flattened-param rows and a
  ``lax.switch`` over padded activation payloads
  (:class:`HeterogeneousPipeline`, round 5);
- the global batch splits into M microbatches; a ``lax.scan`` runs
  M + S - 1 ticks in which every device applies its stage to the activation
  it holds and passes the result to the next stage with neighbor-only
  ``ppermute`` (rides ICI);
- stage 0 injects microbatch t at tick t; the last stage's outputs are
  collected tick-aligned and reassembled, then ``psum``-broadcast.

The whole pipeline is one jitted module and is DIFFERENTIABLE (scan +
ppermute both have transpose rules), so ``jax.grad`` through
``pipeline_apply`` yields per-stage parameter gradients — enough to train.
Bubble fraction is the textbook (S-1)/(M+S-1); pick M >> S.

Production tier (ISSUE 14): :class:`PipelineTrainer` generalizes the
construction to N-stage GPipe AND 1F1B schedules with explicit
forward/backward tick tables (:func:`schedule_meta`), composed with the
data axis on a ``(data × stage)`` mesh, behind the standard fit surface
(listeners, in-graph telemetry aux, checkpoint ``resume_from=`` and the
supervisor's in-memory ``resume_cursor=``). It is SELF-HEALING: a stage
lost mid-run re-cuts the layer partition over the surviving stage
devices (:meth:`PipelineTrainer.remap` — the supervisor's
``remap_and_continue`` policy) and continues from the exact dispatch
boundary, one compile per (stage-count, schedule) ever.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common import faultinject, flightrec, xprof
from ..common.profiler import OpProfiler

logger = logging.getLogger("deeplearning4j_tpu")

# jax < 0.5 has no varying-type system: pvary is the identity there (the
# rep checker it informs does not exist either)
_pvary = getattr(lax, "pvary", lambda x, axis_name: x)


def stack_stage_params(params_list):
    """[per-stage pytree, ...] → one pytree with leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_apply(stage_fn: Callable, stacked_params, x: jnp.ndarray,
                   mesh: Mesh, n_micro: int, axis: str = "stage"):
    """Run ``stage_fn(params, x) -> y`` (same shape in/out) as an S-stage
    pipeline over ``axis``. x: [B, ...] with B divisible by ``n_micro``.
    Returns [B, ...] replicated."""
    from jax.experimental.shard_map import shard_map

    S = mesh.shape[axis]
    B = x.shape[0]
    assert B % n_micro == 0, "batch must divide into microbatches"
    mb = B // n_micro

    def local(params_l, x_full):
        me = lax.axis_index(axis)
        p = jax.tree.map(lambda a: a[0], params_l)     # my stage's slice
        micro = x_full.reshape((n_micro, mb) + x_full.shape[1:])
        T = n_micro + S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            act = carry
            # stage 0 injects microbatch t (clipped; late ticks are
            # pipeline-drain bubbles masked out at collection)
            inj = micro[jnp.clip(t, 0, n_micro - 1)]
            inp = jnp.where(me == 0, inj, act)
            out = stage_fn(p, inp)
            nxt = lax.ppermute(out, axis, perm)
            return nxt, out

        act0 = _pvary(jnp.zeros((mb,) + x_full.shape[1:], x_full.dtype),
                      axis)
        _, outs = lax.scan(tick, act0, jnp.arange(T))   # [T, mb, ...]
        # microbatch m exits the LAST stage at tick m + S - 1
        final = lax.dynamic_slice_in_dim(outs, S - 1, n_micro, axis=0)
        final = final * (me == S - 1).astype(final.dtype)
        final = lax.psum(final, axis)                   # replicate
        return final.reshape((B,) + x_full.shape[1:])

    # P(axis) is a prefix spec: leading (stage) dim sharded, the rest
    # replicated, for every leaf of the params pytree
    fn = shard_map(local, mesh=mesh, in_specs=(P(axis), P()),
                   out_specs=P())
    return fn(stacked_params, x)


# --------------------------------------------------------------------------
# heterogeneous stages (round 5 — VERDICT r4 weak #2)


def _flatten_params(tree):
    """Pytree → (f32 vector, unflatten) — the per-stage param payload for
    the heterogeneous pipeline (stages have DIFFERENT param trees, so they
    ride a common [S, P_max] stacked-vector layout instead of a stacked
    pytree)."""
    leaves, treedef = jax.tree.flatten(tree)
    shapes = [np.shape(l) for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    vec = (jnp.concatenate([jnp.ravel(jnp.asarray(l, jnp.float32))
                            for l in leaves])
           if leaves else jnp.zeros((0,), jnp.float32))

    def unflatten(v):
        out, off = [], 0
        for shp, sz in zip(shapes, sizes):
            out.append(v[off:off + sz].reshape(shp))
            off += sz
        return jax.tree.unflatten(treedef, out)

    return vec, unflatten


class HeterogeneousPipeline:
    """GPipe-style pipeline over stages with DIFFERENT programs, param
    trees, and activation shapes (the homogeneous construction above cannot
    express ResNet/BERT stage cuts — round-4 verdict weak #2).

    SPMD mechanics: every device runs the same jitted program; the
    per-stage computation is a ``lax.switch`` over the stage index, with
    activations packed into a fixed [PAD] f32 payload (PAD = the largest
    inter-stage activation) so every branch — and the neighbor ``ppermute``
    that moves activations down the pipe — has one static shape. Stage
    parameters are flattened to one f32 vector each and stacked [S, P_max],
    sharded over the ``stage`` mesh axis; each device unflattens only ITS
    row inside its switch branch. Differentiable end to end (switch, scan,
    ppermute all transpose), so ``train_step`` trains all stages.

    Parameters are held in FLOAT32 (the flattened payload's dtype).

    Checkpoint story (ISSUE 14 satellite): when built through
    :func:`pipeline_from_mln` the source model rides along (``model`` /
    ``_runs``), and :meth:`snapshot`/:meth:`restore` route the live stage
    params through the PR-3 ``snapshot_training_state`` /
    ``restore_training_state`` machinery — the on-disk layout is the
    model's ordinary per-layer tree, so a pipeline run kill+resumes
    bit-exactly and its checkpoints stay readable by every other path.
    """

    #: the source MultiLayerNetwork (+ its stage layer runs) when built
    #: via pipeline_from_mln — the checkpoint surface; None when the
    #: pipeline was assembled from raw stage_fns
    model = None
    _runs: Optional[List[tuple]] = None

    def __init__(self, stage_fns, params_list, in_shapes, out_shapes,
                 mesh: Mesh, n_micro: int, axis: str = "stage",
                 loss_fn: Callable = None):
        S = len(stage_fns)
        if mesh.shape[axis] != S:
            raise ValueError(f"{S} stages but mesh axis {axis!r} has "
                             f"{mesh.shape[axis]} devices")
        for s in range(S - 1):
            if tuple(out_shapes[s]) != tuple(in_shapes[s + 1]):
                raise ValueError(
                    f"stage {s} outputs {out_shapes[s]} but stage {s + 1} "
                    f"expects {in_shapes[s + 1]}")
        self.mesh, self.axis, self.n_micro = mesh, axis, n_micro
        self.in_shapes = [tuple(s) for s in in_shapes]
        self.out_shapes = [tuple(s) for s in out_shapes]
        self._loss_fn = loss_fn or (lambda out, y: jnp.mean((out - y) ** 2))
        self._stage_fns = list(stage_fns)
        self._place_param_rows(params_list)

    def _place_param_rows(self, params_list) -> None:
        """Flatten+pad per-stage trees into the [S, P_max] stage-sharded
        payload (shared by __init__ and sync_from_model)."""
        vecs, self._unflattens = zip(
            *[_flatten_params(p) for p in params_list])
        p_max = max(max(v.size for v in vecs), 1)
        stacked = jnp.stack([jnp.pad(v, (0, p_max - v.size)) for v in vecs])
        self.params = jax.device_put(
            stacked, NamedSharding(self.mesh, P(self.axis, None)))

    # --- checkpoint routing (state lives on the source model) -----------
    def sync_to_model(self) -> None:
        """Write the live stage rows back onto the source model as OWNING
        per-layer copies (``np.array`` of the device payload — device_get
        can return zero-copy views on the CPU backend, the PR-3 lesson)."""
        if self.model is None or self._runs is None:
            raise ValueError("this pipeline was not built from a model "
                             "(pipeline_from_mln); no checkpoint surface")
        host = np.array(jax.device_get(self.params))
        for s, (lo, hi) in enumerate(self._runs):
            tree = self._unflattens[s](host[s])
            for i in range(lo, hi):
                self.model._params[i] = jax.tree.map(
                    lambda a: jnp.array(a), tree[str(i)])

    def sync_from_model(self) -> None:
        """Re-stack the stage payload from the source model's per-layer
        params (after a checkpoint restore)."""
        if self.model is None or self._runs is None:
            raise ValueError("this pipeline was not built from a model "
                             "(pipeline_from_mln); no checkpoint surface")
        params_list = [{str(i): self.model._params[i]
                        for i in range(lo, hi)} for lo, hi in self._runs]
        self._place_param_rows(params_list)

    def snapshot(self, listeners=None):
        """Host snapshot through the standard checkpoint machinery —
        serialize/commit with ``util.checkpoint`` like any fit path."""
        from ..util.checkpoint import snapshot_training_state

        self.sync_to_model()
        return snapshot_training_state(self.model, listeners)

    def restore(self, path: str, listeners=None):
        """Restore a committed checkpoint into the source model AND the
        live stage payload; returns the pipeline cursor."""
        from ..util.checkpoint import restore_training_state

        cursor = restore_training_state(self.model, path,
                                        listeners=listeners)
        self.sync_from_model()
        return cursor

    def _build(self, mb: int):
        S = len(self._stage_fns)
        axis, n_micro = self.axis, self.n_micro
        in_sz = [mb * int(np.prod(s)) for s in self.in_shapes]
        out_sz = [mb * int(np.prod(s)) for s in self.out_shapes]
        pad = max(in_sz + out_sz)

        def branch(s):
            fn, unflat = self._stage_fns[s], self._unflattens[s]
            ishape, isz, osz = self.in_shapes[s], in_sz[s], out_sz[s]

            def b(pvec, act):
                x = act[:isz].reshape((mb,) + ishape)
                y = fn(unflat(pvec), x)
                return jnp.zeros((pad,), jnp.float32).at[:osz].set(
                    jnp.ravel(y).astype(jnp.float32))

            return b

        branches = [branch(s) for s in range(S)]
        perm = [(i, (i + 1) % S) for i in range(S)]
        o_last = out_sz[-1]
        oshape_last = self.out_shapes[-1]

        def local(pstacked, x_full):
            me = lax.axis_index(axis)
            pvec = pstacked[0]
            B = x_full.shape[0]
            micro = x_full.reshape((n_micro, mb) + x_full.shape[1:])
            T = n_micro + S - 1

            def tick(act, t):
                inj = jnp.zeros((pad,), jnp.float32).at[:in_sz[0]].set(
                    jnp.ravel(micro[jnp.clip(t, 0, n_micro - 1)]).astype(
                        jnp.float32))
                inp = jnp.where(me == 0, inj, act)
                out = lax.switch(me, branches, pvec, inp)
                nxt = lax.ppermute(out, axis, perm)
                return nxt, out

            act0 = _pvary(jnp.zeros((pad,), jnp.float32), axis)
            _, outs = lax.scan(tick, act0, jnp.arange(T))
            final = lax.dynamic_slice_in_dim(outs, S - 1, n_micro, axis=0)
            y = final[:, :o_last].reshape((B,) + oshape_last)
            y = y * (me == S - 1).astype(y.dtype)
            return lax.psum(y, axis)

        from jax.experimental.shard_map import shard_map

        # check_rep=False: the lax.switch over per-stage programs yields
        # branch outputs whose replication types the jax-0.4 checker cannot
        # unify (newer jax resolves this through pvary varying types); the
        # psum at the tail replicates the result regardless
        return shard_map(local, mesh=self.mesh,
                         in_specs=(P(axis, None), P()), out_specs=P(),
                         check_rep=False)

    def _fns(self, B: int):
        cache = getattr(self, "_jit_cache", None)
        if cache is None:
            cache = self._jit_cache = {}
        if B not in cache:
            assert B % self.n_micro == 0, \
                "batch must divide into microbatches"
            mb = B // self.n_micro
            pipe = self._build(mb)
            fwd = xprof.register_jit("pipeline/hetero_fwd", jax.jit(pipe))
            loss_fn = self._loss_fn

            @jax.jit
            def step(params, x, y, lr):
                def lf(p):
                    return loss_fn(pipe(p, x), y)

                loss, grads = jax.value_and_grad(lf)(params)
                return jax.tree.map(lambda p, g: p - lr * g, params,
                                    grads), loss

            step = xprof.register_jit("pipeline/hetero_step", step)
            cache[B] = (fwd, step)
        return cache[B]

    def forward(self, x) -> jnp.ndarray:
        x = jnp.asarray(x)
        return self._fns(x.shape[0])[0](self.params, x)

    def train_step(self, x, y, lr: float = 1e-2) -> float:
        x, y = jnp.asarray(x), jnp.asarray(y)
        self.params, loss = self._fns(x.shape[0])[1](
            self.params, x, y, jnp.float32(lr))
        return loss

    def stage_params(self, s: int):
        """Unflattened param tree of stage ``s`` (for parity checks /
        exporting back into a model). ``np.array``, not ``np.asarray``:
        the caller gets OWNING host copies, never views of the live
        device payload (the PR-3 owning-copy discipline)."""
        return self._unflattens[s](np.array(jax.device_get(self.params))[s])


def pipeline_from_mln(model, mesh: Mesh, n_micro: int, axis: str = "stage",
                      cuts=None, example_input=None):
    """Adapter from a ``MultiLayerNetwork`` to a pipeline.

    Without ``cuts`` (legacy form): the model must be S REPEATED same-shape
    blocks — the [S, ...]-stacked homogeneous construction (VERDICT r3
    item 3c).

    With ``cuts`` (round 5): ``cuts`` lists the first layer index of each
    stage after the first (e.g. ``cuts=[3]`` splits layers 0–2 | 3–end into
    2 stages), mapping ARBITRARY contiguous layer runs — conv front / dense
    head, transformer block splits — onto a :class:`HeterogeneousPipeline`.
    ``example_input`` (one batch-shaped array or shape tuple) is required
    to derive the inter-stage activation shapes. Stages run with
    ``training=False`` layer semantics (no dropout) and stateful layers
    (BatchNorm running stats) are refused, as in the legacy form.
    """
    if cuts is not None:
        return _pipeline_from_mln_het(model, mesh, n_micro, axis, cuts,
                                      example_input)
    return _pipeline_from_mln_homogeneous(model, mesh, n_micro, axis)


def _pipeline_from_mln_het(model, mesh, n_micro, axis, cuts, example_input):
    if example_input is None:
        raise ValueError("cuts=... needs example_input to derive "
                         "inter-stage activation shapes")
    layers = model.conf.layers
    cut_list = sorted(int(c) for c in cuts)
    if (len(set(cut_list)) != len(cut_list)
            or any(c <= 0 or c >= len(layers) for c in cut_list)):
        raise ValueError(
            f"bad cuts {cuts} for {len(layers)} layers: cut indices must "
            f"be unique and in (0, {len(layers)})")
    bounds = [0] + cut_list + [len(layers)]
    runs = list(zip(bounds[:-1], bounds[1:]))
    S = mesh.shape[axis]
    if len(runs) != S:
        raise ValueError(f"cuts give {len(runs)} stages but mesh axis "
                         f"{axis!r} has {S} devices")
    for i in range(len(layers)):
        if model._states[i]:
            raise ValueError(
                f"layer {i} carries state ({list(model._states[i])}) — "
                "stateful layers (BatchNorm) cannot ride this pipeline")

    key = jax.random.PRNGKey(0)

    def make_stage(lo, hi):
        def fn(params, x):
            for i in range(lo, hi):
                pre = model.conf.preprocessors.get(i)
                if pre is not None:
                    x = pre(x)
                x, _ = layers[i].apply(params[str(i)], x, {}, False, key)
            return x

        return fn

    stage_fns = [make_stage(lo, hi) for lo, hi in runs]
    params_list = [{str(i): model._params[i] for i in range(lo, hi)}
                   for lo, hi in runs]

    x = (jnp.zeros(example_input, jnp.float32)
         if isinstance(example_input, (tuple, list))
         else jnp.asarray(example_input))
    in_shapes, out_shapes = [], []
    cur = jax.eval_shape(lambda a: a, x)
    for s, fn in enumerate(stage_fns):
        in_shapes.append(tuple(cur.shape[1:]))
        cur = jax.eval_shape(fn, params_list[s],
                             jax.ShapeDtypeStruct(cur.shape, jnp.float32))
        out_shapes.append(tuple(cur.shape[1:]))
    pp = HeterogeneousPipeline(stage_fns, params_list, in_shapes,
                               out_shapes, mesh, n_micro, axis)
    pp.model = model
    pp._runs = runs
    return pp


def _pipeline_from_mln_homogeneous(model, mesh: Mesh, n_micro: int,
                                   axis: str = "stage") -> "PipelineParallel":
    """S REPEATED same-shape blocks → [S, ...]-stacked pipeline.

    Constraint (documented, inherent to the [S, ...]-stacked construction):
    every layer must be the same class with identical param tree shapes and
    same input/output shape, and be stateless (no BatchNorm running state) —
    e.g. a stack of Dense(n→n) blocks or identical transformer/attention
    blocks. Heterogeneous models (ResNet/BERT stage cuts) go through
    ``cuts=...`` → :class:`HeterogeneousPipeline`.
    """
    layers = model.conf.layers
    S = mesh.shape[axis]
    if len(layers) != S:
        raise ValueError(f"model has {len(layers)} layers but the "
                         f"{axis!r} mesh axis has {S} stages")
    # the shared identical-blocks contract (also PipelineTrainer's):
    # full config equality, stateless, no preprocessors — stage_fn runs
    # every stage with layer 0's program, so any divergence would
    # silently change the math
    _check_identical_blocks(model)
    l0 = layers[0]
    key = jax.random.PRNGKey(0)

    def stage_fn(p, x):
        out, _ = l0.apply(p, x, {}, False, key)
        return out

    pp = PipelineParallel(stage_fn,
                          [model._params[i] for i in range(S)],
                          mesh, n_micro, axis)
    pp.model = model
    return pp


class PipelineParallel:
    """Convenience wrapper: holds stacked stage params sharded over the
    mesh axis and exposes jitted forward / train_step.

    Checkpoint story (ISSUE 14 satellite): when built through
    :func:`pipeline_from_mln` (homogeneous form) the source model rides
    along and :meth:`snapshot`/:meth:`restore` route the stage params
    through ``snapshot_training_state``/``restore_training_state`` —
    on-disk layout is the ordinary per-layer tree, so a pipeline run
    kill+resumes bit-exactly and stays readable by every other path."""

    #: the source MultiLayerNetwork when built via pipeline_from_mln
    model = None

    def __init__(self, stage_fn: Callable, params_list, mesh: Mesh,
                 n_micro: int, axis: str = "stage"):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.axis = axis
        self.n_micro = n_micro
        stacked = stack_stage_params(params_list)
        self.params = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(
                mesh, P(*(axis,) + (None,) * (a.ndim - 1)))), stacked)

        @jax.jit
        def fwd(params, x):
            return pipeline_apply(self.stage_fn, params, x, self.mesh,
                                  self.n_micro, self.axis)

        self._fwd = xprof.register_jit("pipeline/legacy_fwd", fwd)

        @jax.jit
        def step(params, x, y, lr):
            def loss_fn(p):
                out = pipeline_apply(self.stage_fn, p, x, self.mesh,
                                     self.n_micro, self.axis)
                return jnp.mean((out - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
            return new, loss

        self._step = xprof.register_jit("pipeline/legacy_step", step)

    def forward(self, x) -> jnp.ndarray:
        return self._fwd(self.params, jnp.asarray(x))

    def train_step(self, x, y, lr: float = 1e-2) -> float:
        self.params, loss = self._step(self.params, jnp.asarray(x),
                                       jnp.asarray(y), jnp.float32(lr))
        return loss

    # --- checkpoint routing (state lives on the source model) -----------
    def sync_to_model(self) -> None:
        """Write the live [S, ...]-stacked stage params back onto the
        source model as OWNING per-layer copies (``np.array`` first —
        device_get can return zero-copy views on the CPU backend)."""
        if self.model is None:
            raise ValueError("this pipeline was not built from a model "
                             "(pipeline_from_mln); no checkpoint surface")
        host = jax.tree.map(np.array, jax.device_get(self.params))
        n = len(self.model.conf.layers)
        for i in range(n):
            self.model._params[i] = jax.tree.map(
                lambda a, _i=i: jnp.array(a[_i]), host)

    def sync_from_model(self) -> None:
        """Re-stack + re-place the stage params from the source model's
        per-layer trees (after a checkpoint restore)."""
        if self.model is None:
            raise ValueError("this pipeline was not built from a model "
                             "(pipeline_from_mln); no checkpoint surface")
        n = len(self.model.conf.layers)
        stacked = stack_stage_params(
            [self.model._params[i] for i in range(n)])
        self.params = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(
                self.mesh, P(*(self.axis,) + (None,) * (a.ndim - 1)))),
            stacked)

    def snapshot(self, listeners=None):
        """Host snapshot through the standard checkpoint machinery."""
        from ..util.checkpoint import snapshot_training_state

        self.sync_to_model()
        return snapshot_training_state(self.model, listeners)

    def restore(self, path: str, listeners=None):
        """Restore a committed checkpoint into the source model AND the
        live stacked params; returns the pipeline cursor."""
        from ..util.checkpoint import restore_training_state

        cursor = restore_training_state(self.model, path,
                                        listeners=listeners)
        self.sync_from_model()
        return cursor


# --------------------------------------------------------------------------
# N-stage GPipe / 1F1B schedules + the self-healing production trainer
# (ISSUE 14; ROADMAP item 2)
# --------------------------------------------------------------------------

SCHEDULES = ("1f1b", "gpipe")


def stage_partition(n_layers: int, stages: int) -> List[tuple]:
    """Contiguous, RE-CUTTABLE layer partition: stage ``s`` owns layers
    ``[runs[s][0], runs[s][1])``, earlier stages absorbing the remainder.
    A remap from S to S' stages is a pure re-cut of the same layer order,
    so the math (and the checkpoint layout, which is per-layer) is
    stage-count-independent."""
    if stages < 1 or n_layers < stages:
        raise ValueError(
            f"cannot cut {n_layers} layers into {stages} stages "
            "(every stage needs at least one layer)")
    base, rem = divmod(n_layers, stages)
    runs, lo = [], 0
    for s in range(stages):
        hi = lo + base + (1 if s < rem else 0)
        runs.append((lo, hi))
        lo = hi
    return runs


def schedule_meta(schedule: str, stages: int, n_micro: int) -> dict:
    """The microbatch tick schedule as DATA: boolean/index tables over the
    (tick, stage) grid, baked as constants into the compiled step AND fed
    to the profiler ledger and the flight-recorder stage lanes — one
    source of truth, so the bubble accounting can never drift from what
    executes.

    Both schedules run T = 2(M+S-1) ticks with one forward OR one
    backward op per stage per busy tick (2M busy of T → the textbook
    bubble fraction (S-1)/(M+S-1) for both). They differ in the
    INTERLEAVE, which is what bounds the stash (saved stage inputs):

    - ``gpipe``: all M forwards (stage s fwd of microbatch m at tick
      s+m), then all M backwards — M microbatches in flight per stage;
    - ``1f1b``: stage s fwd(m) at tick s+2m, bwd(m) at tick 2S-1-s+2m —
      fwd and bwd tick parities differ per stage so they alternate
      without collision, and at most S-s microbatches are in flight at
      stage s (stash depth S, independent of M).

    Backward ops re-run the stage forward under ``jax.vjp`` against the
    stashed INPUT (activation recompute), which is what makes the 1F1B
    stash bound real rather than cosmetic.
    """
    S, M = int(stages), int(n_micro)
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown schedule {schedule!r}; pick one of "
                         f"{SCHEDULES}")
    T = 2 * (M + S - 1)
    t = np.arange(T)[:, None]
    s = np.arange(S)[None, :]
    if schedule == "1f1b":
        df = t - s
        fwd = (df >= 0) & (df % 2 == 0) & (df < 2 * M)
        m_f = np.clip(df // 2, 0, M - 1)
        db = t - (2 * S - 1 - s)
        bwd = (db >= 0) & (db % 2 == 0) & (db < 2 * M)
        m_b = np.clip(db // 2, 0, M - 1)
        stash = min(S, M)
    else:
        df = t - s
        fwd = (df >= 0) & (df < M)
        m_f = np.clip(df, 0, M - 1)
        db = t - (M + 2 * S - 2 - s)
        bwd = (db >= 0) & (db < M)
        m_b = np.clip(db, 0, M - 1)
        stash = M
    assert not (fwd & bwd).any(), "schedule bug: fwd/bwd tick collision"
    assert fwd.sum() == bwd.sum() == M * S, "schedule bug: dropped op"
    lanes = []
    for k in range(S):
        ft = np.where(fwd[:, k])[0]
        bt = np.where(bwd[:, k])[0]
        lanes.append({"fwd": (int(ft[0]), int(ft[-1]) + 1),
                      "bwd": (int(bt[0]), int(bt[-1]) + 1)})
    busy = int(fwd.sum() + bwd.sum())
    return {"schedule": schedule, "T": T, "stash": stash,
            "fwd": fwd, "m_f": m_f, "bwd": bwd, "m_b": m_b,
            "busy_ticks": busy, "tick_slots": T * S,
            "bubble_fraction": 1.0 - busy / float(T * S),
            "lanes": lanes}


def _check_identical_blocks(model) -> int:
    """The homogeneous-pipeline model contract: every layer the same
    class/config/param shapes (so one block program serves every row of
    the re-cuttable stacked layout), stateless, no preprocessors.
    Returns the layer count."""
    import dataclasses

    layers = model.conf.layers

    def conf_sig(layer):
        d = dataclasses.asdict(layer)
        d.pop("name", None)
        return d

    sig0 = jax.tree.map(lambda a: (a.shape, str(a.dtype)), model._params[0])
    conf0 = conf_sig(layers[0])
    for i in range(len(layers)):
        if i and (jax.tree.map(lambda a: (a.shape, str(a.dtype)),
                               model._params[i]) != sig0
                  or type(layers[i]) is not type(layers[0])
                  or conf_sig(layers[i]) != conf0):
            raise ValueError(
                f"layer {i} ({type(layers[i]).__name__}) does not match "
                f"layer 0 ({type(layers[0]).__name__}) — pipeline stages "
                "must be identical same-shape, same-config blocks")
        if model._states[i]:
            raise ValueError(
                f"layer {i} carries state ({list(model._states[i])}) — "
                "stateful layers (BatchNorm) cannot ride this pipeline")
        if model.conf.preprocessors.get(i) is not None:
            raise ValueError(
                f"layer {i} has an input preprocessor — preprocessors "
                "break the identical-blocks contract")
    return len(layers)


def _weighted_mse(out: jnp.ndarray, y: jnp.ndarray,
                  w: jnp.ndarray) -> jnp.ndarray:
    """Default pipeline loss: per-example MSE weighted by the pipeline's
    pad mask, SUMMED (the trainer divides by the global real-row count
    in-graph, so padded rows contribute exactly nothing)."""
    per = jnp.mean(jnp.square(out - y), axis=tuple(range(1, out.ndim)))
    return jnp.sum(per * w)


class PipelineTrainer:
    """N-stage pipeline-parallel training with GPipe or 1F1B schedules,
    composed with the data axis on a ``(data × stage)`` mesh, behind the
    repo's standard fit surface — and self-healing by ELASTIC REMAP.

    Model contract: a ``MultiLayerNetwork`` of L >= ``stages`` IDENTICAL
    stateless blocks (:func:`_check_identical_blocks`); the loss is
    ``loss_fn(out, y, w)`` — a per-microbatch WEIGHTED SUM (default
    :func:`_weighted_mse`) divided in-graph by the global real-row count,
    so the shared input pipeline's shape-stable pad rows are inert.

    Mechanics: the L layers are cut into contiguous runs
    (:func:`stage_partition`) and stacked into ``[stages * rows, ...]``
    arrays sharded over the ``stage`` mesh axis (pad rows masked, with
    exactly-zero gradients). One ``lax.scan`` over the tick tables of
    :func:`schedule_meta` runs the whole M-microbatch forward+backward
    AND the updater as ONE compiled dispatch per optimizer step: each
    busy tick a stage applies its run to the activation it holds
    (forward, input stashed) or re-runs it under ``jax.vjp`` against the
    stashed input (backward — activation recompute, the 1F1B memory
    bound); neighbor ``ppermute`` moves activations down and cotangents
    up the pipe. Per-layer gradients accumulate in ascending microbatch
    order and cross-replica sums ride a fixed-width data axis, so the
    loss/gradient sequence is BITWISE-identical across schedules and
    stage counts (and to a single-device microbatched reference) — the
    property the kill-a-stage drill's parity gate rests on. Forward and
    backward tick bodies sit behind ``lax.cond``, so a tick pays only
    for the op its schedule slot actually runs (idle bubble ticks cost
    branch overhead, not stage FLOPs); the bubble is accounted in tick
    slots of the executed mask tables (the ``pipeline`` profiler ledger
    + the smoke bench gate, which polices the TABLES against the
    analytic bound — it is schedule accounting, not a wall-clock
    measurement) and rendered as per-stage Chrome-trace lanes
    (``pipeline/stage_fwd``/``_bwd`` flight-recorder events).

    Self-healing: a stage classified as lost triggers the supervisor's
    ``remap_and_continue`` policy → :meth:`remap` re-cuts the layer
    partition over the surviving stage devices (``mesh.elastic_pool``)
    at a dispatch boundary, re-shards the host-materialized OWNING state
    in memory (the PR-3 donation lesson: ``np.array``, never device_get
    views), and training continues from the exact cursor via
    ``fit(resume_cursor=...)`` — no process restart, no disk. Compiled
    steps, meshes and partitions are cached per (stage-count, schedule):
    one compile per (stage-count, schedule) EVER, so a remap or a
    grow-back to a count already trained at swaps executables. A remap
    can never observe a partially-applied microbatch step: the whole
    schedule plus the update is one XLA dispatch and remap only runs
    between dispatches. Checkpoint-restart stays the fallback whenever
    the remap gate refuses (surviving stages < 2, unidentifiable stage,
    state not boundary-consistent).

    Checkpoints ride the standard machinery unchanged: after every
    dispatch the stacked state is republished onto the model as lazy
    per-layer views (nothing is donated, so the views stay valid through
    the listener window), and ``snapshot_training_state`` sees the
    ordinary per-layer tree — a pipeline checkpoint restores into a
    single-device fit or a different stage count with no format
    negotiation, keyed by stage position only through the partition.
    """

    def __init__(self, model, stages: int, n_micro: int,
                 schedule: str = "1f1b", data: int = 1,
                 loss_fn: Optional[Callable] = None,
                 devices: Optional[List[Any]] = None):
        from .mesh import make_pipeline_mesh

        if schedule not in SCHEDULES:
            raise ValueError(f"unknown schedule {schedule!r}; pick one of "
                             f"{SCHEDULES}")
        if stages < 2:
            raise ValueError("a pipeline needs >= 2 stages; a 1-stage "
                             "'pipeline' is a plain fit")
        model._check_init()
        n_layers = _check_identical_blocks(model)
        if n_layers < stages:
            raise ValueError(f"model has {n_layers} layers but the "
                             f"pipeline wants {stages} stages")
        if int(n_micro) < 1:
            raise ValueError("n_micro must be >= 1")
        self.model = model
        self.schedule = schedule
        self.n_micro = int(n_micro)
        self.data_axis = int(data)
        self.stages_count = int(stages)
        self.mesh = make_pipeline_mesh(self.data_axis, self.stages_count,
                                       devices=devices)
        l0 = model.conf.layers[0]
        key0 = jax.random.PRNGKey(0)

        def block(p, x):
            out, _ = l0.apply(p, x, {}, False, key0)
            return out

        self._block = block
        self._loss_fn = loss_fn or _weighted_mse
        self._listeners: List[Any] = []
        self._telemetry = None
        #: per-(stage-count, schedule) compiled artifacts — step, meta,
        #: mesh, partition, active mask. The elastic contract: one
        #: compile per (stage-count, schedule), total.
        self._exec_cache: dict = {}
        self._lost_devices: set = set()
        self._step = None
        self._meta: Optional[dict] = None
        self._stk_params = None
        self._stk_upd = None
        self._active = None
        self._upd_stacked_keys: set = set()
        self._pub_params = None
        self._set_partition(self.stages_count)

    # --- partition / state layout ---------------------------------------
    def _set_partition(self, stages: int) -> None:
        L = len(self.model.conf.layers)
        self._runs = stage_partition(L, stages)
        self._rows = max(hi - lo for lo, hi in self._runs)
        row_of = []
        for s, (lo, hi) in enumerate(self._runs):
            for l in range(lo, hi):
                row_of.append(s * self._rows + (l - lo))
        self._row_of_layer = row_of
        active = np.zeros((stages * self._rows,), np.float32)
        for r in row_of:
            active[r] = 1.0
        self._active_host = active

    def _stack_host(self, per_layer) -> Any:
        """List of L same-structure HOST layer trees → one host tree with
        leading [stages * rows] axis (pad rows zero)."""
        leaves0, treedef = jax.tree.flatten(per_layer[0])
        flat = [jax.tree.flatten(p)[0] for p in per_layer]
        rows: List[Optional[int]] = []
        for lo, hi in self._runs:
            for r in range(self._rows):
                rows.append(lo + r if lo + r < hi else None)
        out = []
        for i in range(len(leaves0)):
            zero = np.zeros_like(np.asarray(leaves0[i]))
            out.append(np.stack([np.asarray(flat[l][i])
                                 if l is not None else zero for l in rows]))
        return jax.tree.unflatten(treedef, out)

    def _place_stacked(self, host_tree):
        sh = NamedSharding(self.mesh, P("stage"))
        return jax.tree.map(lambda a: jax.device_put(a, sh), host_tree)

    def _restack_from_host(self, host_p, host_u) -> None:
        """Host per-layer state → placed stacked device state + published
        per-layer views. The single restack path (first fit, checkpoint
        restore, remap)."""
        self._stk_params = self._place_stacked(self._stack_host(host_p))
        self._active = jax.device_put(
            self._active_host, NamedSharding(self.mesh, P("stage")))
        pstruct = jax.tree.structure(host_p)
        self._upd_stacked_keys = set()
        if isinstance(host_u, dict) and host_u:
            stk = {}
            for k, v in host_u.items():
                if jax.tree.structure(v) == pstruct:
                    self._upd_stacked_keys.add(k)
                    stk[k] = self._place_stacked(self._stack_host(v))
                else:
                    stk[k] = jax.tree.map(jnp.array, v)
            self._stk_upd = stk
        else:
            self._stk_upd = {}
        self._publish()

    def _layer_views(self, stacked):
        return [jax.tree.map(lambda a, _r=r: a[_r], stacked)
                for r in self._row_of_layer]

    def _publish(self) -> None:
        """Republish the live stacked state onto the model as per-layer
        views — lazy device slices, no host sync. MUST precede the
        listener callbacks (a checkpoint listener snapshots
        ``model._params`` at iteration boundaries); valid until the next
        dispatch because the step donates nothing."""
        model = self.model
        model._params = self._layer_views(self._stk_params)
        if isinstance(self._stk_upd, dict) and self._stk_upd:
            model._updater_state = {
                k: (self._layer_views(v) if k in self._upd_stacked_keys
                    else v)
                for k, v in self._stk_upd.items()}
        else:
            model._updater_state = self._stk_upd
        self._pub_params = model._params
        model._live_stages = self.stages_count

    def _ensure_state(self) -> None:
        """Bring the model's per-layer state into this trainer's stacked
        placed layout — first fit, after a checkpoint restore replaced
        the params under us (detected by identity vs the last published
        views), or after an external mutation."""
        model = self.model
        if self._stk_params is not None \
                and model._params is self._pub_params:
            return
        if model._updater_state is None:
            model._updater_state = \
                model.conf.global_conf.updater.init(model._params)
        host_p, host_u = jax.tree.map(np.array, jax.device_get(
            (model._params, model._updater_state)))
        self._restack_from_host(host_p, host_u)
        OpProfiler.get().gauge("pipeline/stages", self.stages_count)

    # --- compiled step ---------------------------------------------------
    def _upd_spec(self):
        if isinstance(self._stk_upd, dict) and self._stk_upd:
            return {k: (P("stage") if k in self._upd_stacked_keys else P())
                    for k in self._stk_upd}
        return P()

    def _ensure_step(self) -> None:
        key = (self.stages_count, self.schedule)
        ent = self._exec_cache.setdefault(key, {})
        ent.update(mesh=self.mesh, runs=self._runs, rows=self._rows,
                   row_of=self._row_of_layer, active=self._active_host)
        if ent.get("step") is None:
            ent["meta"] = schedule_meta(self.schedule, self.stages_count,
                                        self.n_micro)
            ent["step"] = self._build_step(
                self.mesh, self.stages_count, self._rows,
                self._row_of_layer, ent["meta"])
        self._step = ent["step"]
        self._meta = ent["meta"]

    def _build_step(self, mesh: Mesh, S: int, R: int, row_of, meta: dict):
        from jax.experimental.shard_map import shard_map

        M = self.n_micro
        T, K = meta["T"], meta["stash"]
        fwd_c = jnp.asarray(meta["fwd"])
        bwd_c = jnp.asarray(meta["bwd"])
        mf_c = jnp.asarray(meta["m_f"])
        mb_c = jnp.asarray(meta["m_b"])
        row_sel = jnp.asarray(np.asarray(row_of, np.int32))
        block = self._block
        loss_fn = self._loss_fn
        updater = self.model.conf.global_conf.updater
        tele = self._telemetry
        perm_f = [(i, (i + 1) % S) for i in range(S)]
        perm_b = [((i + 1) % S, i) for i in range(S)]

        def run_stage(p_rows, active, x):
            # the stage's (padded) run of layers, applied in order; a pad
            # row selects the input unchanged, so its params get EXACTLY
            # zero gradient through the where
            for r in range(R):
                p_r = jax.tree.map(lambda a, _r=r: a[_r], p_rows)
                x = jnp.where(active[r] > 0, block(p_r, x), x)
            return x

        def local(params, active, upd_state, x, y, w, it):
            me = lax.axis_index("stage")
            is_last = me == S - 1
            mb = x.shape[0] // M
            micro_x = x.reshape((M, mb) + x.shape[1:])
            micro_y = y.reshape((M, mb) + y.shape[1:])
            micro_w = w.reshape((M, mb))
            # global real-row divisor, fixed before the schedule runs —
            # every per-microbatch loss/cotangent divides by it, so the
            # accumulated gradient equals the global weighted mean
            denom = jnp.maximum(lax.psum(jnp.sum(w), "data"), 1.0)

            def tick(carry, t):
                fwd_act, bwd_cot, stash, dp, loss_sum = carry
                fwd_on = fwd_c[t, me]
                bwd_on = bwd_c[t, me]
                m_f = mf_c[t, me]
                m_b = mb_c[t, me]
                # forward: stage 0 injects microbatch m_f, later stages
                # consume the neighbor activation that arrived last
                # tick. lax.cond so an idle/backward tick pays no
                # forward FLOPs (bubbles cost branch overhead, not
                # compute); the schedule is per-device, and no
                # collective sits inside a branch
                x_in = jnp.where(me == 0, micro_x[m_f], fwd_act)
                y_out = lax.cond(fwd_on,
                                 lambda xx: run_stage(params, active, xx),
                                 lambda xx: xx, x_in)
                slot = m_f % K
                stash = stash.at[slot].set(
                    jnp.where(fwd_on, x_in, stash[slot]))

                # backward: re-run the stage under vjp against the
                # stashed input (activation recompute); the last stage
                # seeds the cotangent from the loss, everyone else from
                # the neighbor cotangent that arrived last tick. Also
                # behind a cond — a fwd/idle tick pays no vjp.
                def bwd(ops):
                    x_sv, y_mb, w_mb, cot = ops
                    y_sv, vjp_fn = jax.vjp(
                        lambda p, xx: run_stage(p, active, xx),
                        params, x_sv)
                    l_m = loss_fn(y_sv, y_mb, w_mb) / denom
                    g_seed = jax.grad(
                        lambda yy: loss_fn(yy, y_mb, w_mb) / denom)(y_sv)
                    return vjp_fn(jnp.where(is_last, g_seed, cot)) + (l_m,)

                def bwd_skip(ops):
                    return (jax.tree.map(jnp.zeros_like, params),
                            jnp.zeros_like(ops[0]), jnp.float32(0.0))

                dp_m, dx, l_m = lax.cond(
                    bwd_on, bwd, bwd_skip,
                    (stash[m_b % K], micro_y[m_b], micro_w[m_b], bwd_cot))
                # ascending-m accumulation; adding the skip branch's
                # exact zeros is bitwise-neutral, which is what makes the
                # two schedules (and any stage count) produce identical
                # gradients
                dp = jax.tree.map(lambda a, d: a + d, dp, dp_m)
                loss_sum = loss_sum + jnp.where(bwd_on & is_last,
                                                l_m, 0.0)
                return (lax.ppermute(y_out, "stage", perm_f),
                        lax.ppermute(dx, "stage", perm_b),
                        stash, dp, loss_sum), None

            zero_act = jnp.zeros((mb,) + x.shape[1:], x.dtype)
            carry0 = (_pvary(zero_act, "stage"),
                      _pvary(zero_act, "stage"),
                      _pvary(jnp.zeros((K, mb) + x.shape[1:], x.dtype),
                             "stage"),
                      jax.tree.map(jnp.zeros_like, params),
                      jnp.float32(0.0))
            (_, _, _, dp, loss_sum), _ = lax.scan(tick, carry0,
                                                  jnp.arange(T))
            dp = jax.tree.map(lambda a: lax.psum(a, "data"), dp)
            # only the last stage accumulated loss; the stage psum
            # broadcasts it (summing exact zeros elsewhere)
            loss = lax.psum(lax.psum(loss_sum, "data"), "stage")
            new_params, new_upd = updater.apply(dp, upd_state, params, it)
            if tele is None:
                return new_params, new_upd, loss

            def rows_sumsq(tree):
                tot = jnp.zeros((R,), jnp.float32)
                for leaf in jax.tree.leaves(tree):
                    tot = tot + jnp.sum(
                        jnp.square(leaf.astype(jnp.float32)).reshape(R, -1),
                        axis=1)
                return tot

            nf = jnp.zeros((R,), jnp.int32)
            for leaf in jax.tree.leaves(dp):
                nf = nf + jnp.sum(
                    (~jnp.isfinite(leaf)).astype(jnp.int32).reshape(R, -1),
                    axis=1)

            def per_layer(v):
                # local [R] rows → [S*R] over the stage axis → [L] slots
                return lax.all_gather(v, "stage", tiled=True)[row_sel]

            grad_norm = jnp.sqrt(per_layer(rows_sumsq(dp)))
            update_norm = jnp.sqrt(per_layer(rows_sumsq(
                jax.tree.map(lambda n, o: n - o, new_params, params))))
            param_norm = jnp.sqrt(per_layer(rows_sumsq(new_params)))
            nf_l = per_layer(nf)
            aux = {
                "loss": loss,
                "grad_norm": grad_norm,
                "update_norm": update_norm,
                "param_norm": param_norm,
                "update_ratio": update_norm / jnp.maximum(param_norm,
                                                          1e-12),
                "nonfinite": nf_l,
                "nonfinite_total": (jnp.sum(nf_l).astype(jnp.int32)
                                    + (~jnp.isfinite(loss)).astype(
                                        jnp.int32)),
            }
            return new_params, new_upd, loss, aux

        pspec = P("stage")
        uspec = self._upd_spec()
        out_specs = (pspec, uspec, P())
        if tele is not None:
            out_specs += (P(),)
        sharded = shard_map(
            local, mesh=mesh,
            in_specs=(pspec, P("stage"), uspec, P("data"), P("data"),
                      P("data"), P()),
            out_specs=out_specs, check_rep=False)

        def step(*args):
            OpProfiler.get().count("trace/pipeline_fit_step")
            return sharded(*args)

        return xprof.register_jit("pipeline/fit_step", jax.jit(step))

    # --- fit surface -----------------------------------------------------
    def set_listeners(self, *ls) -> None:
        self._listeners = list(ls)
        for lst in self._listeners:
            bind = getattr(lst, "bind_group", None)
            if callable(bind):
                bind(self._listeners)
        from ..optimize.telemetry import config_for

        cfg = config_for(self._listeners)
        if cfg != self._telemetry:
            # in-graph telemetry is a build-time property of the step —
            # drop every cached executable (meta/mesh/partition stay)
            self._telemetry = cfg
            for ent in self._exec_cache.values():
                ent.pop("step", None)
            self._step = None

    def _bind_batch(self, ds, w):
        x = ds.features.to_numpy()
        y = ds.labels.to_numpy()
        if ds.labels_mask is not None:
            raise ValueError(
                "labels masks do not ride the pipeline trainer; the "
                "example-weight vector carries the pad discipline")
        self.model._last_batch_size = int(x.shape[0])
        return x, y, np.asarray(w, np.float32)

    def _pre_dispatch(self, ordinal: int) -> None:
        # the pipeline-specific drill site, sharing the fit call's
        # dispatch ordinal: device_loss names a STAGE (→ remap drill),
        # slow is a straggler stage, wedge a hung schedule
        faultinject.fault_point("pipeline/stage", ordinal)

    def _emit_stage_lanes(self, meta: dict, t0: float, t1: float) -> None:
        """Derived per-stage Chrome-trace lanes: the dispatch wall time
        split over the tick grid, one fwd WINDOW slice and one bwd
        WINDOW slice per stage on separate sub-lanes (fwd and bwd
        interleave under 1F1B, and partially-overlapping slices on ONE
        Perfetto track render wrong). Each slice spans first..last op of
        its direction — under 1F1B's steady state every other tick in
        the window belongs to the opposite direction, recorded as
        ``tick_stride`` — so the warmup/cooldown bubbles are the leading/
        trailing gaps on each lane."""
        tick = max(t1 - t0, 1e-9) / meta["T"]
        stride = 2 if meta["schedule"] == "1f1b" else 1
        for s, lane in enumerate(meta["lanes"]):
            flo, fhi = lane["fwd"]
            blo, bhi = lane["bwd"]
            flightrec.event("pipeline/stage_fwd", stage=s,
                            micro=self.n_micro, tick_stride=stride,
                            lane=f"pipeline/stage{s}/fwd",
                            dur_s=(fhi - flo) * tick,
                            ts_mono=t0 + fhi * tick)
            flightrec.event("pipeline/stage_bwd", stage=s,
                            micro=self.n_micro, tick_stride=stride,
                            lane=f"pipeline/stage{s}/bwd",
                            dur_s=(bhi - blo) * tick,
                            ts_mono=t0 + bhi * tick)

    def _dispatch_one(self, b, prof) -> None:
        from ..data import pipeline as _pipe

        model = self.model
        xs, ys, ws = b
        meta = self._meta
        t0 = time.monotonic()
        with prof.time_section("pipeline/dispatch"):
            out = self._step(self._stk_params, self._active, self._stk_upd,
                             xs, ys, ws, jnp.asarray(model._iteration))
        self._stk_params, self._stk_upd = out[0], out[1]
        loss = out[2]
        aux = out[3] if self._telemetry is not None else None
        self._publish()
        prof.count("pipeline/microbatches", self.n_micro)
        prof.count("pipeline/busy_ticks", meta["busy_ticks"])
        prof.count("pipeline/tick_slots", meta["tick_slots"])
        if flightrec.enabled():
            self._emit_stage_lanes(meta, t0, time.monotonic())
        _pipe.note_steps(model, self._listeners, [loss],
                         [aux] if aux is not None else None)

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None,
            *, pad_partial: Optional[bool] = None,
            drop_remainder: bool = False, prefetch: int = 2,
            host_prefetch: int = 0, resume_from: Optional[str] = None,
            resume_cursor: Optional[tuple] = None) -> None:
        """Pipeline-parallel training on the shared input/dispatch
        pipeline: batches pad to a multiple of data_axis × n_micro
        (shape-stable microbatches), placement is sharded over the data
        axis, and each dispatch runs the whole microbatch schedule plus
        the update as one compiled step. ``resume_from``: exact
        checkpoint resume through the PR-3 machinery (per-layer on-disk
        layout — stage-count-independent). ``resume_cursor=(epochs_done,
        steps_in_epoch)``: in-memory continuation from the holder's live
        state at a dispatch boundary (the supervisor's remap-and-continue
        path)."""
        from ..nn.multilayer import _same_shapes
        from ..util.checkpoint import begin_fit_cursor
        from ..data import pipeline as _pipe
        from .mesh import shard_batch

        model = self.model
        model._check_init()
        if not self._listeners and getattr(model, "_listeners", None):
            self.set_listeners(*model._listeners)
        if resume_cursor is not None:
            if resume_from is not None:
                raise ValueError(
                    "resume_from and resume_cursor are mutually exclusive")
            skip = (int(resume_cursor[0]), int(resume_cursor[1]))
            model._fit_epoch0 = model._epoch - skip[0]
            model._steps_in_epoch = skip[1]
        else:
            # a restore replaces the per-layer params under us; nothing
            # is donated, so cached executables stay valid — only the
            # stacked placement rebuilds (_ensure_state detects the
            # identity change)
            skip = begin_fit_cursor(model, resume_from,
                                    listeners=self._listeners)
        self._ensure_state()
        self._ensure_step()
        # re-stamp liveness after the begin_fit_cursor anchor cleared it
        # (per-fit metadata: only pipeline fits record a stage count)
        model._live_stages = self.stages_count
        prof = OpProfiler.get()
        prof.gauge("pipeline/stages", self.stages_count)

        def on_epoch():
            model._epoch += 1
            model._steps_in_epoch = 0
            for lst in self._listeners:
                if hasattr(lst, "epoch_done"):
                    lst.epoch_done(model, model._epoch)

        _pipe.run_epochs(
            data, epochs, batch_size,
            pad_partial=True if pad_partial is None else pad_partial,
            drop_remainder=drop_remainder, prefetch=prefetch,
            steps_per_dispatch=1,
            bind=self._bind_batch,
            place=lambda b: shard_batch(self.mesh, *b),
            dispatch_one=lambda b: self._dispatch_one(b, prof),
            dispatch_chunk=lambda g: None,
            stackable=_same_shapes, on_epoch=on_epoch,
            round_to_multiple_of=self.data_axis * self.n_micro,
            host_prefetch=host_prefetch, skip=skip,
            pre_dispatch=self._pre_dispatch)

    # --- elastic remap (shrink/grow the stage axis, no restart) ----------
    def remap(self, stages: int, *, lost_stages=None) -> List[Any]:
        """Online elastic REMAP of the pipeline at a DISPATCH BOUNDARY:
        re-cut the layer partition over ``stages`` stage columns of
        surviving devices, re-shard the training state in memory — no
        process restart, no disk.

        Exact by construction: the per-layer state is host-materialized
        with OWNING copies and re-stacked under the new partition (a pure
        permutation — the same guarantee as checkpoint resharding), and
        the schedule math is stage-count-independent, so the post-remap
        loss sequence is bitwise-equal to a fresh run at the surviving
        count handed the same state/cursor/RNG. Compiled steps are cached
        per (stage-count, schedule); a remap (or grow-back) to a count
        already trained at reuses its executable and mesh.

        ``lost_stages``: stage indices whose device column is gone; their
        devices are excluded from the new mesh and remembered ACROSS
        calls — a later remap re-probes every once-lost device and only
        lets it rejoin after it answers. Returns the devices removed —
        the supervisor's grow-back probe targets.

        Consistency rule (documented for the README): a remap can never
        observe a partially-applied microbatch step — the whole schedule
        plus update is one XLA dispatch, and remap only runs between
        dispatches (or after a fit unwound at a step boundary)."""
        from .mesh import elastic_pool, make_pipeline_mesh, probe_device

        S_new = int(stages)
        old = self.stages_count
        if S_new < 2:
            raise ValueError(
                "a pipeline needs >= 2 stages; shrinking below that is "
                "the remap gate's refusal case (checkpoint-restart owns "
                "it)")
        if S_new > len(self.model.conf.layers):
            raise ValueError(
                f"model has {len(self.model.conf.layers)} layers; cannot "
                f"cut into {S_new} stages")
        lost = sorted({int(s) for s in (lost_stages or ())})
        if any(s < 0 or s >= old for s in lost):
            raise ValueError(f"lost_stages {lost} out of range for "
                             f"{old} stages")
        if S_new == old and not lost:
            return []
        prof = OpProfiler.get()
        with flightrec.span("pipeline/remap", severity="warn",
                            stages_from=old, stages_to=S_new, lost=lost), \
                prof.time_section("pipeline/remap"):
            # 1) host-materialize the per-layer training state with
            # OWNING copies (np.array — never device_get views)
            model = self.model
            self._ensure_state()
            host_p, host_u = jax.tree.map(np.array, jax.device_get(
                (model._params, model._updater_state)))
            # 2) stash this count's artifacts, then reuse or rebuild the
            # target count's mesh+partition. Once-lost devices are
            # remembered across calls and re-probed: a cached mesh can
            # never silently reinstate a still-dead device.
            ent = self._exec_cache.setdefault((old, self.schedule), {})
            ent.update(mesh=self.mesh, runs=self._runs, rows=self._rows,
                       row_of=self._row_of_layer, active=self._active_host)
            old_devs = list(self.mesh.devices.flat)
            lost_devs = [d for s in lost
                         for d in self.mesh.devices[:, s].tolist()]
            self._lost_devices = {d for d in self._lost_devices
                                  if not probe_device(d)}
            self._lost_devices |= set(lost_devs)
            cached = self._exec_cache.get((S_new, self.schedule))
            if cached is not None and cached.get("mesh") is not None \
                    and not (self._lost_devices
                             & set(cached["mesh"].devices.flat)):
                self.mesh = cached["mesh"]
                self._runs = cached["runs"]
                self._rows = cached["rows"]
                self._row_of_layer = cached["row_of"]
                self._active_host = cached["active"]
            else:
                pool = elastic_pool(self.mesh,
                                    exclude=self._lost_devices)
                need = self.data_axis * S_new
                if need > len(pool):
                    raise ValueError(
                        f"remap to {S_new} stages needs {need} devices; "
                        f"only {len(pool)} are available")
                self.mesh = make_pipeline_mesh(self.data_axis, S_new,
                                               devices=pool[:need])
                self._set_partition(S_new)
                if cached is not None:
                    cached.pop("step", None)
            self.stages_count = S_new
            new_devs = set(self.mesh.devices.flat)
            removed = [d for d in old_devs if d not in new_devs]
            # 3) re-stack + place under the new partition, republish
            self._restack_from_host(host_p, host_u)
            self._step = None
            self._meta = None
            prof.gauge("pipeline/stages", S_new)
        prof.count("pipeline/remaps")
        logger.warning("pipeline remap: %d -> %d stages%s", old, S_new,
                       f" (lost stages {lost})" if lost else "")
        return removed

    def resize(self, stages: int, *, lost_replicas=None) -> List[Any]:
        """Supervisor-facing alias: the grow-back machinery drives every
        elastic target through ``resize`` — for a pipeline that means a
        stage-count remap."""
        return self.remap(stages, lost_stages=lost_replicas)

    def probe_stages(self) -> List[int]:
        """Stage indices with any device failing the tiny round-trip
        probe — the ground-truth check behind remap-and-continue when a
        failure did not name the lost stage itself."""
        from .mesh import probe_device

        cols = self.mesh.devices
        return [s for s in range(self.stages_count)
                if any(not probe_device(d) for d in cols[:, s].tolist())]

    def shutdown(self) -> None:
        self._step = None
        self._exec_cache.clear()
