"""Pipeline parallelism: microbatched stage execution over a mesh axis.

Reference status: the reference has NO pipeline parallelism (SURVEY §2.4
marks the row absent; "optional later via shard_map stages"). On TPU it is
a natural mesh dimension, so the rebuild provides the canonical GPipe-style
construction natively (same spirit as the ring-attention and tensor-parallel
additions):

- S stages live one-per-device along a mesh ``stage`` axis — HOMOGENEOUS
  repeated blocks as [S, ...]-stacked params (``pipeline_apply``), or
  HETEROGENEOUS per-stage programs/shapes via flattened-param rows and a
  ``lax.switch`` over padded activation payloads
  (:class:`HeterogeneousPipeline`, round 5);
- the global batch splits into M microbatches; a ``lax.scan`` runs
  M + S - 1 ticks in which every device applies its stage to the activation
  it holds and passes the result to the next stage with neighbor-only
  ``ppermute`` (rides ICI);
- stage 0 injects microbatch t at tick t; the last stage's outputs are
  collected tick-aligned and reassembled, then ``psum``-broadcast.

The whole pipeline is one jitted module and is DIFFERENTIABLE (scan +
ppermute both have transpose rules), so ``jax.grad`` through
``pipeline_apply`` yields per-stage parameter gradients — enough to train.
Bubble fraction is the textbook (S-1)/(M+S-1); pick M >> S.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax < 0.5 has no varying-type system: pvary is the identity there (the
# rep checker it informs does not exist either)
_pvary = getattr(lax, "pvary", lambda x, axis_name: x)


def stack_stage_params(params_list):
    """[per-stage pytree, ...] → one pytree with leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_apply(stage_fn: Callable, stacked_params, x: jnp.ndarray,
                   mesh: Mesh, n_micro: int, axis: str = "stage"):
    """Run ``stage_fn(params, x) -> y`` (same shape in/out) as an S-stage
    pipeline over ``axis``. x: [B, ...] with B divisible by ``n_micro``.
    Returns [B, ...] replicated."""
    from jax.experimental.shard_map import shard_map

    S = mesh.shape[axis]
    B = x.shape[0]
    assert B % n_micro == 0, "batch must divide into microbatches"
    mb = B // n_micro

    def local(params_l, x_full):
        me = lax.axis_index(axis)
        p = jax.tree.map(lambda a: a[0], params_l)     # my stage's slice
        micro = x_full.reshape((n_micro, mb) + x_full.shape[1:])
        T = n_micro + S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            act = carry
            # stage 0 injects microbatch t (clipped; late ticks are
            # pipeline-drain bubbles masked out at collection)
            inj = micro[jnp.clip(t, 0, n_micro - 1)]
            inp = jnp.where(me == 0, inj, act)
            out = stage_fn(p, inp)
            nxt = lax.ppermute(out, axis, perm)
            return nxt, out

        act0 = _pvary(jnp.zeros((mb,) + x_full.shape[1:], x_full.dtype),
                      axis)
        _, outs = lax.scan(tick, act0, jnp.arange(T))   # [T, mb, ...]
        # microbatch m exits the LAST stage at tick m + S - 1
        final = lax.dynamic_slice_in_dim(outs, S - 1, n_micro, axis=0)
        final = final * (me == S - 1).astype(final.dtype)
        final = lax.psum(final, axis)                   # replicate
        return final.reshape((B,) + x_full.shape[1:])

    # P(axis) is a prefix spec: leading (stage) dim sharded, the rest
    # replicated, for every leaf of the params pytree
    fn = shard_map(local, mesh=mesh, in_specs=(P(axis), P()),
                   out_specs=P())
    return fn(stacked_params, x)


# --------------------------------------------------------------------------
# heterogeneous stages (round 5 — VERDICT r4 weak #2)


def _flatten_params(tree):
    """Pytree → (f32 vector, unflatten) — the per-stage param payload for
    the heterogeneous pipeline (stages have DIFFERENT param trees, so they
    ride a common [S, P_max] stacked-vector layout instead of a stacked
    pytree)."""
    leaves, treedef = jax.tree.flatten(tree)
    shapes = [np.shape(l) for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    vec = (jnp.concatenate([jnp.ravel(jnp.asarray(l, jnp.float32))
                            for l in leaves])
           if leaves else jnp.zeros((0,), jnp.float32))

    def unflatten(v):
        out, off = [], 0
        for shp, sz in zip(shapes, sizes):
            out.append(v[off:off + sz].reshape(shp))
            off += sz
        return jax.tree.unflatten(treedef, out)

    return vec, unflatten


class HeterogeneousPipeline:
    """GPipe-style pipeline over stages with DIFFERENT programs, param
    trees, and activation shapes (the homogeneous construction above cannot
    express ResNet/BERT stage cuts — round-4 verdict weak #2).

    SPMD mechanics: every device runs the same jitted program; the
    per-stage computation is a ``lax.switch`` over the stage index, with
    activations packed into a fixed [PAD] f32 payload (PAD = the largest
    inter-stage activation) so every branch — and the neighbor ``ppermute``
    that moves activations down the pipe — has one static shape. Stage
    parameters are flattened to one f32 vector each and stacked [S, P_max],
    sharded over the ``stage`` mesh axis; each device unflattens only ITS
    row inside its switch branch. Differentiable end to end (switch, scan,
    ppermute all transpose), so ``train_step`` trains all stages.

    Parameters are held in FLOAT32 (the flattened payload's dtype).
    """

    def __init__(self, stage_fns, params_list, in_shapes, out_shapes,
                 mesh: Mesh, n_micro: int, axis: str = "stage",
                 loss_fn: Callable = None):
        S = len(stage_fns)
        if mesh.shape[axis] != S:
            raise ValueError(f"{S} stages but mesh axis {axis!r} has "
                             f"{mesh.shape[axis]} devices")
        for s in range(S - 1):
            if tuple(out_shapes[s]) != tuple(in_shapes[s + 1]):
                raise ValueError(
                    f"stage {s} outputs {out_shapes[s]} but stage {s + 1} "
                    f"expects {in_shapes[s + 1]}")
        self.mesh, self.axis, self.n_micro = mesh, axis, n_micro
        self.in_shapes = [tuple(s) for s in in_shapes]
        self.out_shapes = [tuple(s) for s in out_shapes]
        self._loss_fn = loss_fn or (lambda out, y: jnp.mean((out - y) ** 2))

        vecs, self._unflattens = zip(
            *[_flatten_params(p) for p in params_list])
        p_max = max(max(v.size for v in vecs), 1)
        stacked = jnp.stack([jnp.pad(v, (0, p_max - v.size)) for v in vecs])
        self.params = jax.device_put(
            stacked, NamedSharding(mesh, P(axis, None)))
        self._stage_fns = list(stage_fns)

    def _build(self, mb: int):
        S = len(self._stage_fns)
        axis, n_micro = self.axis, self.n_micro
        in_sz = [mb * int(np.prod(s)) for s in self.in_shapes]
        out_sz = [mb * int(np.prod(s)) for s in self.out_shapes]
        pad = max(in_sz + out_sz)

        def branch(s):
            fn, unflat = self._stage_fns[s], self._unflattens[s]
            ishape, isz, osz = self.in_shapes[s], in_sz[s], out_sz[s]

            def b(pvec, act):
                x = act[:isz].reshape((mb,) + ishape)
                y = fn(unflat(pvec), x)
                return jnp.zeros((pad,), jnp.float32).at[:osz].set(
                    jnp.ravel(y).astype(jnp.float32))

            return b

        branches = [branch(s) for s in range(S)]
        perm = [(i, (i + 1) % S) for i in range(S)]
        o_last = out_sz[-1]
        oshape_last = self.out_shapes[-1]

        def local(pstacked, x_full):
            me = lax.axis_index(axis)
            pvec = pstacked[0]
            B = x_full.shape[0]
            micro = x_full.reshape((n_micro, mb) + x_full.shape[1:])
            T = n_micro + S - 1

            def tick(act, t):
                inj = jnp.zeros((pad,), jnp.float32).at[:in_sz[0]].set(
                    jnp.ravel(micro[jnp.clip(t, 0, n_micro - 1)]).astype(
                        jnp.float32))
                inp = jnp.where(me == 0, inj, act)
                out = lax.switch(me, branches, pvec, inp)
                nxt = lax.ppermute(out, axis, perm)
                return nxt, out

            act0 = _pvary(jnp.zeros((pad,), jnp.float32), axis)
            _, outs = lax.scan(tick, act0, jnp.arange(T))
            final = lax.dynamic_slice_in_dim(outs, S - 1, n_micro, axis=0)
            y = final[:, :o_last].reshape((B,) + oshape_last)
            y = y * (me == S - 1).astype(y.dtype)
            return lax.psum(y, axis)

        from jax.experimental.shard_map import shard_map

        # check_rep=False: the lax.switch over per-stage programs yields
        # branch outputs whose replication types the jax-0.4 checker cannot
        # unify (newer jax resolves this through pvary varying types); the
        # psum at the tail replicates the result regardless
        return shard_map(local, mesh=self.mesh,
                         in_specs=(P(axis, None), P()), out_specs=P(),
                         check_rep=False)

    def _fns(self, B: int):
        cache = getattr(self, "_jit_cache", None)
        if cache is None:
            cache = self._jit_cache = {}
        if B not in cache:
            assert B % self.n_micro == 0, \
                "batch must divide into microbatches"
            mb = B // self.n_micro
            pipe = self._build(mb)
            fwd = jax.jit(pipe)
            loss_fn = self._loss_fn

            @jax.jit
            def step(params, x, y, lr):
                def lf(p):
                    return loss_fn(pipe(p, x), y)

                loss, grads = jax.value_and_grad(lf)(params)
                return jax.tree.map(lambda p, g: p - lr * g, params,
                                    grads), loss

            cache[B] = (fwd, step)
        return cache[B]

    def forward(self, x) -> jnp.ndarray:
        x = jnp.asarray(x)
        return self._fns(x.shape[0])[0](self.params, x)

    def train_step(self, x, y, lr: float = 1e-2) -> float:
        x, y = jnp.asarray(x), jnp.asarray(y)
        self.params, loss = self._fns(x.shape[0])[1](
            self.params, x, y, jnp.float32(lr))
        return loss

    def stage_params(self, s: int):
        """Unflattened param tree of stage ``s`` (for parity checks /
        exporting back into a model)."""
        return self._unflattens[s](np.asarray(self.params)[s])


def pipeline_from_mln(model, mesh: Mesh, n_micro: int, axis: str = "stage",
                      cuts=None, example_input=None):
    """Adapter from a ``MultiLayerNetwork`` to a pipeline.

    Without ``cuts`` (legacy form): the model must be S REPEATED same-shape
    blocks — the [S, ...]-stacked homogeneous construction (VERDICT r3
    item 3c).

    With ``cuts`` (round 5): ``cuts`` lists the first layer index of each
    stage after the first (e.g. ``cuts=[3]`` splits layers 0–2 | 3–end into
    2 stages), mapping ARBITRARY contiguous layer runs — conv front / dense
    head, transformer block splits — onto a :class:`HeterogeneousPipeline`.
    ``example_input`` (one batch-shaped array or shape tuple) is required
    to derive the inter-stage activation shapes. Stages run with
    ``training=False`` layer semantics (no dropout) and stateful layers
    (BatchNorm running stats) are refused, as in the legacy form.
    """
    if cuts is not None:
        return _pipeline_from_mln_het(model, mesh, n_micro, axis, cuts,
                                      example_input)
    return _pipeline_from_mln_homogeneous(model, mesh, n_micro, axis)


def _pipeline_from_mln_het(model, mesh, n_micro, axis, cuts, example_input):
    if example_input is None:
        raise ValueError("cuts=... needs example_input to derive "
                         "inter-stage activation shapes")
    layers = model.conf.layers
    cut_list = sorted(int(c) for c in cuts)
    if (len(set(cut_list)) != len(cut_list)
            or any(c <= 0 or c >= len(layers) for c in cut_list)):
        raise ValueError(
            f"bad cuts {cuts} for {len(layers)} layers: cut indices must "
            f"be unique and in (0, {len(layers)})")
    bounds = [0] + cut_list + [len(layers)]
    runs = list(zip(bounds[:-1], bounds[1:]))
    S = mesh.shape[axis]
    if len(runs) != S:
        raise ValueError(f"cuts give {len(runs)} stages but mesh axis "
                         f"{axis!r} has {S} devices")
    for i in range(len(layers)):
        if model._states[i]:
            raise ValueError(
                f"layer {i} carries state ({list(model._states[i])}) — "
                "stateful layers (BatchNorm) cannot ride this pipeline")

    key = jax.random.PRNGKey(0)

    def make_stage(lo, hi):
        def fn(params, x):
            for i in range(lo, hi):
                pre = model.conf.preprocessors.get(i)
                if pre is not None:
                    x = pre(x)
                x, _ = layers[i].apply(params[str(i)], x, {}, False, key)
            return x

        return fn

    stage_fns = [make_stage(lo, hi) for lo, hi in runs]
    params_list = [{str(i): model._params[i] for i in range(lo, hi)}
                   for lo, hi in runs]

    x = (jnp.zeros(example_input, jnp.float32)
         if isinstance(example_input, (tuple, list))
         else jnp.asarray(example_input))
    in_shapes, out_shapes = [], []
    cur = jax.eval_shape(lambda a: a, x)
    for s, fn in enumerate(stage_fns):
        in_shapes.append(tuple(cur.shape[1:]))
        cur = jax.eval_shape(fn, params_list[s],
                             jax.ShapeDtypeStruct(cur.shape, jnp.float32))
        out_shapes.append(tuple(cur.shape[1:]))
    return HeterogeneousPipeline(stage_fns, params_list, in_shapes,
                                 out_shapes, mesh, n_micro, axis)


def _pipeline_from_mln_homogeneous(model, mesh: Mesh, n_micro: int,
                                   axis: str = "stage") -> "PipelineParallel":
    """S REPEATED same-shape blocks → [S, ...]-stacked pipeline.

    Constraint (documented, inherent to the [S, ...]-stacked construction):
    every layer must be the same class with identical param tree shapes and
    same input/output shape, and be stateless (no BatchNorm running state) —
    e.g. a stack of Dense(n→n) blocks or identical transformer/attention
    blocks. Heterogeneous models (ResNet/BERT stage cuts) go through
    ``cuts=...`` → :class:`HeterogeneousPipeline`.
    """
    layers = model.conf.layers
    S = mesh.shape[axis]
    if len(layers) != S:
        raise ValueError(f"model has {len(layers)} layers but the "
                         f"{axis!r} mesh axis has {S} stages")
    import dataclasses

    def conf_sig(layer):
        d = dataclasses.asdict(layer)
        d.pop("name", None)
        return d

    sig0 = jax.tree.map(lambda a: (a.shape, str(a.dtype)), model._params[0])
    conf0 = conf_sig(layers[0])
    for i in range(1, S):
        sig = jax.tree.map(lambda a: (a.shape, str(a.dtype)),
                           model._params[i])
        # full CONFIG equality, not just class+shapes: stage_fn runs every
        # stage with layer 0's config, so a differing activation/dropout
        # would silently change the math
        if (sig != sig0 or type(layers[i]) is not type(layers[0])
                or conf_sig(layers[i]) != conf0):
            raise ValueError(
                f"layer {i} ({type(layers[i]).__name__}) does not match "
                f"layer 0 ({type(layers[0]).__name__}) — pipeline stages "
                "must be identical same-shape, same-config blocks")
        if model._states[i]:
            raise ValueError(
                f"layer {i} carries state ({list(model._states[i])}) — "
                "stateful layers (BatchNorm) cannot ride this pipeline")
    l0 = layers[0]
    key = jax.random.PRNGKey(0)

    def stage_fn(p, x):
        out, _ = l0.apply(p, x, {}, False, key)
        return out

    return PipelineParallel(stage_fn,
                            [model._params[i] for i in range(S)],
                            mesh, n_micro, axis)


class PipelineParallel:
    """Convenience wrapper: holds stacked stage params sharded over the
    mesh axis and exposes jitted forward / train_step."""

    def __init__(self, stage_fn: Callable, params_list, mesh: Mesh,
                 n_micro: int, axis: str = "stage"):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.axis = axis
        self.n_micro = n_micro
        stacked = stack_stage_params(params_list)
        self.params = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(
                mesh, P(*(axis,) + (None,) * (a.ndim - 1)))), stacked)

        @jax.jit
        def fwd(params, x):
            return pipeline_apply(self.stage_fn, params, x, self.mesh,
                                  self.n_micro, self.axis)

        self._fwd = fwd

        @jax.jit
        def step(params, x, y, lr):
            def loss_fn(p):
                out = pipeline_apply(self.stage_fn, p, x, self.mesh,
                                     self.n_micro, self.axis)
                return jnp.mean((out - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
            return new, loss

        self._step = step

    def forward(self, x) -> jnp.ndarray:
        return self._fwd(self.params, jnp.asarray(x))

    def train_step(self, x, y, lr: float = 1e-2) -> float:
        self.params, loss = self._step(self.params, jnp.asarray(x),
                                       jnp.asarray(y), jnp.float32(lr))
        return loss
