"""Pipeline parallelism: microbatched stage execution over a mesh axis.

Reference status: the reference has NO pipeline parallelism (SURVEY §2.4
marks the row absent; "optional later via shard_map stages"). On TPU it is
a natural mesh dimension, so the rebuild provides the canonical GPipe-style
construction natively (same spirit as the ring-attention and tensor-parallel
additions):

- S homogeneous stages live one-per-device along a mesh ``stage`` axis
  (stage parameters stacked on a leading [S, ...] axis and sharded over it);
- the global batch splits into M microbatches; a ``lax.scan`` runs
  M + S - 1 ticks in which every device applies its stage to the activation
  it holds and passes the result to the next stage with neighbor-only
  ``ppermute`` (rides ICI);
- stage 0 injects microbatch t at tick t; the last stage's outputs are
  collected tick-aligned and reassembled, then ``psum``-broadcast.

The whole pipeline is one jitted module and is DIFFERENTIABLE (scan +
ppermute both have transpose rules), so ``jax.grad`` through
``pipeline_apply`` yields per-stage parameter gradients — enough to train.
Bubble fraction is the textbook (S-1)/(M+S-1); pick M >> S.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(params_list):
    """[per-stage pytree, ...] → one pytree with leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_apply(stage_fn: Callable, stacked_params, x: jnp.ndarray,
                   mesh: Mesh, n_micro: int, axis: str = "stage"):
    """Run ``stage_fn(params, x) -> y`` (same shape in/out) as an S-stage
    pipeline over ``axis``. x: [B, ...] with B divisible by ``n_micro``.
    Returns [B, ...] replicated."""
    from jax.experimental.shard_map import shard_map

    S = mesh.shape[axis]
    B = x.shape[0]
    assert B % n_micro == 0, "batch must divide into microbatches"
    mb = B // n_micro

    def local(params_l, x_full):
        me = lax.axis_index(axis)
        p = jax.tree.map(lambda a: a[0], params_l)     # my stage's slice
        micro = x_full.reshape((n_micro, mb) + x_full.shape[1:])
        T = n_micro + S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            act = carry
            # stage 0 injects microbatch t (clipped; late ticks are
            # pipeline-drain bubbles masked out at collection)
            inj = micro[jnp.clip(t, 0, n_micro - 1)]
            inp = jnp.where(me == 0, inj, act)
            out = stage_fn(p, inp)
            nxt = lax.ppermute(out, axis, perm)
            return nxt, out

        act0 = lax.pvary(jnp.zeros((mb,) + x_full.shape[1:], x_full.dtype),
                         axis)
        _, outs = lax.scan(tick, act0, jnp.arange(T))   # [T, mb, ...]
        # microbatch m exits the LAST stage at tick m + S - 1
        final = lax.dynamic_slice_in_dim(outs, S - 1, n_micro, axis=0)
        final = final * (me == S - 1).astype(final.dtype)
        final = lax.psum(final, axis)                   # replicate
        return final.reshape((B,) + x_full.shape[1:])

    # P(axis) is a prefix spec: leading (stage) dim sharded, the rest
    # replicated, for every leaf of the params pytree
    fn = shard_map(local, mesh=mesh, in_specs=(P(axis), P()),
                   out_specs=P())
    return fn(stacked_params, x)


def pipeline_from_mln(model, mesh: Mesh, n_micro: int,
                      axis: str = "stage") -> "PipelineParallel":
    """Adapter from a ``MultiLayerNetwork`` of S REPEATED same-shape blocks
    to an S-stage pipeline (VERDICT r3 item 3c).

    Constraint (documented, inherent to the [S, ...]-stacked construction):
    every layer must be the same class with identical param tree shapes and
    same input/output shape, and be stateless (no BatchNorm running state) —
    e.g. a stack of Dense(n→n) blocks or identical transformer/attention
    blocks. Heterogeneous models (ResNet/BERT stage cuts) need per-stage
    programs and are out of scope for this construction.
    """
    layers = model.conf.layers
    S = mesh.shape[axis]
    if len(layers) != S:
        raise ValueError(f"model has {len(layers)} layers but the "
                         f"{axis!r} mesh axis has {S} stages")
    import dataclasses

    def conf_sig(layer):
        d = dataclasses.asdict(layer)
        d.pop("name", None)
        return d

    sig0 = jax.tree.map(lambda a: (a.shape, str(a.dtype)), model._params[0])
    conf0 = conf_sig(layers[0])
    for i in range(1, S):
        sig = jax.tree.map(lambda a: (a.shape, str(a.dtype)),
                           model._params[i])
        # full CONFIG equality, not just class+shapes: stage_fn runs every
        # stage with layer 0's config, so a differing activation/dropout
        # would silently change the math
        if (sig != sig0 or type(layers[i]) is not type(layers[0])
                or conf_sig(layers[i]) != conf0):
            raise ValueError(
                f"layer {i} ({type(layers[i]).__name__}) does not match "
                f"layer 0 ({type(layers[0]).__name__}) — pipeline stages "
                "must be identical same-shape, same-config blocks")
        if model._states[i]:
            raise ValueError(
                f"layer {i} carries state ({list(model._states[i])}) — "
                "stateful layers (BatchNorm) cannot ride this pipeline")
    l0 = layers[0]
    key = jax.random.PRNGKey(0)

    def stage_fn(p, x):
        out, _ = l0.apply(p, x, {}, False, key)
        return out

    return PipelineParallel(stage_fn,
                            [model._params[i] for i in range(S)],
                            mesh, n_micro, axis)


class PipelineParallel:
    """Convenience wrapper: holds stacked stage params sharded over the
    mesh axis and exposes jitted forward / train_step."""

    def __init__(self, stage_fn: Callable, params_list, mesh: Mesh,
                 n_micro: int, axis: str = "stage"):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.axis = axis
        self.n_micro = n_micro
        stacked = stack_stage_params(params_list)
        self.params = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(
                mesh, P(*(axis,) + (None,) * (a.ndim - 1)))), stacked)

        @jax.jit
        def fwd(params, x):
            return pipeline_apply(self.stage_fn, params, x, self.mesh,
                                  self.n_micro, self.axis)

        self._fwd = fwd

        @jax.jit
        def step(params, x, y, lr):
            def loss_fn(p):
                out = pipeline_apply(self.stage_fn, p, x, self.mesh,
                                     self.n_micro, self.axis)
                return jnp.mean((out - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
            return new, loss

        self._step = step

    def forward(self, x) -> jnp.ndarray:
        return self._fwd(self.params, jnp.asarray(x))

    def train_step(self, x, y, lr: float = 1e-2) -> float:
        self.params, loss = self._step(self.params, jnp.asarray(x),
                                       jnp.asarray(y), jnp.float32(lr))
        return loss
