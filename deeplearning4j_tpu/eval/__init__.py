from .evaluation import (Evaluation, EvaluationBinary, EvaluationCalibration,
                         ROC, ROCBinary, ROCMultiClass, RegressionEvaluation)
