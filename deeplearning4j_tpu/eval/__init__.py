from .evaluation import Evaluation, EvaluationBinary, ROC, ROCMultiClass, RegressionEvaluation
