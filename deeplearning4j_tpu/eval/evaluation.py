"""Evaluation suite — streaming, mergeable metrics.

Reference: nd4j-api ``org.nd4j.evaluation.classification.{Evaluation,
EvaluationBinary, ROC, ROCBinary, ROCMultiClass, EvaluationCalibration}`` and
``regression.RegressionEvaluation`` (SURVEY.md §2.1). All accumulate
incrementally over minibatches and merge across workers (the Spark-reducible
contract — here, mergeable across data-parallel hosts).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Evaluation:
    """Multi-class classification metrics over one-hot or index labels."""

    def __init__(self, num_classes: Optional[int] = None, top_n: int = 1):
        self.num_classes = num_classes
        self.top_n = top_n
        self.confusion: Optional[np.ndarray] = None
        self.top_n_correct = 0
        self.count = 0

    # ------------------------------------------------------------------
    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:  # time series [B,T,C] → flatten with mask
            b, t, c = labels.shape
            labels = labels.reshape(-1, c)
            predictions = predictions.reshape(-1, c)
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[m], predictions[m]
        elif mask is not None:  # per-example mask on plain batches
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        if labels.ndim == 2:
            true_idx = labels.argmax(1)
            n_cls = labels.shape[1]
        else:
            true_idx = labels.astype(int)
            n_cls = int(predictions.shape[-1])
        pred_idx = predictions.argmax(1)
        if self.confusion is None:
            self.num_classes = self.num_classes or n_cls
            self.confusion = np.zeros((self.num_classes, self.num_classes), np.int64)
        np.add.at(self.confusion, (true_idx, pred_idx), 1)
        self.count += len(true_idx)
        if self.top_n > 1:
            top = np.argsort(-predictions, axis=1)[:, :self.top_n]
            self.top_n_correct += int((top == true_idx[:, None]).any(1).sum())
        else:
            self.top_n_correct += int((pred_idx == true_idx).sum())

    def merge(self, other: "Evaluation") -> "Evaluation":
        if self.confusion is None:
            self.confusion = other.confusion
            self.num_classes = other.num_classes
        elif other.confusion is not None:
            self.confusion = self.confusion + other.confusion
        self.count += other.count
        self.top_n_correct += other.top_n_correct
        return self

    # --- metrics -------------------------------------------------------
    def accuracy(self) -> float:
        if self.count == 0:
            return 0.0
        return float(np.trace(self.confusion)) / self.count

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / self.count if self.count else 0.0

    def _tp(self) -> np.ndarray:
        return np.diag(self.confusion).astype(np.float64)

    def precision(self, cls: Optional[int] = None) -> float:
        col = self.confusion.sum(0).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(col > 0, self._tp() / col, 0.0)
        return float(per[cls]) if cls is not None else float(per[col > 0].mean() if (col > 0).any() else 0.0)

    def recall(self, cls: Optional[int] = None) -> float:
        row = self.confusion.sum(1).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(row > 0, self._tp() / row, 0.0)
        return float(per[cls]) if cls is not None else float(per[row > 0].mean() if (row > 0).any() else 0.0)

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0

    def matthews_correlation(self) -> float:
        """Binary MCC from the confusion matrix."""
        c = self.confusion
        if c.shape != (2, 2):
            raise ValueError("MCC defined for binary confusion only")
        tn, fp, fn, tp = c[0, 0], c[0, 1], c[1, 0], c[1, 1]
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return float((tp * tn - fp * fn) / denom) if denom > 0 else 0.0

    def stats(self) -> str:
        lines = [
            f"# examples: {self.count}",
            f"Accuracy:  {self.accuracy():.4f}",
            f"Precision: {self.precision():.4f}",
            f"Recall:    {self.recall():.4f}",
            f"F1:        {self.f1():.4f}",
        ]
        if self.top_n > 1:
            lines.append(f"Top-{self.top_n} accuracy: {self.top_n_accuracy():.4f}")
        lines.append("Confusion matrix (rows=actual):")
        lines.append(str(self.confusion))
        return "\n".join(lines)


class EvaluationBinary:
    """Per-output independent binary metrics (multi-label)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.tp = self.fp = self.tn = self.fn = None

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        preds = (np.asarray(predictions) >= self.threshold).astype(np.int64)
        lab = (labels >= 0.5).astype(np.int64)
        if mask is not None:
            m = np.asarray(mask).astype(bool)
        else:
            m = np.ones_like(lab, bool)
        tp = ((preds == 1) & (lab == 1) & m).sum(0)
        fp = ((preds == 1) & (lab == 0) & m).sum(0)
        tn = ((preds == 0) & (lab == 0) & m).sum(0)
        fn = ((preds == 0) & (lab == 1) & m).sum(0)
        if self.tp is None:
            self.tp, self.fp, self.tn, self.fn = tp, fp, tn, fn
        else:
            self.tp += tp
            self.fp += fp
            self.tn += tn
            self.fn += fn

    def merge(self, other: "EvaluationBinary") -> "EvaluationBinary":
        for attr in ("tp", "fp", "tn", "fn"):
            mine, theirs = getattr(self, attr), getattr(other, attr)
            setattr(self, attr, theirs if mine is None else mine + theirs)
        return self

    def accuracy(self, output: int) -> float:
        tot = self.tp[output] + self.fp[output] + self.tn[output] + self.fn[output]
        return float(self.tp[output] + self.tn[output]) / tot if tot else 0.0

    def precision(self, output: int) -> float:
        d = self.tp[output] + self.fp[output]
        return float(self.tp[output]) / d if d else 0.0

    def recall(self, output: int) -> float:
        d = self.tp[output] + self.fn[output]
        return float(self.tp[output]) / d if d else 0.0

    def f1(self, output: int) -> float:
        p, r = self.precision(output), self.recall(output)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0


class ROC:
    """Binary ROC/AUC + precision-recall.

    Exact mode (``num_thresholds=0``) keeps raw (label, score) pairs — the
    reference's exact-AUC path — but SPILLS automatically into thresholded
    histogram mode once ``max_exact_examples`` pairs accumulate (round-1
    verdict weak #8: unbounded host memory on large eval sets; the
    reference's thresholded mode exists exactly for this). Thresholded mode
    (``num_thresholds=N``, reference default 200) stores only 2·N bin
    counts, O(1) per example."""

    SPILL_THRESHOLDS = 200

    def __init__(self, num_thresholds: int = 0,
                 max_exact_examples: int = 1_000_000):
        self.num_thresholds = num_thresholds
        self.max_exact_examples = max_exact_examples
        self.spilled = False
        self._scores: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []
        self._n_exact = 0
        if num_thresholds > 0:
            self._init_bins(num_thresholds)
        else:
            self._pos = self._neg = None

    def _init_bins(self, t: int) -> None:
        self.num_thresholds = t
        self._pos = np.zeros(t, dtype=np.int64)
        self._neg = np.zeros(t, dtype=np.int64)

    def _bin(self, scores: np.ndarray) -> np.ndarray:
        return np.clip((scores * self.num_thresholds).astype(np.int64), 0,
                       self.num_thresholds - 1)

    def _add_binned(self, labels: np.ndarray, scores: np.ndarray) -> None:
        bins = self._bin(scores)
        self._pos += np.bincount(bins, weights=labels,
                                 minlength=self.num_thresholds)             .astype(np.int64)
        self._neg += np.bincount(bins, weights=1 - labels,
                                 minlength=self.num_thresholds)             .astype(np.int64)

    def _spill(self, thresholds: Optional[int] = None) -> None:
        self._init_bins(thresholds or self.SPILL_THRESHOLDS)
        for y, s in zip(self._labels, self._scores):
            self._add_binned(y, s)
        self._labels, self._scores = [], []
        self.spilled = True

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        if labels.ndim == 2 and labels.shape[1] == 2:
            labels = labels[:, 1]
            preds = preds[:, 1]
        labels = labels.ravel().astype(np.float64)
        preds = preds.ravel().astype(np.float64)
        if self._pos is not None:
            self._add_binned(labels, preds)
            return
        self._labels.append(labels)
        self._scores.append(preds)
        self._n_exact += labels.size
        if self._n_exact > self.max_exact_examples:
            self._spill()

    def merge(self, other: "ROC") -> "ROC":
        if self._pos is not None or other._pos is not None:
            # an exact side adopts the binned peer's bin count (its raw
            # pairs can be binned at ANY resolution)
            if self._pos is None:
                self._spill(other.num_thresholds)
            if other._pos is None:
                # bin the peer's raw pairs into OUR counts without
                # mutating the peer
                for y, sc in zip(other._labels, other._scores):
                    self._add_binned(y, sc)
                return self
            if other.num_thresholds != self.num_thresholds:
                raise ValueError("cannot merge ROCs with different "
                                 "threshold counts")
            self._pos += other._pos
            self._neg += other._neg
            return self
        self._labels.extend(other._labels)
        self._scores.extend(other._scores)
        self._n_exact += other._n_exact
        if self._n_exact > self.max_exact_examples:
            self._spill()
        return self

    def _collect(self):
        return np.concatenate(self._labels), np.concatenate(self._scores)

    def _curve_binned(self):
        """(fpr, tpr, precision ascending-threshold order) from bins."""
        # descending score: accumulate from the TOP bin down
        tps = np.cumsum(self._pos[::-1]).astype(np.float64)
        fps = np.cumsum(self._neg[::-1]).astype(np.float64)
        p, n = max(tps[-1], 1e-12), max(fps[-1], 1e-12)
        tpr = np.concatenate([[0.0], tps / p])
        fpr = np.concatenate([[0.0], fps / n])
        precision = tps / np.maximum(tps + fps, 1e-12)
        recall = tps / p
        return fpr, tpr, precision, recall

    def calculate_auc(self) -> float:
        if self._pos is not None:
            fpr, tpr, _, _ = self._curve_binned()
            return float(np.trapezoid(tpr, fpr))
        y, s = self._collect()
        order = np.argsort(-s, kind="mergesort")
        y = y[order]
        tps = np.cumsum(y)
        fps = np.cumsum(1 - y)
        p, n = y.sum(), (1 - y).sum()
        if p == 0 or n == 0:
            return 0.0
        tpr = np.concatenate([[0], tps / p])
        fpr = np.concatenate([[0], fps / n])
        return float(np.trapezoid(tpr, fpr))

    def calculate_auprc(self) -> float:
        if self._pos is not None:
            _, _, precision, recall = self._curve_binned()
            return float(np.sum(np.diff(np.concatenate([[0.0], recall]))
                                * precision))
        y, s = self._collect()
        order = np.argsort(-s, kind="mergesort")
        y = y[order]
        tps = np.cumsum(y)
        precision = tps / np.arange(1, len(y) + 1)
        recall = tps / max(y.sum(), 1)
        return float(np.sum(np.diff(np.concatenate([[0], recall])) * precision))


class ROCBinary:
    """Per-output-label binary ROC for MULTI-LABEL networks (reference
    org.nd4j.evaluation.classification.ROCBinary — one independent ROC per
    sigmoid output column)."""

    def __init__(self, num_thresholds: int = 0):
        self.num_thresholds = num_thresholds
        self._rocs: Dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        if labels.ndim != 2:
            raise ValueError("ROCBinary expects [N, num_labels] arrays")
        for c in range(labels.shape[1]):
            if mask is not None:
                m = np.asarray(mask)
                mc = m[:, c] if m.ndim == 2 else m
                keep = mc > 0
                if not keep.any():
                    continue
                self._rocs.setdefault(c, ROC(self.num_thresholds)).eval(
                    labels[keep, c], preds[keep, c])
            else:
                self._rocs.setdefault(c, ROC(self.num_thresholds)).eval(
                    labels[:, c], preds[:, c])

    def merge(self, other: "ROCBinary") -> "ROCBinary":
        for c, r in other._rocs.items():
            if c not in self._rocs:
                # fresh instance, never an alias: later eval() on the
                # merged object must not mutate the source
                self._rocs[c] = ROC(self.num_thresholds)
            self._rocs[c].merge(r)
        return self

    def calculate_auc(self, label_idx: int) -> float:
        return self._rocs[label_idx].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc()
                              for r in self._rocs.values()]))

    def num_labels(self) -> int:
        return len(self._rocs)


class EvaluationCalibration:
    """Reliability diagram + probability histograms (reference
    org.nd4j.evaluation.classification.EvaluationCalibration): per
    probability bin, how often was the prediction right — plus expected
    calibration error. Bounded memory: only per-bin counts accumulate."""

    def __init__(self, reliability_bins: int = 10,
                 histogram_bins: int = 50):
        self.reliability_bins = reliability_bins
        self.histogram_bins = histogram_bins
        self._counts = None      # [C, bins]
        self._prob_sum = None    # [C, bins] sum of predicted prob
        self._pos = None         # [C, bins] count where label == 1
        self._hist_pred = None   # [C, hist_bins] prob histogram

    def _init(self, n_classes: int) -> None:
        rb, hb = self.reliability_bins, self.histogram_bins
        self._counts = np.zeros((n_classes, rb), np.int64)
        self._prob_sum = np.zeros((n_classes, rb), np.float64)
        self._pos = np.zeros((n_classes, rb), np.int64)
        self._hist_pred = np.zeros((n_classes, hb), np.int64)

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels, np.float64)
        preds = np.asarray(predictions, np.float64)
        if labels.ndim != 2:
            raise ValueError("EvaluationCalibration expects [N, C] arrays")
        if self._counts is None:
            self._init(labels.shape[1])
        rb, hb = self.reliability_bins, self.histogram_bins
        for c in range(labels.shape[1]):
            p = preds[:, c]
            y = labels[:, c]
            if mask is not None:
                m = np.asarray(mask)
                mc = (m[:, c] if m.ndim == 2 else m.ravel()) > 0
                p, y = p[mc], y[mc]
            bins = np.clip((p * rb).astype(np.int64), 0, rb - 1)
            self._counts[c] += np.bincount(bins, minlength=rb)
            self._prob_sum[c] += np.bincount(bins, weights=p, minlength=rb)
            self._pos[c] += np.bincount(bins, weights=y,
                                        minlength=rb).astype(np.int64)
            hbins = np.clip((p * hb).astype(np.int64), 0, hb - 1)
            self._hist_pred[c] += np.bincount(hbins, minlength=hb)

    def merge(self, other: "EvaluationCalibration") -> "EvaluationCalibration":
        if other._counts is None:
            return self
        if self._counts is None:
            # copies, not aliases: later in-place += merges must not
            # corrupt the source object
            self._counts = other._counts.copy()
            self._prob_sum = other._prob_sum.copy()
            self._pos = other._pos.copy()
            self._hist_pred = other._hist_pred.copy()
            return self
        self._counts += other._counts
        self._prob_sum += other._prob_sum
        self._pos += other._pos
        self._hist_pred += other._hist_pred
        return self

    def get_reliability_info(self, class_idx: int):
        """(mean_predicted_prob, observed_frequency, counts) per bin —
        the reliability-diagram rows (reference getReliabilityInfo)."""
        counts = self._counts[class_idx]
        safe = np.maximum(counts, 1)
        return (self._prob_sum[class_idx] / safe,
                self._pos[class_idx] / safe, counts)

    def expected_calibration_error(self, class_idx: Optional[int] = None) -> float:
        """Count-weighted |confidence - accuracy| over bins."""
        idxs = (range(self._counts.shape[0]) if class_idx is None
                else [class_idx])
        total_err = total_n = 0.0
        for c in idxs:
            mean_p, frac, counts = self.get_reliability_info(c)
            total_err += float(np.sum(counts * np.abs(mean_p - frac)))
            total_n += float(counts.sum())
        return total_err / max(total_n, 1.0)

    def get_probability_histogram(self, class_idx: int) -> np.ndarray:
        return self._hist_pred[class_idx].copy()


class ROCMultiClass:
    """One-vs-all ROC per class."""

    def __init__(self):
        self._rocs: Dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        for c in range(labels.shape[1]):
            self._rocs.setdefault(c, ROC()).eval(labels[:, c], preds[:, c])

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs.values()]))


class RegressionEvaluation:
    """Per-column MSE/MAE/RMSE/R²/correlation (reference RegressionEvaluation)."""

    def __init__(self):
        self.n = 0
        self.sum_err2 = None
        self.sum_abs = None
        self.sum_label = None
        self.sum_label2 = None
        self.sum_pred = None
        self.sum_pred2 = None
        self.sum_lp = None

    def eval(self, labels, predictions, mask=None) -> None:
        l = np.asarray(labels, np.float64)
        p = np.asarray(predictions, np.float64)
        if l.ndim == 1:
            l, p = l[:, None], p[:, None]
        err = p - l
        add = lambda cur, v: v if cur is None else cur + v
        self.sum_err2 = add(self.sum_err2, (err ** 2).sum(0))
        self.sum_abs = add(self.sum_abs, np.abs(err).sum(0))
        self.sum_label = add(self.sum_label, l.sum(0))
        self.sum_label2 = add(self.sum_label2, (l ** 2).sum(0))
        self.sum_pred = add(self.sum_pred, p.sum(0))
        self.sum_pred2 = add(self.sum_pred2, (p ** 2).sum(0))
        self.sum_lp = add(self.sum_lp, (l * p).sum(0))
        self.n += l.shape[0]

    def merge(self, other: "RegressionEvaluation") -> "RegressionEvaluation":
        for attr in ("sum_err2", "sum_abs", "sum_label", "sum_label2",
                     "sum_pred", "sum_pred2", "sum_lp"):
            mine, theirs = getattr(self, attr), getattr(other, attr)
            setattr(self, attr, theirs if mine is None else mine + theirs)
        self.n += other.n
        return self

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self.sum_err2[col] / self.n)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self.sum_abs[col] / self.n)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int = 0) -> float:
        ss_tot = self.sum_label2[col] - self.sum_label[col] ** 2 / self.n
        ss_res = self.sum_err2[col]
        return float(1.0 - ss_res / ss_tot) if ss_tot > 0 else 0.0

    def pearson_correlation(self, col: int = 0) -> float:
        cov = self.sum_lp[col] - self.sum_label[col] * self.sum_pred[col] / self.n
        vl = self.sum_label2[col] - self.sum_label[col] ** 2 / self.n
        vp = self.sum_pred2[col] - self.sum_pred[col] ** 2 / self.n
        d = np.sqrt(vl * vp)
        return float(cov / d) if d > 0 else 0.0
