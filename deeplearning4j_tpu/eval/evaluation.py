"""Evaluation suite — streaming, mergeable metrics.

Reference: nd4j-api ``org.nd4j.evaluation.classification.{Evaluation,
EvaluationBinary, ROC, ROCBinary, ROCMultiClass, EvaluationCalibration}`` and
``regression.RegressionEvaluation`` (SURVEY.md §2.1). All accumulate
incrementally over minibatches and merge across workers (the Spark-reducible
contract — here, mergeable across data-parallel hosts).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Evaluation:
    """Multi-class classification metrics over one-hot or index labels."""

    def __init__(self, num_classes: Optional[int] = None, top_n: int = 1):
        self.num_classes = num_classes
        self.top_n = top_n
        self.confusion: Optional[np.ndarray] = None
        self.top_n_correct = 0
        self.count = 0

    # ------------------------------------------------------------------
    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:  # time series [B,T,C] → flatten with mask
            b, t, c = labels.shape
            labels = labels.reshape(-1, c)
            predictions = predictions.reshape(-1, c)
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[m], predictions[m]
        elif mask is not None:  # per-example mask on plain batches
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        if labels.ndim == 2:
            true_idx = labels.argmax(1)
            n_cls = labels.shape[1]
        else:
            true_idx = labels.astype(int)
            n_cls = int(predictions.shape[-1])
        pred_idx = predictions.argmax(1)
        if self.confusion is None:
            self.num_classes = self.num_classes or n_cls
            self.confusion = np.zeros((self.num_classes, self.num_classes), np.int64)
        np.add.at(self.confusion, (true_idx, pred_idx), 1)
        self.count += len(true_idx)
        if self.top_n > 1:
            top = np.argsort(-predictions, axis=1)[:, :self.top_n]
            self.top_n_correct += int((top == true_idx[:, None]).any(1).sum())
        else:
            self.top_n_correct += int((pred_idx == true_idx).sum())

    def merge(self, other: "Evaluation") -> "Evaluation":
        if self.confusion is None:
            self.confusion = other.confusion
            self.num_classes = other.num_classes
        elif other.confusion is not None:
            self.confusion = self.confusion + other.confusion
        self.count += other.count
        self.top_n_correct += other.top_n_correct
        return self

    # --- metrics -------------------------------------------------------
    def accuracy(self) -> float:
        if self.count == 0:
            return 0.0
        return float(np.trace(self.confusion)) / self.count

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / self.count if self.count else 0.0

    def _tp(self) -> np.ndarray:
        return np.diag(self.confusion).astype(np.float64)

    def precision(self, cls: Optional[int] = None) -> float:
        col = self.confusion.sum(0).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(col > 0, self._tp() / col, 0.0)
        return float(per[cls]) if cls is not None else float(per[col > 0].mean() if (col > 0).any() else 0.0)

    def recall(self, cls: Optional[int] = None) -> float:
        row = self.confusion.sum(1).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(row > 0, self._tp() / row, 0.0)
        return float(per[cls]) if cls is not None else float(per[row > 0].mean() if (row > 0).any() else 0.0)

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0

    def matthews_correlation(self) -> float:
        """Binary MCC from the confusion matrix."""
        c = self.confusion
        if c.shape != (2, 2):
            raise ValueError("MCC defined for binary confusion only")
        tn, fp, fn, tp = c[0, 0], c[0, 1], c[1, 0], c[1, 1]
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return float((tp * tn - fp * fn) / denom) if denom > 0 else 0.0

    def stats(self) -> str:
        lines = [
            f"# examples: {self.count}",
            f"Accuracy:  {self.accuracy():.4f}",
            f"Precision: {self.precision():.4f}",
            f"Recall:    {self.recall():.4f}",
            f"F1:        {self.f1():.4f}",
        ]
        if self.top_n > 1:
            lines.append(f"Top-{self.top_n} accuracy: {self.top_n_accuracy():.4f}")
        lines.append("Confusion matrix (rows=actual):")
        lines.append(str(self.confusion))
        return "\n".join(lines)


class EvaluationBinary:
    """Per-output independent binary metrics (multi-label)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.tp = self.fp = self.tn = self.fn = None

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        preds = (np.asarray(predictions) >= self.threshold).astype(np.int64)
        lab = (labels >= 0.5).astype(np.int64)
        if mask is not None:
            m = np.asarray(mask).astype(bool)
        else:
            m = np.ones_like(lab, bool)
        tp = ((preds == 1) & (lab == 1) & m).sum(0)
        fp = ((preds == 1) & (lab == 0) & m).sum(0)
        tn = ((preds == 0) & (lab == 0) & m).sum(0)
        fn = ((preds == 0) & (lab == 1) & m).sum(0)
        if self.tp is None:
            self.tp, self.fp, self.tn, self.fn = tp, fp, tn, fn
        else:
            self.tp += tp
            self.fp += fp
            self.tn += tn
            self.fn += fn

    def merge(self, other: "EvaluationBinary") -> "EvaluationBinary":
        for attr in ("tp", "fp", "tn", "fn"):
            mine, theirs = getattr(self, attr), getattr(other, attr)
            setattr(self, attr, theirs if mine is None else mine + theirs)
        return self

    def accuracy(self, output: int) -> float:
        tot = self.tp[output] + self.fp[output] + self.tn[output] + self.fn[output]
        return float(self.tp[output] + self.tn[output]) / tot if tot else 0.0

    def precision(self, output: int) -> float:
        d = self.tp[output] + self.fp[output]
        return float(self.tp[output]) / d if d else 0.0

    def recall(self, output: int) -> float:
        d = self.tp[output] + self.fn[output]
        return float(self.tp[output]) / d if d else 0.0

    def f1(self, output: int) -> float:
        p, r = self.precision(output), self.recall(output)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0


class ROC:
    """Binary ROC/AUC + precision-recall, exact mode (threshold=0 analog of the
    reference's exact AUC; thresholded mode via `num_thresholds`)."""

    def __init__(self, num_thresholds: int = 0):
        self.num_thresholds = num_thresholds
        self._scores: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        if labels.ndim == 2 and labels.shape[1] == 2:
            labels = labels[:, 1]
            preds = preds[:, 1]
        self._labels.append(labels.ravel())
        self._scores.append(preds.ravel())

    def merge(self, other: "ROC") -> "ROC":
        self._labels.extend(other._labels)
        self._scores.extend(other._scores)
        return self

    def _collect(self):
        return np.concatenate(self._labels), np.concatenate(self._scores)

    def calculate_auc(self) -> float:
        y, s = self._collect()
        order = np.argsort(-s, kind="mergesort")
        y = y[order]
        tps = np.cumsum(y)
        fps = np.cumsum(1 - y)
        p, n = y.sum(), (1 - y).sum()
        if p == 0 or n == 0:
            return 0.0
        tpr = np.concatenate([[0], tps / p])
        fpr = np.concatenate([[0], fps / n])
        return float(np.trapezoid(tpr, fpr))

    def calculate_auprc(self) -> float:
        y, s = self._collect()
        order = np.argsort(-s, kind="mergesort")
        y = y[order]
        tps = np.cumsum(y)
        precision = tps / np.arange(1, len(y) + 1)
        recall = tps / max(y.sum(), 1)
        return float(np.sum(np.diff(np.concatenate([[0], recall])) * precision))


class ROCMultiClass:
    """One-vs-all ROC per class."""

    def __init__(self):
        self._rocs: Dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        for c in range(labels.shape[1]):
            self._rocs.setdefault(c, ROC()).eval(labels[:, c], preds[:, c])

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs.values()]))


class RegressionEvaluation:
    """Per-column MSE/MAE/RMSE/R²/correlation (reference RegressionEvaluation)."""

    def __init__(self):
        self.n = 0
        self.sum_err2 = None
        self.sum_abs = None
        self.sum_label = None
        self.sum_label2 = None
        self.sum_pred = None
        self.sum_pred2 = None
        self.sum_lp = None

    def eval(self, labels, predictions, mask=None) -> None:
        l = np.asarray(labels, np.float64)
        p = np.asarray(predictions, np.float64)
        if l.ndim == 1:
            l, p = l[:, None], p[:, None]
        err = p - l
        add = lambda cur, v: v if cur is None else cur + v
        self.sum_err2 = add(self.sum_err2, (err ** 2).sum(0))
        self.sum_abs = add(self.sum_abs, np.abs(err).sum(0))
        self.sum_label = add(self.sum_label, l.sum(0))
        self.sum_label2 = add(self.sum_label2, (l ** 2).sum(0))
        self.sum_pred = add(self.sum_pred, p.sum(0))
        self.sum_pred2 = add(self.sum_pred2, (p ** 2).sum(0))
        self.sum_lp = add(self.sum_lp, (l * p).sum(0))
        self.n += l.shape[0]

    def merge(self, other: "RegressionEvaluation") -> "RegressionEvaluation":
        for attr in ("sum_err2", "sum_abs", "sum_label", "sum_label2",
                     "sum_pred", "sum_pred2", "sum_lp"):
            mine, theirs = getattr(self, attr), getattr(other, attr)
            setattr(self, attr, theirs if mine is None else mine + theirs)
        self.n += other.n
        return self

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self.sum_err2[col] / self.n)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self.sum_abs[col] / self.n)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int = 0) -> float:
        ss_tot = self.sum_label2[col] - self.sum_label[col] ** 2 / self.n
        ss_res = self.sum_err2[col]
        return float(1.0 - ss_res / ss_tot) if ss_tot > 0 else 0.0

    def pearson_correlation(self, col: int = 0) -> float:
        cov = self.sum_lp[col] - self.sum_label[col] * self.sum_pred[col] / self.n
        vl = self.sum_label2[col] - self.sum_label[col] ** 2 / self.n
        vp = self.sum_pred2[col] - self.sum_pred[col] ** 2 / self.n
        d = np.sqrt(vl * vp)
        return float(cov / d) if d > 0 else 0.0
