"""RNG — stateful shell over jax threefry keys.

Reference: nd4j-api ``org.nd4j.linalg.api.rng.Random`` + libnd4j Philox streams
(libnd4j/include/graph/RandomGenerator.h, helpers/RandomLauncher.h).

Parity note (SURVEY.md §7.3.5): stream parity with the reference is
*statistical*, not bitwise — the reference uses Philox/mt19937, jax uses
threefry. Each draw splits the internal key so repeated calls produce
independent streams, and ``set_seed`` makes a run reproducible.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .ndarray import NDArray


class Random:
    """Stateful random stream. Thread-safe via a lock; one instance per thread
    is handed out by :func:`get_random` (the Nd4j.getRandomFactory() pattern)."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._key = jax.random.PRNGKey(seed)
        self._seed = seed

    def set_seed(self, seed: int) -> None:
        with self._lock:
            self._key = jax.random.PRNGKey(seed)
            self._seed = seed

    def get_seed(self) -> int:
        return self._seed

    def next_key(self) -> jax.Array:
        """Split off a fresh subkey (the primitive everything else uses)."""
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            return sub

    # --- checkpoint/resume support ------------------------------------
    def get_state(self) -> dict:
        """The full stream state. Capturing ``key`` (not just the seed)
        is what makes a resumed run draw the EXACT keys the killed run
        would have drawn next — seed-only restore would replay the stream
        from the beginning (util.checkpoint snapshots this)."""
        with self._lock:
            return {"seed": self._seed, "key": self._key}

    def set_state(self, state: dict) -> None:
        """Restore a :meth:`get_state` snapshot; ``key`` may arrive as a
        jax array, numpy array, or the (list, dtype-string) pair a JSON
        checkpoint round-trip produces."""
        import numpy as np

        key = state["key"]
        if not hasattr(key, "dtype") or not hasattr(key, "shape"):
            key = np.asarray(key, dtype=state.get("key_dtype", "uint32"))
        with self._lock:
            self._key = jnp.asarray(key)
            self._seed = int(state.get("seed", self._seed))

    # --- distribution draws -------------------------------------------
    def uniform(self, shape: Sequence[int], low: float = 0.0, high: float = 1.0,
                dtype=jnp.float32) -> NDArray:
        return NDArray(jax.random.uniform(self.next_key(), tuple(shape), dtype=dtype,
                                          minval=low, maxval=high))

    def gaussian(self, shape: Sequence[int], mean: float = 0.0, std: float = 1.0,
                 dtype=jnp.float32) -> NDArray:
        return NDArray(jax.random.normal(self.next_key(), tuple(shape), dtype=dtype) * std + mean)

    def bernoulli(self, shape: Sequence[int], p: float = 0.5) -> NDArray:
        return NDArray(jax.random.bernoulli(self.next_key(), p, tuple(shape)))

    def binomial(self, shape: Sequence[int], n: int, p: float) -> NDArray:
        draws = jax.random.bernoulli(self.next_key(), p, (n,) + tuple(shape))
        return NDArray(jnp.sum(draws.astype(jnp.int32), axis=0))

    def randint(self, shape: Sequence[int], low: int, high: int) -> NDArray:
        return NDArray(jax.random.randint(self.next_key(), tuple(shape), low, high))

    def permutation(self, n: int) -> NDArray:
        return NDArray(jax.random.permutation(self.next_key(), n))

    def next_gaussian(self) -> float:
        return float(jax.random.normal(self.next_key(), ()))

    def next_double(self) -> float:
        return float(jax.random.uniform(self.next_key(), ()))

    def next_int(self, bound: int) -> int:
        return int(jax.random.randint(self.next_key(), (), 0, bound))


_thread_local = threading.local()
_default_seed = 119  # Nd4j's default seed


def get_random() -> Random:
    """Per-thread Random instance (Nd4j.getRandom() analog)."""
    r = getattr(_thread_local, "random", None)
    if r is None:
        r = Random(_default_seed)
        _thread_local.random = r
    return r


def set_default_seed(seed: int) -> None:
    global _default_seed
    _default_seed = seed
    get_random().set_seed(seed)
