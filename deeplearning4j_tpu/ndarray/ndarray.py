"""NDArray — the INDArray analog, a mutable shell over immutable XLA buffers.

Reference: nd4j-api ``org.nd4j.linalg.api.ndarray.{INDArray, BaseNDArray}``.

Design (SURVEY.md §7.1.3 "functional core, stateful shell"): the engine is pure
jax — device buffers are immutable and every op is traceable — while this class
provides the reference's mutation culture (``addi``/``muli``/``assign``/view
writes) by swapping the underlying buffer. A *view* holds a reference to its
parent plus an index spec; writes to a view recurse up the chain as functional
scatter-updates (``x.at[idx].set``) so ``slice.addi(...)`` alias-updates the
base, matching BaseNDArray view semantics without host round-trips.

Divergence from the reference (documented, deliberate): ``reshape``/``permute``
return fresh arrays rather than stride-tricked views — XLA has no user-visible
strides, and write-through reshaped views are not supported. All other view
writes (slicing, ``get``, ``slice()``, ``tensor_along_dimension``) alias.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..common.dtypes import DataType

IndexSpec = Union[int, slice, Tuple[Any, ...]]


def _as_jax(value) -> jax.Array:
    if isinstance(value, NDArray):
        return value.value
    return jnp.asarray(value)


def _normalize_shape(shape) -> Tuple[int, ...]:
    """Accept both f(2, 3) and f((2, 3)) varargs-shape call styles."""
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        return tuple(shape[0])
    return tuple(shape)


def _clean_idx(idx):
    """Unwrap NDArray (fancy/boolean) indices to raw jax arrays."""
    if isinstance(idx, NDArray):
        return idx.value
    if isinstance(idx, tuple):
        return tuple(i.value if isinstance(i, NDArray) else i for i in idx)
    return idx


class NDArray:
    """Mutable tensor handle. ``.value`` is the current immutable jax buffer."""

    __slots__ = ("_value", "_base", "_idx")

    def __init__(self, value, base: Optional["NDArray"] = None, idx: Optional[IndexSpec] = None):
        self._base = base
        self._idx = idx
        self._value = None if base is not None else jnp.asarray(value)

    # --- buffer access -------------------------------------------------
    @property
    def value(self) -> jax.Array:
        if self._base is not None:
            return self._base.value[self._idx]
        return self._value

    def _set_value(self, new: jax.Array) -> None:
        if self._base is not None:
            self._base._write(self._idx, new)
        else:
            self._value = new

    def _write(self, idx: IndexSpec, new: jax.Array) -> None:
        if self._base is not None:
            cur = self.value
            self._base._write(self._idx, cur.at[idx].set(new))
        else:
            self._value = self._value.at[idx].set(new)

    @property
    def is_view(self) -> bool:
        return self._base is not None

    # --- metadata ------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.value.shape)

    @property
    def rank(self) -> int:
        return self.value.ndim

    @property
    def ndim(self) -> int:
        return self.value.ndim

    def length(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    def size(self, dim: int) -> int:
        return self.shape[dim]

    def data_type(self) -> DataType:
        return DataType.from_np(self.value.dtype)

    @property
    def dtype(self):
        return self.value.dtype

    def is_scalar(self) -> bool:
        return self.value.ndim == 0 or self.length() == 1

    def is_vector(self) -> bool:
        return self.rank == 1 or (self.rank == 2 and 1 in self.shape)

    def is_matrix(self) -> bool:
        return self.rank == 2

    def rows(self) -> int:
        return self.shape[0]

    def columns(self) -> int:
        return self.shape[1]

    # --- conversion ----------------------------------------------------
    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.value)

    def dup(self) -> "NDArray":
        return NDArray(self.value)

    def cast(self, dtype: Union[DataType, Any]) -> "NDArray":
        np_dt = dtype.to_np() if isinstance(dtype, DataType) else np.dtype(dtype)
        return NDArray(self.value.astype(np_dt))

    def astype(self, dtype) -> "NDArray":
        return self.cast(dtype)

    # --- scalar access -------------------------------------------------
    def get_double(self, *indices: int) -> float:
        return float(self.value[tuple(indices)] if indices else self.value)

    def get_int(self, *indices: int) -> int:
        return int(self.value[tuple(indices)] if indices else self.value)

    def get_scalar(self, *indices: int) -> "NDArray":
        return NDArray(self.value[tuple(indices)])

    def put_scalar(self, indices, value) -> "NDArray":
        if isinstance(indices, int):
            indices = (indices,)
        self._write(tuple(indices), jnp.asarray(value, dtype=self.dtype))
        return self

    # --- views ---------------------------------------------------------
    def __getitem__(self, idx) -> "NDArray":
        return NDArray(None, base=self, idx=_clean_idx(idx))

    def __setitem__(self, idx, value) -> None:
        self._write(_clean_idx(idx), jnp.asarray(_as_jax(value), dtype=self.dtype))

    def get(self, idx) -> "NDArray":
        """View via index (INDArray.get(INDArrayIndex...) analog)."""
        return self[idx]

    def slice_view(self, i: int, dim: int = 0) -> "NDArray":
        idx = tuple([slice(None)] * dim + [i])
        return self[idx]

    def tensor_along_dimension(self, index: int, *dims: int) -> "NDArray":
        """TAD analog: the index-th subtensor spanning `dims`."""
        dims = tuple(d % self.rank for d in dims)
        other = [d for d in range(self.rank) if d not in dims]
        counts = [self.shape[d] for d in other]
        sub = np.unravel_index(index, counts) if counts else ()
        idx: list = [slice(None)] * self.rank
        for d, i in zip(other, sub):
            idx[d] = int(i)
        return self[tuple(idx)]

    def assign(self, other) -> "NDArray":
        new = jnp.broadcast_to(jnp.asarray(_as_jax(other), dtype=self.dtype), self.shape)
        self._set_value(new)
        return self

    # --- shape ops (fresh arrays; see module docstring) ----------------
    def reshape(self, *shape) -> "NDArray":
        return NDArray(self.value.reshape(_normalize_shape(shape)))

    def ravel(self) -> "NDArray":
        return NDArray(self.value.ravel())

    def permute(self, *dims) -> "NDArray":
        return NDArray(jnp.transpose(self.value, _normalize_shape(dims)))

    def transpose(self) -> "NDArray":
        return NDArray(self.value.T)

    @property
    def T(self) -> "NDArray":
        return self.transpose()

    def broadcast(self, *shape) -> "NDArray":
        return NDArray(jnp.broadcast_to(self.value, _normalize_shape(shape)))

    def repeat(self, repeats: int, axis: int) -> "NDArray":
        return NDArray(jnp.repeat(self.value, repeats, axis=axis))

    # --- arithmetic: pure ----------------------------------------------
    def add(self, other) -> "NDArray":
        return NDArray(self.value + _as_jax(other))

    def sub(self, other) -> "NDArray":
        return NDArray(self.value - _as_jax(other))

    def mul(self, other) -> "NDArray":
        return NDArray(self.value * _as_jax(other))

    def div(self, other) -> "NDArray":
        return NDArray(self.value / _as_jax(other))

    def rsub(self, other) -> "NDArray":
        return NDArray(_as_jax(other) - self.value)

    def rdiv(self, other) -> "NDArray":
        return NDArray(_as_jax(other) / self.value)

    def neg(self) -> "NDArray":
        return NDArray(-self.value)

    def mmul(self, other) -> "NDArray":
        return NDArray(self.value @ _as_jax(other))

    # --- arithmetic: in-place (the DL4J `i` suffix family) -------------
    def addi(self, other) -> "NDArray":
        self._set_value(jnp.asarray(self.value + _as_jax(other), dtype=self.dtype))
        return self

    def subi(self, other) -> "NDArray":
        self._set_value(jnp.asarray(self.value - _as_jax(other), dtype=self.dtype))
        return self

    def muli(self, other) -> "NDArray":
        self._set_value(jnp.asarray(self.value * _as_jax(other), dtype=self.dtype))
        return self

    def divi(self, other) -> "NDArray":
        self._set_value(jnp.asarray(self.value / _as_jax(other), dtype=self.dtype))
        return self

    def rsubi(self, other) -> "NDArray":
        self._set_value(jnp.asarray(_as_jax(other) - self.value, dtype=self.dtype))
        return self

    def rdivi(self, other) -> "NDArray":
        self._set_value(jnp.asarray(_as_jax(other) / self.value, dtype=self.dtype))
        return self

    def negi(self) -> "NDArray":
        self._set_value(-self.value)
        return self

    # --- python operators ----------------------------------------------
    __add__ = add
    __sub__ = sub
    __mul__ = mul
    __truediv__ = div
    __matmul__ = mmul
    __neg__ = neg

    def __radd__(self, other):
        return NDArray(_as_jax(other) + self.value)

    def __rsub__(self, other):
        return self.rsub(other)

    def __rmul__(self, other):
        return NDArray(_as_jax(other) * self.value)

    def __rtruediv__(self, other):
        return self.rdiv(other)

    def __pow__(self, p):
        return NDArray(self.value ** p)

    def __lt__(self, other):
        return NDArray(self.value < _as_jax(other))

    def __le__(self, other):
        return NDArray(self.value <= _as_jax(other))

    def __gt__(self, other):
        return NDArray(self.value > _as_jax(other))

    def __ge__(self, other):
        return NDArray(self.value >= _as_jax(other))

    def eq(self, other):
        return NDArray(self.value == _as_jax(other))

    def neq(self, other):
        return NDArray(self.value != _as_jax(other))

    # Elementwise like numpy — NDArray is consequently unhashable.
    __eq__ = eq
    __ne__ = neq
    __hash__ = None

    # --- reductions ----------------------------------------------------
    def sum(self, *dims, keepdims: bool = False) -> "NDArray":
        return NDArray(jnp.sum(self.value, axis=dims or None, keepdims=keepdims))

    def mean(self, *dims, keepdims: bool = False) -> "NDArray":
        return NDArray(jnp.mean(self.value, axis=dims or None, keepdims=keepdims))

    def std(self, *dims, keepdims: bool = False, bias_corrected: bool = True) -> "NDArray":
        ddof = 1 if bias_corrected else 0
        return NDArray(jnp.std(self.value, axis=dims or None, keepdims=keepdims, ddof=ddof))

    def var(self, *dims, keepdims: bool = False, bias_corrected: bool = True) -> "NDArray":
        ddof = 1 if bias_corrected else 0
        return NDArray(jnp.var(self.value, axis=dims or None, keepdims=keepdims, ddof=ddof))

    def max(self, *dims, keepdims: bool = False) -> "NDArray":
        return NDArray(jnp.max(self.value, axis=dims or None, keepdims=keepdims))

    def min(self, *dims, keepdims: bool = False) -> "NDArray":
        return NDArray(jnp.min(self.value, axis=dims or None, keepdims=keepdims))

    def prod(self, *dims, keepdims: bool = False) -> "NDArray":
        return NDArray(jnp.prod(self.value, axis=dims or None, keepdims=keepdims))

    def argmax(self, *dims) -> "NDArray":
        return NDArray(jnp.argmax(self.value, axis=dims[0] if dims else None))

    def argmin(self, *dims) -> "NDArray":
        return NDArray(jnp.argmin(self.value, axis=dims[0] if dims else None))

    def cumsum(self, dim: int = 0) -> "NDArray":
        return NDArray(jnp.cumsum(self.value, axis=dim))

    def norm1(self, *dims) -> "NDArray":
        return NDArray(jnp.sum(jnp.abs(self.value), axis=dims or None))

    def norm2(self, *dims) -> "NDArray":
        return NDArray(jnp.sqrt(jnp.sum(jnp.square(self.value), axis=dims or None)))

    def norm_max(self, *dims) -> "NDArray":
        return NDArray(jnp.max(jnp.abs(self.value), axis=dims or None))

    # --- comparisons ----------------------------------------------------
    def equals_to(self, other, eps: float = 1e-5) -> bool:
        other_v = _as_jax(other)
        if tuple(other_v.shape) != self.shape:
            return False
        # f64 comparison so DOUBLE/INT64 values beyond f32 precision don't
        # collapse to false equality (x64 is enabled at package import).
        cmp_dt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        return bool(jnp.all(jnp.abs(self.value.astype(cmp_dt) - other_v.astype(cmp_dt)) <= eps))

    def __repr__(self) -> str:
        return f"NDArray(shape={self.shape}, dtype={self.value.dtype}, view={self.is_view})\n{np.asarray(self.value)}"

    def __len__(self) -> int:
        return self.shape[0]

    # jax interop: NDArray can be passed straight into jnp functions.
    def __jax_array__(self) -> jax.Array:
        return self.value

    def __array__(self, dtype=None) -> np.ndarray:
        a = np.asarray(self.value)
        return a.astype(dtype) if dtype is not None else a
