"""Array factory — the ``Nd4j`` static-factory analog.

Reference: nd4j-api ``org.nd4j.linalg.factory.Nd4j`` (create/zeros/ones/rand/
randn/arange/linspace/valueArrayOf/eye/concat/stack/...). Backed directly by
jnp; every produced buffer lives on the default jax device (HBM on TPU).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..common.dtypes import DataType
from ..common.environment import Environment
from .ndarray import NDArray, _as_jax, _normalize_shape
from .rng import get_random


def _np_dtype(dtype) -> Any:
    if dtype is None:
        return np.dtype(Environment.get().default_dtype())
    if isinstance(dtype, DataType):
        return dtype.to_np()
    return np.dtype(dtype) if not hasattr(dtype, "dtype") else dtype


def create(data=None, shape: Optional[Sequence[int]] = None, dtype=None) -> NDArray:
    """Nd4j.create analog: from data, or zero-filled by shape."""
    dt = _np_dtype(dtype)
    if data is None:
        if shape is None:
            raise ValueError("create() needs data or shape")
        return NDArray(jnp.zeros(tuple(shape), dtype=dt))
    arr = jnp.asarray(np.asarray(data, dtype=dt))
    if shape is not None:
        arr = arr.reshape(tuple(shape))
    return NDArray(arr)


def zeros(*shape, dtype=None) -> NDArray:
    return NDArray(jnp.zeros(_normalize_shape(shape), dtype=_np_dtype(dtype)))


def ones(*shape, dtype=None) -> NDArray:
    return NDArray(jnp.ones(_normalize_shape(shape), dtype=_np_dtype(dtype)))


def zeros_like(arr) -> NDArray:
    return NDArray(jnp.zeros_like(_as_jax(arr)))


def ones_like(arr) -> NDArray:
    return NDArray(jnp.ones_like(_as_jax(arr)))


def value_array_of(shape: Sequence[int], value, dtype=None) -> NDArray:
    return NDArray(jnp.full(tuple(shape), value, dtype=_np_dtype(dtype)))


def scalar(value, dtype=None) -> NDArray:
    return NDArray(jnp.asarray(value, dtype=_np_dtype(dtype)))


def eye(n: int, dtype=None) -> NDArray:
    return NDArray(jnp.eye(n, dtype=_np_dtype(dtype)))


def arange(*args, dtype=None) -> NDArray:
    return NDArray(jnp.arange(*args, dtype=_np_dtype(dtype)))


def linspace(start, stop, num: int, dtype=None) -> NDArray:
    return NDArray(jnp.linspace(start, stop, num, dtype=_np_dtype(dtype)))


def rand(*shape, dtype=None) -> NDArray:
    return get_random().uniform(_normalize_shape(shape), dtype=_np_dtype(dtype))


def randn(*shape, dtype=None) -> NDArray:
    return get_random().gaussian(_normalize_shape(shape), dtype=_np_dtype(dtype))


def concat(dim: int, *arrays) -> NDArray:
    return NDArray(jnp.concatenate([_as_jax(a) for a in arrays], axis=dim))


def stack(dim: int, *arrays) -> NDArray:
    return NDArray(jnp.stack([_as_jax(a) for a in arrays], axis=dim))


def hstack(*arrays) -> NDArray:
    return concat(-1, *arrays)


def vstack(*arrays) -> NDArray:
    return concat(0, *arrays)


def tile(arr, *reps) -> NDArray:
    return NDArray(jnp.tile(_as_jax(arr), _normalize_shape(reps)))


def where(cond, x, y) -> NDArray:
    return NDArray(jnp.where(_as_jax(cond), _as_jax(x), _as_jax(y)))


def sort(arr, dim: int = -1, descending: bool = False) -> NDArray:
    s = jnp.sort(_as_jax(arr), axis=dim)
    if descending:
        s = jnp.flip(s, axis=dim)
    return NDArray(s)


def gemm(a, b, transpose_a: bool = False, transpose_b: bool = False,
         alpha: float = 1.0, beta: float = 0.0, c=None) -> NDArray:
    """BLAS gemm analog (reference MmulHelper) — rides the MXU via dot."""
    av, bv = _as_jax(a), _as_jax(b)
    if transpose_a:
        av = av.T
    if transpose_b:
        bv = bv.T
    out = alpha * (av @ bv)
    if c is not None and beta != 0.0:
        out = out + beta * _as_jax(c)
    return NDArray(out)


def matmul(a, b) -> NDArray:
    return NDArray(_as_jax(a) @ _as_jax(b))


def write(arr: NDArray, path: str) -> None:
    """Nd4j.write analog — raw npy container."""
    np.save(path, arr.to_numpy())


def read(path: str) -> NDArray:
    return NDArray(jnp.asarray(np.load(path)))
