"""Mixed-precision policy for the training hot path.

One module owns every dtype-boundary rule in the stack (the fp32-boundary
doc the serving tier and the trainer used to state separately):

- **Compute** may run in bfloat16 (``GlobalConf.compute_dtype``): params and
  activations cast down for the MXU, loss head and reductions in float32,
  gradients flow back to fp32 master params. (Implemented by the models;
  this module is the shared cast helper.)
- **Inference params** may be served in bfloat16
  (``ServingEngine.Builder.bf16``): one cast at startup, float32 at the API
  boundary. :func:`cast_floating` here is THE cast both sides use.
- **Updater state** may be *stored* in bfloat16
  (``updater.state_dtype = "bfloat16"``): moments live in bf16 (half the
  optimizer HBM; under ZeRO-1 half of the already-1/N per-replica
  footprint), the update math still runs in float32 (:func:`apply_updater`
  upcasts, applies the untouched fp32 updater, and writes the new moments
  back down with **stochastic rounding** driven by the step's existing RNG
  stream), so the parameter update itself never sees bf16 arithmetic.

Why stochastic rounding: deterministic round-to-nearest of a bf16
accumulator loses every increment smaller than ~2^-8 of the stored value —
an EMA like Adam's second moment simply stops moving once
``(1-beta2)*g^2`` drops below the rounding ulp. Rounding *stochastically*
(up with probability proportional to the dropped fraction) makes the
stored moment an unbiased estimator of the fp32 one: E[SR(x)] == x, so
the error is zero-mean noise instead of a systematic stall
(tests/test_precision.py pins the unbiasedness).

Documented numerics envelope (pinned by tests and the ``mfu-smoke``
bench): with ``state_dtype="bfloat16"`` the per-step training loss tracks
the fp32-state run within ``|Δ| <= 1e-3 + 0.05 * |loss|`` over the smoke
horizon. Parameters stay fp32; their trajectories accumulate the
zero-mean rounding noise and so wander apart chaotically rather than
tracking element-wise — measured ≲1e-2 absolute over the smoke horizon,
gated as gross-divergence-only (``0.01 + 0.1*|p|``). The fp32-state path
is bit-identical to the per-leaf reference — ``state_dtype=None``
changes NOTHING.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..common.profiler import OpProfiler

Pytree = Any

# fold_in tags deriving the stochastic-rounding stream from the step key —
# distinct from the dropout splits (which use jax.random.split) and from
# each other, so no RNG draw is ever consumed twice
SR_STREAM_TAG = 0x5AD0


def cast_floating(tree: Pytree, dtype) -> Pytree:
    """Cast every floating leaf of ``tree`` to ``dtype`` (round-to-nearest),
    leaving integer/bool leaves untouched. THE shared fp32-boundary cast:
    serving's bf16 inference params and the trainer's updater-state
    up/down casts all route through here."""
    dt = jnp.dtype(dtype)

    def c(a):
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
            return jnp.asarray(a, dt)
        return a

    return jax.tree.map(c, tree)


def stochastic_round(x, rbits, dtype=jnp.bfloat16):
    """float32 ``x`` → ``dtype`` (bfloat16) with stochastic rounding.

    ``rbits``: uint32 random bits, same shape as ``x`` — only the LOW 16
    bits are consumed (callers holding one uint32 draw per element can
    spend the high halfword on a second tensor; see
    :func:`ops.pallas_update.fused_apply`).

    Mechanics: bf16 is the top 16 bits of the fp32 pattern, and for a
    fixed exponent the 2^16 droppable mantissa patterns are equidistant —
    adding a uniform 16-bit integer to the fp32 bits and truncating
    therefore rounds up with probability exactly (dropped bits)/2^16:
    E[SR(x)] == x. Carries propagate into the exponent correctly (IEEE
    ordering), overflow past the largest finite value rounds to ±inf (the
    round-up neighbor), and non-finite inputs pass through untouched.

    Pure jnp/lax elementwise — traces identically into XLA and into a
    Pallas kernel body, so the fused and unfused paths agree bit-for-bit
    given the same ``rbits``.
    """
    if jnp.dtype(dtype) != jnp.bfloat16:
        raise NotImplementedError(
            f"stochastic rounding targets bfloat16 (top half of the fp32 "
            f"pattern); got {dtype}")
    x32 = x.astype(jnp.float32)
    u = lax.bitcast_convert_type(x32, jnp.uint32)
    u = u + (rbits.astype(jnp.uint32) & jnp.uint32(0xFFFF))
    u = u & jnp.uint32(0xFFFF0000)
    rounded = lax.bitcast_convert_type(u, jnp.float32).astype(jnp.bfloat16)
    return jnp.where(jnp.isfinite(x32), rounded, x32.astype(jnp.bfloat16))


def random_bits_for(key, shape) -> jnp.ndarray:
    """One uint32 of randomness per element, counted in the profiler's
    ``precision/sr_draws`` ledger. The counter bumps at TRACE time (the
    Python body only runs while jax traces), so it records the draws
    baked into one compiled step — the per-execution draw count of every
    step that executable runs."""
    n = 1
    for d in shape:
        n *= int(d)
    OpProfiler.get().count("precision/sr_draws", n)
    return jax.random.bits(key, shape, dtype=jnp.uint32)


def sr_cast_state(state: Pytree, dtype, key) -> Pytree:
    """Stochastically round every floating leaf of an (fp32) updater-state
    tree down to ``dtype``, each leaf on its own fold_in-derived stream."""
    leaves, treedef = jax.tree.flatten(state)
    out = []
    for i, leaf in enumerate(leaves):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            sub = jax.random.fold_in(key, i)
            bits = random_bits_for(sub, leaf.shape)
            out.append(stochastic_round(leaf, bits, dtype))
        else:
            out.append(leaf)
    return jax.tree.unflatten(treedef, out)


def state_dtype_of(updater) -> Optional[str]:
    """The configured low-precision state dtype, or None for fp32."""
    sd = getattr(updater, "state_dtype", None)
    return str(jnp.dtype(sd)) if sd else None


def apply_updater(updater, grads, state, params, iteration, key=None):
    """THE updater dispatch every step core routes through.

    fp32 state (``state_dtype`` unset): exactly ``updater.apply`` —
    bit-identical to the historical path. Low-precision state: upcast the
    stored moments to float32, run the unmodified fp32 updater math, and
    stochastically round the NEW moments back down using ``key`` (the
    step's RNG stream, fold_in-tagged so dropout draws are untouched).
    Parameters stay fp32 throughout — only the stored state narrows.
    """
    sd = state_dtype_of(updater)
    if not sd:
        return updater.apply(grads, state, params, iteration)
    if key is None:
        raise ValueError(
            f"{type(updater).__name__}(state_dtype={sd!r}) needs the step "
            "RNG key for stochastic rounding — this fit path does not "
            "thread one; unset state_dtype or use a pipeline fit")
    wide = cast_floating(state, jnp.float32)
    new_params, new_state = updater.apply(grads, wide, params, iteration)
    sr_key = jax.random.fold_in(key, SR_STREAM_TAG)
    new_state = sr_cast_state(new_state, jnp.dtype(sd), sr_key)
    return new_params, new_state


def updater_state_bytes(state) -> Dict[str, int]:
    """Host-side footprint ledger: total bytes per leaf dtype (plus
    ``total``). Empty dict for stateless updaters."""
    out: Dict[str, int] = {}
    for leaf in jax.tree.leaves(state or {}):
        n = int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
        k = str(jnp.dtype(leaf.dtype))
        out[k] = out.get(k, 0) + n
    if out:
        out["total"] = sum(out.values())
    return out


def note_state_bytes(state, prefix: str = "precision") -> None:
    """Record the live updater-state footprint as profiler gauges
    (``precision/updater_state_bytes_<dtype>`` + ``..._total``) — the
    ``precision_stats()`` /api/health view of what the state actually
    costs. Level quantities: gauges, not counters."""
    prof = OpProfiler.get()
    fresh = updater_state_bytes(state)
    for k in list(prof.get_counters()):
        # zero out stale per-dtype gauges from a previous state layout
        # (the dtype SET changes when state_dtype flips)
        if k.startswith(f"{prefix}/updater_state_bytes_") \
                and k[len(prefix) + len("/updater_state_bytes_"):] \
                not in fresh:
            prof.gauge(k, 0)
    for k, v in fresh.items():
        prof.gauge(f"{prefix}/updater_state_bytes_{k}", v)
