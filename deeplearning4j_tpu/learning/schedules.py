"""Learning-rate schedules.

Reference: nd4j-api ``org.nd4j.linalg.schedule.{ISchedule, StepSchedule,
ExponentialSchedule, PolySchedule, InverseSchedule, SigmoidSchedule,
CycleSchedule, FixedSchedule}``. Schedules are pure functions of the iteration
counter so they trace cleanly into the compiled train step.
"""

from __future__ import annotations

import math
from dataclasses import dataclass


class ISchedule:
    def value_at(self, iteration, epoch: int = 0):
        raise NotImplementedError

    def __call__(self, iteration, epoch: int = 0):
        return self.value_at(iteration, epoch)


@dataclass
class FixedSchedule(ISchedule):
    value: float

    def value_at(self, iteration, epoch: int = 0):
        return self.value


@dataclass
class StepSchedule(ISchedule):
    """lr * decay_rate^floor(iter / step)"""

    initial_value: float
    decay_rate: float
    step: float

    def value_at(self, iteration, epoch: int = 0):
        import jax.numpy as jnp

        return self.initial_value * self.decay_rate ** jnp.floor(iteration / self.step)


@dataclass
class ExponentialSchedule(ISchedule):
    initial_value: float
    gamma: float

    def value_at(self, iteration, epoch: int = 0):
        return self.initial_value * self.gamma ** iteration


@dataclass
class PolySchedule(ISchedule):
    initial_value: float
    power: float
    max_iter: int

    def value_at(self, iteration, epoch: int = 0):
        import jax.numpy as jnp

        frac = jnp.minimum(iteration / self.max_iter, 1.0)
        return self.initial_value * (1.0 - frac) ** self.power


@dataclass
class InverseSchedule(ISchedule):
    initial_value: float
    gamma: float
    power: float

    def value_at(self, iteration, epoch: int = 0):
        return self.initial_value / (1.0 + self.gamma * iteration) ** self.power


@dataclass
class SigmoidSchedule(ISchedule):
    initial_value: float
    gamma: float
    step_size: int

    def value_at(self, iteration, epoch: int = 0):
        import jax.numpy as jnp

        return self.initial_value / (1.0 + jnp.exp(self.gamma * (iteration - self.step_size)))


@dataclass
class CycleSchedule(ISchedule):
    """1cycle-style: ramp up to max, back down, then annihilate."""

    initial_value: float
    max_value: float
    cycle_length: int
    annealing_cycles: float = 0.1

    def value_at(self, iteration, epoch: int = 0):
        import jax.numpy as jnp

        up = self.cycle_length * (1.0 - self.annealing_cycles) / 2.0
        pos = iteration % self.cycle_length
        ramp_up = self.initial_value + (self.max_value - self.initial_value) * (pos / up)
        ramp_down = self.max_value - (self.max_value - self.initial_value) * ((pos - up) / up)
        anneal_start = 2 * up
        anneal = self.initial_value * (1.0 - (pos - anneal_start) /
                                       jnp.maximum(self.cycle_length - anneal_start, 1.0))
        return jnp.where(pos < up, ramp_up, jnp.where(pos < anneal_start, ramp_down, anneal))
