"""Gradient updaters (optimizers).

Reference: nd4j-api ``org.nd4j.linalg.learning.config.{Sgd,Adam,AdamW,
Nesterovs,AdaGrad,AdaDelta,AdaMax,Nadam,AMSGrad,RmsProp,NoOp}`` + the stateful
``GradientUpdater`` impls that call fused native updater kernels
(``ops.impl.updaters.*``). Here each updater is a pure pytree transform —
``init(params) -> state`` and ``apply(grads, state, params, iteration) ->
(new_params, new_state)`` — that fuses into the compiled train step, which is
exactly what the reference's fused native updater ops were approximating.

Default hyperparameters match the reference config classes (e.g. Adam lr=1e-3,
beta1=0.9, beta2=0.999, eps=1e-8; Nesterovs momentum=0.9; RmsProp decay=0.95).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp

from .schedules import ISchedule

Pytree = Any


def _lr_at(lr: Union[float, ISchedule], iteration):
    if isinstance(lr, ISchedule):
        return lr.value_at(iteration)
    return lr


class GradientUpdater:
    """Base: stateless config; state is an explicit pytree.

    ``elementwise``: the updater computes each parameter element from only
    that element's own gradient/state (plus scalar hyperparameters), so
    applying it to any PERMUTATION or SLICE of the flattened parameter
    vector is bit-identical to applying it leaf-by-leaf. That property is
    what lets ``ParallelWrapper``'s ZeRO-1 path
    (``ReduceScatterAccumulator``) run the updater on each replica's flat
    1/N shard with sharded state. Every built-in sets it True explicitly;
    the BASE default is False so a custom updater that couples elements
    within a leaf (global-norm clipping, whitening, ...) is refused by the
    sharded path unless its author opts in — never silently diverged
    from the dense math.

    ``state_dtype`` (opt-in, e.g. ``"bfloat16"``): STORE the updater
    state (moments) in this dtype instead of the params'. The update math
    still runs in float32 — ``learning.precision.apply_updater`` upcasts,
    calls the unchanged ``apply``, and writes the new moments back down
    with stochastic rounding on the step's RNG stream. Halves optimizer
    HBM (and halves ZeRO-1's per-replica state again); numerics envelope
    documented in ``learning/precision.py``. ``apply`` itself NEVER
    consumes the field — handing it bf16 state directly just widens
    through jnp promotion, so always go through ``apply_updater``."""

    learning_rate: Union[float, ISchedule]
    elementwise: bool = False
    state_dtype: Optional[str] = None

    def init(self, params: Pytree) -> Pytree:
        return {}

    def _zeros_like(self, params: Pytree) -> Pytree:
        """Fresh state mirroring ``params`` — in ``state_dtype`` when set
        (zeros are exactly representable, so low-precision init equals
        round(fp32 init) bit-for-bit)."""
        if not self.state_dtype:
            return jax.tree.map(jnp.zeros_like, params)
        dt = jnp.dtype(self.state_dtype)

        def z(p):
            if jnp.issubdtype(p.dtype, jnp.floating):
                return jnp.zeros(p.shape, dt)
            return jnp.zeros_like(p)

        return jax.tree.map(z, params)

    def apply(self, grads: Pytree, state: Pytree, params: Pytree, iteration):
        raise NotImplementedError

    # alias used by the training sessions
    def update(self, grads, state, params, iteration):
        return self.apply(grads, state, params, iteration)


@dataclass
class Sgd(GradientUpdater):
    elementwise = True
    learning_rate: Union[float, ISchedule] = 1e-1

    def apply(self, grads, state, params, iteration):
        lr = _lr_at(self.learning_rate, iteration)
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, state


@dataclass
class NoOp(GradientUpdater):
    elementwise = True
    learning_rate: Union[float, ISchedule] = 0.0

    def apply(self, grads, state, params, iteration):
        return params, state


@dataclass
class Nesterovs(GradientUpdater):
    elementwise = True
    learning_rate: Union[float, ISchedule] = 0.1
    momentum: float = 0.9

    def init(self, params):
        return {"v": self._zeros_like(params)}

    def apply(self, grads, state, params, iteration):
        lr = _lr_at(self.learning_rate, iteration)
        mu = self.momentum
        # reference Nesterovs: vPrev = v; v = mu*v - lr*g; p += -mu*vPrev + (1+mu)*v
        def upd(p, g, v):
            v_new = mu * v - lr * g
            p_new = p + (-mu * v + (1.0 + mu) * v_new)
            return p_new, v_new

        flat = jax.tree.map(upd, params, grads, state["v"])
        new_params = jax.tree.map(lambda t: t[0], flat, is_leaf=lambda t: isinstance(t, tuple))
        new_v = jax.tree.map(lambda t: t[1], flat, is_leaf=lambda t: isinstance(t, tuple))
        return new_params, {"v": new_v}


@dataclass
class AdaGrad(GradientUpdater):
    elementwise = True
    learning_rate: Union[float, ISchedule] = 1e-1
    epsilon: float = 1e-6

    def init(self, params):
        return {"h": self._zeros_like(params)}

    def apply(self, grads, state, params, iteration):
        lr = _lr_at(self.learning_rate, iteration)

        def upd(p, g, h):
            h_new = h + jnp.square(g)
            p_new = p - lr * g / (jnp.sqrt(h_new) + self.epsilon)
            return p_new, h_new

        flat = jax.tree.map(upd, params, grads, state["h"])
        return (jax.tree.map(lambda t: t[0], flat, is_leaf=lambda t: isinstance(t, tuple)),
                {"h": jax.tree.map(lambda t: t[1], flat, is_leaf=lambda t: isinstance(t, tuple))})


@dataclass
class AdaDelta(GradientUpdater):
    elementwise = True
    rho: float = 0.95
    epsilon: float = 1e-6
    learning_rate: Union[float, ISchedule] = 1.0  # AdaDelta is LR-free

    def init(self, params):
        return {"msg": self._zeros_like(params),
                "msdx": self._zeros_like(params)}

    def apply(self, grads, state, params, iteration):
        rho, eps = self.rho, self.epsilon

        def upd(p, g, msg, msdx):
            msg_new = rho * msg + (1 - rho) * jnp.square(g)
            dx = -jnp.sqrt(msdx + eps) / jnp.sqrt(msg_new + eps) * g
            msdx_new = rho * msdx + (1 - rho) * jnp.square(dx)
            return p + dx, msg_new, msdx_new

        flat = jax.tree.map(upd, params, grads, state["msg"], state["msdx"])
        pick = lambda i: jax.tree.map(lambda t: t[i], flat, is_leaf=lambda t: isinstance(t, tuple))
        return pick(0), {"msg": pick(1), "msdx": pick(2)}


@dataclass
class RmsProp(GradientUpdater):
    elementwise = True
    learning_rate: Union[float, ISchedule] = 1e-1
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def init(self, params):
        return {"g2": self._zeros_like(params)}

    def apply(self, grads, state, params, iteration):
        lr = _lr_at(self.learning_rate, iteration)
        d = self.rms_decay

        def upd(p, g, g2):
            g2_new = d * g2 + (1 - d) * jnp.square(g)
            return p - lr * g / (jnp.sqrt(g2_new) + self.epsilon), g2_new

        flat = jax.tree.map(upd, params, grads, state["g2"])
        pick = lambda i: jax.tree.map(lambda t: t[i], flat, is_leaf=lambda t: isinstance(t, tuple))
        return pick(0), {"g2": pick(1)}


@dataclass
class Adam(GradientUpdater):
    elementwise = True
    learning_rate: Union[float, ISchedule] = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init(self, params):
        return {"m": self._zeros_like(params),
                "v": self._zeros_like(params)}

    def _moments(self, g, m, v):
        m_new = self.beta1 * m + (1 - self.beta1) * g
        v_new = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        return m_new, v_new

    def apply(self, grads, state, params, iteration):
        lr = _lr_at(self.learning_rate, iteration)
        t = iteration + 1
        bc1 = 1 - self.beta1 ** t
        bc2 = 1 - self.beta2 ** t

        def upd(p, g, m, v):
            m_new, v_new = self._moments(g, m, v)
            step = lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.epsilon)
            return p - step, m_new, v_new

        flat = jax.tree.map(upd, params, grads, state["m"], state["v"])
        pick = lambda i: jax.tree.map(lambda t_: t_[i], flat, is_leaf=lambda t_: isinstance(t_, tuple))
        return pick(0), {"m": pick(1), "v": pick(2)}


@dataclass
class AdamW(Adam):
    """Adam with decoupled weight decay (reference AdamW semantics)."""

    weight_decay: float = 1e-2

    def apply(self, grads, state, params, iteration):
        lr = _lr_at(self.learning_rate, iteration)
        t = iteration + 1
        bc1 = 1 - self.beta1 ** t
        bc2 = 1 - self.beta2 ** t

        def upd(p, g, m, v):
            m_new, v_new = self._moments(g, m, v)
            step = lr * ((m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.epsilon)
                         + self.weight_decay * p)
            return p - step, m_new, v_new

        flat = jax.tree.map(upd, params, grads, state["m"], state["v"])
        pick = lambda i: jax.tree.map(lambda t_: t_[i], flat, is_leaf=lambda t_: isinstance(t_, tuple))
        return pick(0), {"m": pick(1), "v": pick(2)}


@dataclass
class AdaMax(Adam):
    def apply(self, grads, state, params, iteration):
        lr = _lr_at(self.learning_rate, iteration)
        t = iteration + 1
        bc1 = 1 - self.beta1 ** t

        def upd(p, g, m, u):
            m_new = self.beta1 * m + (1 - self.beta1) * g
            u_new = jnp.maximum(self.beta2 * u, jnp.abs(g))
            return p - lr * (m_new / bc1) / (u_new + self.epsilon), m_new, u_new

        flat = jax.tree.map(upd, params, grads, state["m"], state["v"])
        pick = lambda i: jax.tree.map(lambda t_: t_[i], flat, is_leaf=lambda t_: isinstance(t_, tuple))
        return pick(0), {"m": pick(1), "v": pick(2)}


@dataclass
class Nadam(Adam):
    def apply(self, grads, state, params, iteration):
        lr = _lr_at(self.learning_rate, iteration)
        t = iteration + 1
        bc1 = 1 - self.beta1 ** t
        bc2 = 1 - self.beta2 ** t

        def upd(p, g, m, v):
            m_new, v_new = self._moments(g, m, v)
            m_hat = self.beta1 * m_new / bc1 + (1 - self.beta1) * g / bc1
            return p - lr * m_hat / (jnp.sqrt(v_new / bc2) + self.epsilon), m_new, v_new

        flat = jax.tree.map(upd, params, grads, state["m"], state["v"])
        pick = lambda i: jax.tree.map(lambda t_: t_[i], flat, is_leaf=lambda t_: isinstance(t_, tuple))
        return pick(0), {"m": pick(1), "v": pick(2)}


@dataclass
class AMSGrad(Adam):
    def init(self, params):
        return {"m": self._zeros_like(params),
                "v": self._zeros_like(params),
                "vhat": self._zeros_like(params)}

    def apply(self, grads, state, params, iteration):
        lr = _lr_at(self.learning_rate, iteration)
        t = iteration + 1
        bc1 = 1 - self.beta1 ** t
        bc2 = 1 - self.beta2 ** t

        def upd(p, g, m, v, vh):
            m_new, v_new = self._moments(g, m, v)
            vh_new = jnp.maximum(vh, v_new)
            return (p - lr * (m_new / bc1) / (jnp.sqrt(vh_new / bc2) + self.epsilon),
                    m_new, v_new, vh_new)

        flat = jax.tree.map(upd, params, grads, state["m"], state["v"], state["vhat"])
        pick = lambda i: jax.tree.map(lambda t_: t_[i], flat, is_leaf=lambda t_: isinstance(t_, tuple))
        return pick(0), {"m": pick(1), "v": pick(2), "vhat": pick(3)}


_BY_NAME = {
    "sgd": Sgd, "adam": Adam, "adamw": AdamW, "nesterovs": Nesterovs,
    "adagrad": AdaGrad, "adadelta": AdaDelta, "adamax": AdaMax, "nadam": Nadam,
    "amsgrad": AMSGrad, "rmsprop": RmsProp, "noop": NoOp,
}


def updater_from_name(name: str, **kwargs) -> GradientUpdater:
    return _BY_NAME[name.lower()](**kwargs)
