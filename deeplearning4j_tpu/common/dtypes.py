"""Data type zoo.

TPU-native analog of ND4J's ``org.nd4j.linalg.api.buffer.DataType``
(reference: nd4j/nd4j-backends/nd4j-api-parent/nd4j-api/src/main/java/org/nd4j/
linalg/api/buffer/DataType.java). Each DL4J dtype maps onto a numpy/jax dtype;
UTF8 is represented host-side only (strings never reach the MXU).
"""

from __future__ import annotations

import enum

import numpy as np


class DataType(enum.Enum):
    """Mirrors the reference dtype set; values are the canonical names."""

    FLOAT = "float32"
    DOUBLE = "float64"
    HALF = "float16"
    BFLOAT16 = "bfloat16"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    BOOL = "bool"
    UTF8 = "utf8"  # host-side only

    # ------------------------------------------------------------------
    def to_np(self) -> np.dtype:
        if self is DataType.UTF8:
            raise TypeError("UTF8 arrays are host-side objects, not device dtypes")
        if self is DataType.BFLOAT16:
            import ml_dtypes

            return np.dtype(ml_dtypes.bfloat16)
        return np.dtype(self.value)

    @property
    def is_fp(self) -> bool:
        return self in (DataType.FLOAT, DataType.DOUBLE, DataType.HALF, DataType.BFLOAT16)

    @property
    def is_int(self) -> bool:
        return self.value.startswith(("int", "uint"))

    @property
    def width(self) -> int:
        """Byte width of one element."""
        if self is DataType.BOOL:
            return 1
        if self is DataType.UTF8:
            raise TypeError("UTF8 has no fixed width")
        return self.to_np().itemsize

    @staticmethod
    def from_np(dtype) -> "DataType":
        name = np.dtype(dtype).name
        if name == "bfloat16":
            return DataType.BFLOAT16
        for dt in DataType:
            if dt.value == name:
                return dt
        raise TypeError(f"no DataType for numpy dtype {name!r}")


# Convenience aliases matching Nd4j default naming.
FLOAT = DataType.FLOAT
DOUBLE = DataType.DOUBLE
HALF = DataType.HALF
BFLOAT16 = DataType.BFLOAT16
INT = DataType.INT32
LONG = DataType.INT64
BOOL = DataType.BOOL
