"""Flight recorder: correlated cross-subsystem event tracing.

The runtime spans seven interacting subsystems (pipeline, supervisor,
checkpointing, elastic resize, ZeRO-1 exchange, serving, fault
injection); before this module their observability was a dozen
disconnected pull-based ledgers on ``OpProfiler`` plus ``/api/health``
snapshots — no single timeline showed *what happened in what order
across threads*, and nothing survived a crash. This is the reference
stack's ``PerformanceListener``/``SystemInfo``/UIServer remote-telemetry
role (SURVEY §5.5) rebuilt as a black box: a **thread-safe bounded ring
buffer of structured events** every subsystem appends to, cheap enough
to leave on in production and small enough to dump whole at a crash.

Event model
-----------
One event = one dict: monotonic + wall timestamps, a registered ``name``
(``subsystem/what``, see the registry below), severity (``info`` /
``warn`` / ``error``), free-form ``attrs``, the emitting thread, an
optional **correlation id** and optional **span id / parent span id**.

- **Correlation ids** stitch one logical incident across subsystems and
  threads: the supervisor sets an ambient ``incN.aM``
  (incarnation.attempt) id for each supervised attempt, which every
  event emitted meanwhile inherits (checkpoint commits from the writer
  thread, fault firings, pipeline epochs, elastic resizes); serving
  requests carry their own explicit ``req<ordinal>`` id through
  enqueue → batch → dispatch → reply. One grep of the timeline for a
  correlation id reconstructs a kill-restart-resume or a
  kill-a-replica-mid-load incident end to end.
- **Spans** (:func:`span`) are nestable begin/end pairs with per-thread
  parent tracking — each thread keeps its own span stack, so spans nest
  correctly across concurrent threads.
- The **disabled path is near-zero cost**: one global read plus one
  attribute check, no allocation, no lock.

Consumers
---------
1. :func:`export_chrome_trace` — Chrome trace event format (loadable in
   Perfetto / ``chrome://tracing``): spans as B/E pairs, instants as
   ``i``, and ``OpProfiler.time_section`` durations (recorded as
   ``profiler/section`` events carrying ``dur_s``) as complete ``X``
   events, all mapped onto real thread lanes with thread-name metadata.
2. ``GET /api/metrics`` on :class:`ui.server.UIServer` — Prometheus
   text exposition of every profiler counter/gauge/ledger plus the
   recorder's own totals (the pull half; this module is the push half).
3. :func:`dump_blackbox` — the crash black box: the last-N events as
   JSONL. The supervisor dumps it beside the checkpoints on every
   failure classification and on the SIGTERM preemption path, and
   attaches the tail to ``RestartBudgetExceeded`` — postmortems need no
   live process.

Event-name registry
-------------------
Emitted names must come from :data:`EVENT_SITES` — enforced project-wide
by graftlint's ``event-name-registry`` rule (every emitted literal
registered; every registered name emitted, documented in the table
below, and referenced by a test/bench drill). The table is
generated-checked against the registry, like faultinject's.

=========================  ==========  =================================
event name                 severity    emitted by / drill
=========================  ==========  =================================
supervisor/attempt_start   info        TrainingSupervisor.fit attempt
                                       loop; blackbox drill
supervisor/attempt         info        span around each supervised
                                       attempt (B/E); obs-smoke trace
supervisor/attempt_failed  error       failure classification; blackbox
                                       drill
supervisor/restart         warn        checkpoint-restart decision;
                                       blackbox drill
supervisor/watchdog_fire   warn        hang watchdog; test_supervisor
                                       wedge drill
supervisor/preempted       warn        SIGTERM/SIGINT flush path;
                                       test_supervisor SIGTERM drill
supervisor/give_up         error       budget/storm exhaustion; blackbox
                                       drill
supervisor/completed       info        supervised fit completion
checkpoint/commit          info        util.checkpoint.commit_checkpoint
checkpoint/restore         info        util.checkpoint.
                                       restore_training_state
fault/fired                warn        faultinject.fault_point
pipeline/epoch             info        span around each training epoch
                                       (data.pipeline.run_epochs)
pipeline/dispatch          info        per-dispatch instant (ordinal)
pipeline/stage_fwd         info        PipelineTrainer per-stage forward
                                       window slice (Chrome ``X`` on a
                                       ``pipeline/stage<k>/fwd`` lane;
                                       warmup/cooldown bubbles are the
                                       gaps); test_pipeline_parallel
pipeline/stage_bwd         info        PipelineTrainer per-stage backward
                                       window slice (its own ``/bwd``
                                       lane — 1F1B windows interleave);
                                       test_pipeline_parallel
pipeline/remap             warn        span around an online stage-count
                                       remap (from/to stage counts +
                                       lost stages as attrs);
                                       test_pipeline_parallel drill
elastic/resize             warn        span around ParallelWrapper.
                                       resize; test_elastic drill
serving/enqueue            info        ServingEngine request admission
serving/batch              info        continuous-batching batch formed
                                       (request ids listed)
serving/reply              info        per-request completion + latency
serving/retire             warn        serving replica retirement
serving/shed               warn        brownout shed-level change
                                       (per transition, never per
                                       request); test_autoscale
serving/canary             info        candidate weights on the canary
                                       replica (corr ``pub<N>``)
serving/promote            info        canary promoted fleet-wide
serving/rollback           warn        violation rollback (prior params
                                       restored bitwise)
autoscale/decide           warn        span around one autoscale
                                       decision, signals as attrs
autoscale/scale            info        replica-count change actuated
inference/resurrected      info        replica resurrection landing
fleet/cull                 warn        FleetTrainer.cull froze a member
                                       slice in-graph; test_fleet +
                                       fleet-smoke cull drill
fleet/spawn                info        FleetTrainer.spawn re-initialized
                                       a member slice in place;
                                       test_fleet spawn drill
fleet/nan_cull             warn        per-member NaN isolation flipped
                                       one member's alive bit in-graph;
                                       test_fleet + fleet-smoke NaN
                                       drill
tracecheck/violation       error       steady-state region tripped
profiler/section           info        OpProfiler.time_section duration
                                       (Chrome ``X`` lane)
perf/rate                  info        PerformanceListener throughput
                                       sample
xprof/exec                 info        executable census: a new compiled
                                       generation landed (jit retrace,
                                       AOT bucket, or counted
                                       sub-executable); test_xprof
xprof/hbm                  info        HBM watermark: a phase's live-
                                       buffer peak rose (census bytes
                                       attached); test_xprof
watchtower/alert           warn/error  SLO burn-rate alert transition
                                       (error = page, warn = warn, info
                                       = clear); test_watchtower +
                                       soak-smoke drills
watchtower/incident        warn/info   incident report opened (warn) or
                                       finalized (info) with id + path;
                                       test_watchtower + soak-smoke
cluster/form               info        ClusterRuntime.form bring-up
                                       landed (rank, world, coordinator,
                                       attempts); test_cluster +
                                       cluster-smoke
cluster/barrier            error       barrier deadline expired (rank,
                                       missing ranks, per-rank heartbeat
                                       staleness); test_cluster +
                                       cluster-smoke timeout drills
cluster/rank_lost          error       supervisor classified a dead/hung
                                       rank (rank, class, exit code) —
                                       the incident chain's CAUSE;
                                       test_cluster + cluster-smoke
cluster/group_restart      warn        group restart decision (lost
                                       rank, world_from/world_to —
                                       shrink-to-survivors when they
                                       differ); test_cluster +
                                       cluster-smoke
integrity/fingerprint      info        replica-consistency check window
                                       drained (iteration, fp, running
                                       check count); test_integrity +
                                       integrity-smoke
integrity/divergence       error       in-graph fingerprint divergence
                                       (iteration, replica, fp) — a
                                       DETECTION anchor for the
                                       incident chain; test_integrity +
                                       integrity-smoke bitflip drills
integrity/scrub            info        checkpoint scrub pass summary
                                       (scanned/verified/quarantined/
                                       skipped); test_integrity +
                                       integrity-smoke scrub drill
integrity/quarantine       warn        divergent replica or rotten
                                       checkpoint generation
                                       quarantined (replica or file +
                                       reason) — a MITIGATION anchor;
                                       test_integrity + integrity-smoke
=========================  ==========  =================================

Deliberately stdlib-only (no jax, no profiler import) so every
subsystem — including the profiler itself — can emit without import
cycles, and the crash path has no heavy dependencies.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

#: The central event-name registry (generated-checked against the module
#: docstring table by graftlint's ``event-name-registry`` rule): name ->
#: what emits it + the drill that proves it fires. Emitting an
#: unregistered literal is a lint finding.
EVENT_SITES: Dict[str, Dict[str, str]] = {
    "supervisor/attempt_start": {
        "desc": "supervised attempt begins (resume point named)",
        "drill": "test_observability blackbox drill"},
    "supervisor/attempt": {
        "desc": "span around one supervised attempt",
        "drill": "obs-smoke chrome-trace gate"},
    "supervisor/attempt_failed": {
        "desc": "failure classified (class, policy, error)",
        "drill": "test_observability blackbox drill"},
    "supervisor/restart": {
        "desc": "checkpoint-restart decision + backoff",
        "drill": "test_observability blackbox drill"},
    "supervisor/watchdog_fire": {
        "desc": "hang watchdog abandoned a wedged attempt",
        "drill": "test_supervisor watchdog drill"},
    "supervisor/preempted": {
        "desc": "preemption signal -> flush checkpoint + resumable exit",
        "drill": "test_supervisor SIGTERM drill"},
    "supervisor/give_up": {
        "desc": "restart budget / storm breaker exhausted",
        "drill": "test_observability give-up drill"},
    "supervisor/completed": {
        "desc": "supervised fit ran to completion",
        "drill": "test_observability blackbox drill"},
    "checkpoint/commit": {
        "desc": "checkpoint atomically committed to the manifest",
        "drill": "test_observability blackbox drill"},
    "checkpoint/restore": {
        "desc": "checkpoint restored into a model (resume)",
        "drill": "test_observability blackbox drill"},
    "fault/fired": {
        "desc": "an injected fault fired (site, kind, index)",
        "drill": "test_observability blackbox drill"},
    "pipeline/epoch": {
        "desc": "span around one training epoch",
        "drill": "test_observability chrome-trace test; obs-smoke"},
    "pipeline/dispatch": {
        "desc": "one train-step dispatch (ordinal)",
        "drill": "test_observability chrome-trace test"},
    "pipeline/stage_fwd": {
        "desc": "per-stage forward schedule window (Chrome X on its own "
                "pipeline/stage<k>/fwd lane; bubbles are the gaps)",
        "drill": "test_pipeline_parallel lanes test; "
                 "pipeline-parallel-smoke"},
    "pipeline/stage_bwd": {
        "desc": "per-stage backward schedule window (its own /bwd lane "
                "— 1F1B fwd/bwd windows interleave)",
        "drill": "test_pipeline_parallel lanes test; "
                 "pipeline-parallel-smoke"},
    "pipeline/remap": {
        "desc": "span around an online stage-count remap (stages_from/"
                "stages_to + lost stages as attrs)",
        "drill": "test_pipeline_parallel remap drills; "
                 "pipeline-parallel-smoke"},
    "elastic/resize": {
        "desc": "span around an online data-axis resize",
        "drill": "test_elastic resize drill"},
    "serving/enqueue": {
        "desc": "request admitted to the serving queue (req ordinal)",
        "drill": "test_observability serving lifecycle test"},
    "serving/batch": {
        "desc": "continuous-batching batch formed (request ids)",
        "drill": "test_observability serving lifecycle test"},
    "serving/reply": {
        "desc": "request completed (latency attached)",
        "drill": "test_observability serving lifecycle test"},
    "serving/retire": {
        "desc": "serving replica retired mid-load (batch requeued)",
        "drill": "test_observability serving kill drill"},
    "serving/shed": {
        "desc": "brownout shed-level change (classes shed, reason)",
        "drill": "test_autoscale brownout drills; autoscale-smoke"},
    "serving/canary": {
        "desc": "candidate weights landed on the canary replica",
        "drill": "test_autoscale canary drills; autoscale-smoke"},
    "serving/promote": {
        "desc": "canary promoted fleet-wide after an SLO-clean window",
        "drill": "test_autoscale canary drills; autoscale-smoke"},
    "serving/rollback": {
        "desc": "violation rollback restored the prior params bitwise",
        "drill": "test_autoscale rollback drill; autoscale-smoke"},
    "autoscale/decide": {
        "desc": "span around one scale decision (input signals as attrs)",
        "drill": "test_autoscale controller drills; autoscale-smoke"},
    "autoscale/scale": {
        "desc": "replica-count change actuated (from, to, reason)",
        "drill": "test_autoscale controller drills; autoscale-smoke"},
    "inference/resurrected": {
        "desc": "a retired replica's replacement joined the pool",
        "drill": "test_observability serving kill drill"},
    "fleet/cull": {
        "desc": "a fleet member's alive bit dropped (updates freeze "
                "in-graph; reason attached)",
        "drill": "test_fleet cull drills; fleet-smoke"},
    "fleet/spawn": {
        "desc": "a fleet member slice re-initialized in place (params/"
                "updater/stream key fresh, alive restored)",
        "drill": "test_fleet spawn drills; fleet-smoke"},
    "fleet/nan_cull": {
        "desc": "per-member NaN isolation flipped one member's alive "
                "bit in-graph (other members' updates landed)",
        "drill": "test_fleet NaN drills; fleet-smoke"},
    "tracecheck/violation": {
        "desc": "a declared steady-state region retraced/synced",
        "drill": "test_observability injected-retrace test"},
    "profiler/section": {
        "desc": "one OpProfiler.time_section duration (Chrome X event)",
        "drill": "test_observability chrome-trace test"},
    "perf/rate": {
        "desc": "PerformanceListener throughput/latency sample",
        "drill": "test_observability PerformanceListener test"},
    "xprof/exec": {
        "desc": "executable census generation (jit retrace / AOT bucket "
                "/ counted sub-executable, compile wall attached)",
        "drill": "test_xprof census events; xprof-smoke"},
    "xprof/hbm": {
        "desc": "HBM watermark peak rose for a phase (live/device bytes "
                "attached)",
        "drill": "test_xprof watermark test; xprof-smoke"},
    "watchtower/alert": {
        "desc": "SLO alert state transition (slo, from/to, burn rates, "
                "budget remaining)",
        "drill": "test_watchtower burn/hysteresis drills; soak-smoke"},
    "watchtower/incident": {
        "desc": "incident report opened/finalized (id, reason, path)",
        "drill": "test_watchtower incident drills; soak-smoke"},
    "cluster/form": {
        "desc": "cluster bring-up landed (rank, world, coordinator, "
                "attempts, incarnation)",
        "drill": "test_cluster form drills; cluster-smoke"},
    "cluster/barrier": {
        "desc": "barrier deadline expired (rank, missing ranks, per-rank "
                "heartbeat staleness)",
        "drill": "test_cluster barrier-timeout drills; cluster-smoke"},
    "cluster/rank_lost": {
        "desc": "a rank classified dead/hung (rank, class, exit code) — "
                "incident-chain cause",
        "drill": "test_cluster exit-classification drills; cluster-smoke "
                 "kill drill"},
    "cluster/group_restart": {
        "desc": "group restart decision (lost rank, world_from/world_to; "
                "shrink-to-survivors when they differ)",
        "drill": "test_cluster shrink drill; cluster-smoke"},
    "integrity/fingerprint": {
        "desc": "replica-consistency check window drained (iteration, "
                "fp, running check count)",
        "drill": "test_integrity fingerprint drills; integrity-smoke"},
    "integrity/divergence": {
        "desc": "in-graph fingerprint divergence (iteration, replica, "
                "fp) — detection anchor for the incident chain",
        "drill": "test_integrity bitflip drills; integrity-smoke"},
    "integrity/scrub": {
        "desc": "checkpoint scrub pass summary "
                "(scanned/verified/quarantined/skipped)",
        "drill": "test_integrity scrubber drills; integrity-smoke "
                 "scrub drill"},
    "integrity/quarantine": {
        "desc": "divergent replica or rotten checkpoint generation "
                "quarantined (replica or file + reason) — mitigation "
                "anchor",
        "drill": "test_integrity quarantine drills; integrity-smoke"},
}

DEFAULT_CAPACITY = 4096


class FlightRecorder:
    """Thread-safe bounded ring buffer of structured events with a
    nestable span API. Enabled by default; :meth:`configure` flips it
    (the disabled path is one attribute check). Instantiable for tests;
    the process-wide instance is :func:`get`."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool = True):
        self._lock = threading.Lock()
        self._buf: "deque" = deque(maxlen=max(1, int(capacity)))
        self._enabled = bool(enabled)
        self._total = 0          # events ever appended (== next seq)
        self._dropped = 0        # ring-overflow evictions
        self._span_seq = 0
        self._corr: Optional[str] = None    # ambient correlation id
        self._tls = threading.local()       # per-thread span stack

    # -- config / introspection ------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def configure(self, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None) -> "FlightRecorder":
        with self._lock:
            if enabled is not None:
                self._enabled = bool(enabled)
            if capacity is not None and capacity != self._buf.maxlen:
                cap = max(1, int(capacity))
                # a shrink evicts the oldest buffered events — they count
                # as drops, or consumers trusting dropped==0 (the chrome
                # B/E-balance gate) would read a truncated ring as whole
                self._dropped += max(0, len(self._buf) - cap)
                self._buf = deque(self._buf, maxlen=cap)
        return self

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"enabled": self._enabled,
                    "capacity": self._buf.maxlen,
                    "buffered": len(self._buf),
                    "events_total": self._total,
                    "dropped": self._dropped}

    def reset(self) -> None:
        with self._lock:
            self._buf.clear()
            self._total = 0
            self._dropped = 0
            self._corr = None

    # -- correlation ------------------------------------------------------
    def set_correlation(self, corr: Optional[str]) -> None:
        """Set the AMBIENT correlation id every subsequent event (from
        any thread) inherits unless it passes an explicit ``corr``. The
        supervisor owns this slot during supervised runs (one run at a
        time); explicit per-event ids (serving requests) always win."""
        with self._lock:
            self._corr = corr

    def correlation(self) -> Optional[str]:
        return self._corr

    @contextlib.contextmanager
    def correlate(self, corr: Optional[str]) -> Iterator[None]:
        prev = self._corr
        self.set_correlation(corr)
        try:
            yield
        finally:
            self.set_correlation(prev)

    # -- emission ---------------------------------------------------------
    def record(self, name: str, severity: str = "info",
               corr: Optional[str] = None,
               attrs: Optional[Dict[str, Any]] = None,
               phase: str = "i", span_id: Optional[int] = None,
               parent_id: Optional[int] = None,
               force: bool = False) -> None:
        """Append one event. Near-zero when disabled (one attribute
        check, nothing allocated). ``force`` records even while
        disabled — only span close uses it, so a mid-span disable cannot
        orphan a recorded B.

        Two reserved attr keys serve DERIVED timeline slices (events
        reconstructed after the fact, e.g. the pipeline trainer's
        per-stage schedule lanes): ``ts_mono`` overrides the event's
        monotonic timestamp (popped, not stored), and ``lane`` makes the
        Chrome exporter render the event on its own named synthetic lane
        instead of the emitting thread's."""
        if not self._enabled and not force:
            return
        m = time.monotonic()
        if attrs and "ts_mono" in attrs:
            attrs = dict(attrs)
            m = float(attrs.pop("ts_mono"))
        t = threading.current_thread()
        ev = {"t": time.time(), "m": m, "name": name,
              "sev": severity, "corr": corr, "ph": phase,
              "span": span_id, "parent": parent_id,
              "thread": t.name, "tid": t.ident,
              "attrs": attrs or {}}
        with self._lock:
            if corr is None:
                ev["corr"] = self._corr
            ev["seq"] = self._total
            if len(self._buf) == self._buf.maxlen:
                self._dropped += 1
            self._buf.append(ev)
            self._total += 1

    def event(self, name: str, severity: str = "info",
              corr: Optional[str] = None, **attrs) -> None:
        self.record(name, severity=severity, corr=corr, attrs=attrs)

    def _stack(self) -> List[int]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    @contextlib.contextmanager
    def span(self, name: str, severity: str = "info",
             corr: Optional[str] = None, **attrs) -> Iterator[Optional[int]]:
        """Nestable begin/end span: emits a ``B`` event on entry and an
        ``E`` event on exit (exceptions included), parented on the
        calling thread's innermost open span."""
        if not self._enabled:
            yield None
            return
        stack = self._stack()
        with self._lock:
            self._span_seq += 1
            sid = self._span_seq
            if corr is None:
                # resolve the ambient id ONCE, at open: a span that
                # outlives a correlation change (a zombie attempt's epoch
                # unwinding after its replacement started) must close
                # under the incident it opened under
                corr = self._corr
        parent = stack[-1] if stack else None
        self.record(name, severity=severity, corr=corr, attrs=attrs,
                    phase="B", span_id=sid, parent_id=parent)
        stack.append(sid)
        try:
            yield sid
        finally:
            stack.pop()
            # force: a recorded B must get its E even if the recorder was
            # disabled mid-span, or the trace carries a never-ending
            # slice while dropped==0 claims the ring is whole
            self.record(name, severity=severity, corr=corr, phase="E",
                        span_id=sid, parent_id=parent, force=True)

    # -- reading ----------------------------------------------------------
    def snapshot(self) -> List[Dict[str, Any]]:
        """Owning copy of the ring, oldest first."""
        with self._lock:
            return list(self._buf)

    def events(self, prefix: Optional[str] = None,
               corr: Optional[str] = None) -> List[Dict[str, Any]]:
        evs = self.snapshot()
        if prefix is not None:
            evs = [e for e in evs if e["name"].startswith(prefix)]
        if corr is not None:
            evs = [e for e in evs if e["corr"] == corr]
        return evs

    def tail(self, n: int) -> List[Dict[str, Any]]:
        return self.snapshot()[-max(0, int(n)):]

    # -- consumers --------------------------------------------------------
    def chrome_trace(self, corr: Optional[str] = None) -> Dict[str, Any]:
        """The ring as a Chrome trace event document (Perfetto /
        ``chrome://tracing`` loadable). Spans map to ``B``/``E`` pairs,
        instants to ``i``, events carrying a ``dur_s`` attr (the
        profiler's ``time_section`` durations) to complete ``X`` events
        named after their section; each emitting thread gets its own
        lane with a ``thread_name`` metadata record. ``corr`` filters to
        one correlation id — the incident-link view ``/api/trace``
        serves over HTTP."""
        evs = self.snapshot()
        if corr is not None:
            evs = [e for e in evs if e["corr"] == corr]
        pid = os.getpid()
        out: List[Dict[str, Any]] = []
        threads: Dict[int, str] = {}
        lane_tids: Dict[str, int] = {}
        for e in evs:
            args = dict(e["attrs"])
            lane = args.pop("lane", None)
            if lane is not None:
                # named synthetic lane (per-stage pipeline schedule
                # slices): negative tids can't collide with OS threads
                tid = lane_tids.setdefault(str(lane),
                                           -(len(lane_tids) + 1))
                threads.setdefault(tid, str(lane))
            else:
                tid = e["tid"] or 0
                threads.setdefault(tid, e["thread"])
            if e["corr"]:
                args["corr"] = e["corr"]
            if e["span"] is not None:
                args["span"] = e["span"]
                if e["parent"] is not None:
                    args["parent_span"] = e["parent"]
            name, cat = e["name"], e["name"].split("/", 1)[0]
            base = {"pid": pid, "tid": tid, "cat": cat, "args": args}
            dur = e["attrs"].get("dur_s")
            if e["ph"] in ("B", "E"):
                out.append({**base, "ph": e["ph"], "name": name,
                            "ts": e["m"] * 1e6})
            elif dur is not None:
                sec = e["attrs"].get("section", name)
                out.append({**base, "ph": "X",
                            "name": sec, "cat": str(sec).split("/", 1)[0],
                            "ts": (e["m"] - float(dur)) * 1e6,
                            "dur": float(dur) * 1e6})
            else:
                out.append({**base, "ph": "i", "s": "t", "name": name,
                            "ts": e["m"] * 1e6})
        for tid, tname in threads.items():
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name", "args": {"name": tname}})
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str,
                            corr: Optional[str] = None) -> int:
        """Write :meth:`chrome_trace` atomically (tmp + rename).
        Returns the number of trace events written."""
        doc = self.chrome_trace(corr=corr)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return len(doc["traceEvents"])

    def dump_blackbox(self, path: str,
                      last_n: Optional[int] = None) -> str:
        """Write the last-N events (whole ring by default) as JSONL, one
        event per line, atomically (tmp + rename — a crash mid-dump
        leaves the previous black box intact). The postmortem artifact:
        readable with no live process, greppable by correlation id."""
        evs = self.snapshot()
        if last_n is not None:
            evs = evs[-max(0, int(last_n)):]
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for e in evs:
                f.write(json.dumps(e, default=str) + "\n")
        os.replace(tmp, path)
        return path


# -- the process-wide recorder + module-level facade ----------------------

_REC: Optional[FlightRecorder] = None
_rec_lock = threading.Lock()


def get() -> FlightRecorder:
    global _REC
    rec = _REC
    if rec is None:
        with _rec_lock:
            if _REC is None:
                _REC = FlightRecorder()
            rec = _REC
    return rec


def configure(enabled: Optional[bool] = None,
              capacity: Optional[int] = None) -> FlightRecorder:
    return get().configure(enabled=enabled, capacity=capacity)


def enabled() -> bool:
    """Cheapest possible recording check — for call sites whose event
    ATTRS are themselves expensive to build (list comprehensions over a
    batch, latency math): guard them so the disabled path allocates
    nothing. A not-yet-created recorder reports True (it is born
    enabled; the first event() call creates it)."""
    rec = _REC
    return rec is None or rec._enabled


def event(name: str, severity: str = "info", corr: Optional[str] = None,
          **attrs) -> None:
    """Emit one instant event (the hot-path entry point — when the
    recorder is disabled this is one global read + one attribute
    check)."""
    rec = _REC
    if rec is None:
        rec = get()
    if not rec._enabled:
        return
    rec.record(name, severity=severity, corr=corr, attrs=attrs)


def span(name: str, severity: str = "info", corr: Optional[str] = None,
         **attrs):
    return get().span(name, severity=severity, corr=corr, **attrs)


def set_correlation(corr: Optional[str]) -> None:
    get().set_correlation(corr)


def correlate(corr: Optional[str]):
    return get().correlate(corr)


def events(prefix: Optional[str] = None,
           corr: Optional[str] = None) -> List[Dict[str, Any]]:
    return get().events(prefix=prefix, corr=corr)


def tail(n: int) -> List[Dict[str, Any]]:
    return get().tail(n)


def stats() -> Dict[str, Any]:
    return get().stats()


def reset() -> None:
    get().reset()


def chrome_trace(corr: Optional[str] = None) -> Dict[str, Any]:
    return get().chrome_trace(corr=corr)


def export_chrome_trace(path: str, corr: Optional[str] = None) -> int:
    return get().export_chrome_trace(path, corr=corr)


def dump_blackbox(path: str, last_n: Optional[int] = None) -> str:
    return get().dump_blackbox(path, last_n=last_n)
