"""Process-wide environment singleton.

TPU-native analog of libnd4j's ``sd::Environment`` + ND4J's
``Nd4j.getEnvironment()`` (reference: libnd4j/include/system/Environment.h,
nd4j-api org/nd4j/linalg/factory/Environment.java). Fronts jax.config knobs,
XLA flags, and framework toggles behind one object so user code has a single
place to flip debug/verbose/determinism, matching the reference's pattern of
env-var + runtime-settable flags.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional


class Environment:
    _instance: Optional["Environment"] = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._debug = _env_bool("DL4J_TPU_DEBUG", False)
        self._verbose = _env_bool("DL4J_TPU_VERBOSE", False)
        self._profiling = False
        self._check_nan = False          # NAN_PANIC analog (jax_debug_nans)
        self._deterministic = _env_bool("DL4J_TPU_DETERMINISTIC", False)
        self._default_dtype = os.environ.get("DL4J_TPU_DTYPE", "float32")
        self._allow_pallas = _env_bool("DL4J_TPU_ALLOW_PALLAS", True)
        self._properties: Dict[str, Any] = {}
        self._compile_cache_dir: Optional[str] = None
        # Opt-in persistent executable cache (SURVEY §5.6; VERDICT r3
        # weak #7): setting DL4J_TPU_COMPILE_CACHE=<dir> makes every
        # process sharing that dir skip XLA recompilation — the analog of
        # the reference shipping prebuilt libnd4j binaries. The first
        # Environment.get() applies it, so plain library users get it
        # without touching jax.config themselves.
        if os.environ.get("DL4J_TPU_COMPILE_CACHE"):
            # best-effort: a stale/unwritable path in someone's shell
            # profile must not break every Environment.get() in
            # compilation-unrelated code
            try:
                self.set_compile_cache(
                    os.environ["DL4J_TPU_COMPILE_CACHE"])
            except Exception as e:   # noqa: BLE001
                import warnings

                warnings.warn(
                    f"DL4J_TPU_COMPILE_CACHE="
                    f"{os.environ['DL4J_TPU_COMPILE_CACHE']!r} could not "
                    f"be applied: {e}", RuntimeWarning, stacklevel=2)

    # ------------------------------------------------------------------
    @classmethod
    def get(cls) -> "Environment":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # --- flags ---------------------------------------------------------
    def is_debug(self) -> bool:
        return self._debug

    def set_debug(self, v: bool) -> None:
        self._debug = bool(v)

    def is_verbose(self) -> bool:
        return self._verbose

    def set_verbose(self, v: bool) -> None:
        self._verbose = bool(v)

    def is_profiling(self) -> bool:
        return self._profiling

    def set_profiling(self, v: bool) -> None:
        self._profiling = bool(v)

    def is_check_nan(self) -> bool:
        return self._check_nan

    def set_check_nan(self, v: bool) -> None:
        """NAN_PANIC analog: makes jax raise on any NaN produced under jit."""
        import jax

        self._check_nan = bool(v)
        jax.config.update("jax_debug_nans", bool(v))

    def is_deterministic(self) -> bool:
        return self._deterministic

    def set_deterministic(self, v: bool) -> None:
        self._deterministic = bool(v)

    def allow_pallas(self) -> bool:
        return self._allow_pallas

    def set_allow_pallas(self, v: bool) -> None:
        self._allow_pallas = bool(v)

    def default_dtype(self) -> str:
        return self._default_dtype

    def set_default_dtype(self, name: str) -> None:
        self._default_dtype = name

    def compile_cache_dir(self) -> Optional[str]:
        return self._compile_cache_dir

    def set_compile_cache(self, path: str,
                          min_compile_secs: float = 1.0) -> str:
        """Enable the persistent executable cache at ``path`` (see
        :func:`enable_compilation_cache`)."""
        self._compile_cache_dir = enable_compilation_cache(
            path, min_compile_secs)
        return self._compile_cache_dir

    # --- device info -----------------------------------------------------
    def devices(self) -> List[Any]:
        import jax

        return jax.devices()

    def num_devices(self) -> int:
        return len(self.devices())

    def is_tpu(self) -> bool:
        return any(d.platform in ("tpu", "axon") for d in self.devices())

    # --- generic key/value (ND4JSystemProperties analog) -----------------
    def set_property(self, key: str, value: Any) -> None:
        self._properties[key] = value

    def get_property(self, key: str, default: Any = None) -> Any:
        return self._properties.get(key, default)


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def enable_compilation_cache(path: Optional[str] = None,
                             min_compile_secs: float = 1.0) -> str:
    """Turn on JAX's persistent executable cache (the TPU analog of the
    reference shipping pre-built libnd4j kernels: compile once per machine,
    not once per process). Word2Vec-class workloads spend 20–35 s compiling
    their scan blocks on TPU — with this cache every later process skips
    that entirely (verified working through the axon relay backend).

    ``path`` defaults to ``$DL4J_TPU_COMPILE_CACHE`` or ``.jax_cache`` under
    the current working directory. Returns the directory used.
    """
    import jax

    path = (path or os.environ.get("DL4J_TPU_COMPILE_CACHE")
            or os.path.join(os.getcwd(), ".jax_cache"))
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    return path
