"""XLA performance observatory: executable census, roofline ledger, HBM
watermarks.

The repo can time a step (profiler sections, bench fences) but before
this module it could not say *why* a step is slow: no per-executable
FLOPs/bytes, no compute-vs-memory-bound verdict, no HBM watermark, no
compile ledger. The whole-graph-compilation line of work (TVM, nGraph —
PAPERS.md) argues that graph-level optimization is only steerable with
per-kernel cost models; this is that layer, built on jax's own
``lowered.cost_analysis()`` / ``compiled.memory_analysis()`` artifacts.

Three instruments, one module:

1. **Executable census** — every long-lived compiled function in the
   package registers under a stable name from :data:`EXEC_SITES`
   (enforced project-wide by graftlint's ``executable-census`` rule, the
   fault-site-registry 4-way pattern: call sites vs registry vs the
   docstring table below vs the test/bench corpus).
   :func:`register_jit` wraps a ``jax.jit`` callable and tracks, per
   entry: call count, cumulative dispatch wall time, a retrace
   GENERATION counter (``jit._cache_size()`` growth — a new input
   signature means a new executable), the first-call wall time of each
   generation (trace+compile+first run), and the argument avals of the
   newest generation (``ShapeDtypeStruct`` only — donation-safe, no
   buffer retention). :func:`register_aot` records explicitly
   ``.lower().compile()``-d executables (the serving bucket ladder) with
   their cost/memory analysis extracted immediately — already compiled,
   nothing re-traced. :func:`note_subexec` records fused kernels that
   live INSIDE a parent executable (the Pallas flat-bucket updaters)
   with analytic counted cost at trace time.
2. **Roofline attribution ledger** — :func:`analyze` lowers registered
   entries against their stored avals and extracts
   ``cost_analysis()`` (flops, bytes accessed, transcendentals) and,
   with ``compile=True``, ``memory_analysis()`` (argument/output/temp/
   generated-code bytes) plus an input-sharding fingerprint. Backends
   without cost analysis degrade to a COUNTED fallback (bytes from the
   avals, flops omitted) — never a crash. :func:`roofline` joins the
   analytic cost with measured dispatch time into per-executable MFU,
   arithmetic intensity, and a compute-bound vs HBM-bound verdict
   against the platform roof (:func:`set_roof` to override);
   :func:`ledger` flattens it into the ``xla`` entry of
   ``OpProfiler.LEDGERS`` so ``/api/health``, ``/api/metrics`` and
   ``print_statistics`` all carry it for free. CAVEATS: dispatch wall
   time is host-side submit time — on an async backend it converges to
   device time only when the caller fences (the bench does; feed the
   fenced per-step median via :func:`note_measured` for honest MFU);
   ``analyze`` RE-TRACES the function body (trace counters move, jax
   compile events fire) — call it outside ``tracecheck.steady_state``
   regions, never in a hot loop.
3. **HBM watermarks** — :func:`memory_watermark` takes the SAME
   device/host memory census ``/api/health`` serves
   (``common.system_info.memory_summary``: per-device PJRT stats + the
   ``jax.live_arrays`` walk — one census function, two consumers) and
   folds it into per-phase peak gauges. ``data.pipeline.run_epochs``
   samples once per epoch (phase ``fit``), the serving warmup samples
   ``serving_warmup``, and the supervisor's crash blackbox dumps the
   full census (:func:`dump_memory_census` → ``memcensus.json`` beside
   ``blackbox.jsonl``) so OOM-class failures carry the memory picture
   alongside the event tail.

Census overhead is one enabled-flag read plus two ``perf_counter`` calls
and a lock per dispatch (``configure(enabled=False)`` reduces it to the
flag read); the ``xprof-smoke`` bench config A/B-gates it at <=5% with a
zero retrace delta.

Executable-census registry
--------------------------
==========================  ============================================
census name                 executable / registrar
==========================  ============================================
mln/infer                   MultiLayerNetwork.output jit
mln/fit_step                MultiLayerNetwork per-step train jit
mln/fit_chunk               MultiLayerNetwork steps_per_dispatch scan jit
mln/tbptt_step              MultiLayerNetwork TBPTT segment jit
mln/pretrain_step           MultiLayerNetwork layerwise pretrain jit
graph/infer                 ComputationGraph.output jit
graph/fit_step              ComputationGraph per-step train jit
graph/fit_chunk             ComputationGraph scan-chunk jit
transfer/featurize          TransferLearningHelper frozen-bottom jit
pw/fit_step                 ParallelWrapper shard_map step jit (dense +
                            ZeRO-1 paths — one executable)
pw/fit_chunk                ParallelWrapper scan-chunk jit
pipeline/fit_step           PipelineTrainer whole-schedule step jit (one
                            generation per (stage-count, schedule))
pipeline/legacy_fwd         legacy PipelineParallel forward jit
pipeline/legacy_step        legacy PipelineParallel train-step jit
pipeline/hetero_fwd         HeterogeneousPipeline forward jit
pipeline/hetero_step        HeterogeneousPipeline train-step jit
fleet/step                  FleetTrainer vmapped population step jit
fleet/infer                 FleetTrainer vmapped inference jit
embeddings/lookup           ShardedEmbeddings gather jit
embeddings/update           ShardedEmbeddings scatter-update jit
serving/bucket              ServingEngine AOT bucket executables (one
                            variant per (shape, device slot))
samediff/exec               SameDiff cached forward-exec jit
samediff/grad               SameDiff cached gradient jit
samediff/fit_step           SameDiff fused train-step jit
nlp/w2v_subsample           Word2Vec device subsampling jit
nlp/w2v_sg_block            Word2Vec skip-gram pair-block jit
nlp/w2v_table_block         Word2Vec dense-round table jit (plain +
                            sharded-table variants)
nlp/w2v_cbow_block          Word2Vec CBOW windowed-block jit
nlp/pv_dbow_block           ParagraphVectors DBOW block jit
nlp/pv_dm_block             ParagraphVectors DM (CBOW-class) block jit
nlp/pv_pos_map              ParagraphVectors shuffled-pair-order jit
nlp/pv_subsample            ParagraphVectors 3-stream subsampling jit
nlp/fasttext_block          FastText subword CBOW block jit
nlp/glove_block             GloVe AdaGrad descent block jit
data/feature_transform      AsyncDataSetIterator on-device transform jit
pallas/update_bucket        fused flat-bucket updater kernels (counted
                            sub-executable: dispatches inside the parent
                            step; analytic flops/bytes at trace time)
==========================  ============================================
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Any, Dict, Optional, Tuple

from . import flightrec
from .profiler import OpProfiler

#: The central executable-census registry (generated-checked against the
#: module docstring table by graftlint's ``executable-census`` rule):
#: census name -> what registers it + the drill that proves it. A
#: ``register_jit``/``register_aot``/``note_subexec`` call with an
#: unregistered literal is a lint finding AND a runtime ValueError.
EXEC_SITES: Dict[str, Dict[str, str]] = {
    "mln/infer": {
        "desc": "MultiLayerNetwork.output inference jit",
        "drill": "test_xprof census coverage"},
    "mln/fit_step": {
        "desc": "MultiLayerNetwork per-step train jit",
        "drill": "test_xprof census coverage; xprof-smoke"},
    "mln/fit_chunk": {
        "desc": "MultiLayerNetwork steps_per_dispatch scan jit",
        "drill": "test_xprof census coverage"},
    "mln/tbptt_step": {
        "desc": "MultiLayerNetwork TBPTT segment jit",
        "drill": "test_xprof registry table"},
    "mln/pretrain_step": {
        "desc": "MultiLayerNetwork layerwise pretrain jit",
        "drill": "test_xprof registry table"},
    "graph/infer": {
        "desc": "ComputationGraph.output inference jit",
        "drill": "test_xprof census coverage"},
    "graph/fit_step": {
        "desc": "ComputationGraph per-step train jit",
        "drill": "test_xprof census coverage; bench resnet50 roofline"},
    "graph/fit_chunk": {
        "desc": "ComputationGraph scan-chunk jit",
        "drill": "test_xprof registry table"},
    "transfer/featurize": {
        "desc": "TransferLearningHelper frozen-bottom featurize jit",
        "drill": "test_xprof registry table"},
    "pw/fit_step": {
        "desc": "ParallelWrapper shard_map step jit (dense + ZeRO-1)",
        "drill": "test_xprof census coverage"},
    "pw/fit_chunk": {
        "desc": "ParallelWrapper scan-chunk jit",
        "drill": "test_xprof registry table"},
    "pipeline/fit_step": {
        "desc": "PipelineTrainer whole-schedule step jit",
        "drill": "test_xprof registry table"},
    "pipeline/legacy_fwd": {
        "desc": "legacy PipelineParallel forward jit",
        "drill": "test_xprof registry table"},
    "pipeline/legacy_step": {
        "desc": "legacy PipelineParallel train-step jit",
        "drill": "test_xprof registry table"},
    "pipeline/hetero_fwd": {
        "desc": "HeterogeneousPipeline forward jit",
        "drill": "test_xprof registry table"},
    "pipeline/hetero_step": {
        "desc": "HeterogeneousPipeline train-step jit",
        "drill": "test_xprof registry table"},
    "fleet/step": {
        "desc": "FleetTrainer vmapped population step jit",
        "drill": "test_xprof census coverage"},
    "fleet/infer": {
        "desc": "FleetTrainer vmapped inference jit",
        "drill": "test_xprof registry table"},
    "embeddings/lookup": {
        "desc": "ShardedEmbeddings gather jit",
        "drill": "test_xprof registry table"},
    "embeddings/update": {
        "desc": "ShardedEmbeddings scatter-update jit",
        "drill": "test_xprof registry table"},
    "serving/bucket": {
        "desc": "ServingEngine AOT bucket executable (variant per "
                "(shape, device slot))",
        "drill": "test_xprof serving AOT census; xprof-smoke"},
    "samediff/exec": {
        "desc": "SameDiff cached forward-exec jit",
        "drill": "test_xprof registry table"},
    "samediff/grad": {
        "desc": "SameDiff cached gradient jit",
        "drill": "test_xprof registry table"},
    "samediff/fit_step": {
        "desc": "SameDiff fused train-step jit",
        "drill": "test_xprof registry table"},
    "nlp/w2v_subsample": {
        "desc": "Word2Vec device subsampling jit",
        "drill": "test_xprof registry table"},
    "nlp/w2v_sg_block": {
        "desc": "Word2Vec skip-gram pair-block jit",
        "drill": "test_xprof registry table"},
    "nlp/w2v_table_block": {
        "desc": "Word2Vec dense-round table jit (plain + sharded)",
        "drill": "test_xprof registry table"},
    "nlp/w2v_cbow_block": {
        "desc": "Word2Vec CBOW windowed-block jit",
        "drill": "test_xprof registry table"},
    "nlp/pv_dbow_block": {
        "desc": "ParagraphVectors DBOW block jit",
        "drill": "test_xprof registry table"},
    "nlp/pv_dm_block": {
        "desc": "ParagraphVectors DM block jit",
        "drill": "test_xprof registry table"},
    "nlp/pv_pos_map": {
        "desc": "ParagraphVectors shuffled-pair-order jit",
        "drill": "test_xprof registry table"},
    "nlp/pv_subsample": {
        "desc": "ParagraphVectors 3-stream subsampling jit",
        "drill": "test_xprof registry table"},
    "nlp/fasttext_block": {
        "desc": "FastText subword CBOW block jit",
        "drill": "test_xprof registry table"},
    "nlp/glove_block": {
        "desc": "GloVe AdaGrad descent block jit",
        "drill": "test_xprof registry table"},
    "data/feature_transform": {
        "desc": "AsyncDataSetIterator on-device feature transform jit",
        "drill": "test_xprof registry table"},
    "pallas/update_bucket": {
        "desc": "fused flat-bucket updater kernels (counted "
                "sub-executable inside the parent step)",
        "drill": "test_xprof counted sub-executable test"},
}

#: Platform rooflines: (peak flops/s, peak memory bytes/s). The TPU row
#: is the published v5e bf16 peak + HBM bandwidth; the CPU row is a
#: NOMINAL single-core planning roof for the build container (MFU/bound
#: verdicts against it are approximate by construction — override with
#: :func:`set_roof` when the host is characterized).
PLATFORM_ROOFS: Dict[str, Tuple[float, float]] = {
    "tpu": (197e12, 819e9),
    "cpu": (5e10, 2e10),
}


def _now() -> float:
    return time.perf_counter()


class _Entry:
    """One census entry: identity + accumulated dispatch/compile
    accounting + the newest generation's avals + analysis results."""

    __slots__ = ("name", "calls", "dispatch_s", "generations", "compile_s",
                 "avals", "fn_ref", "fingerprint", "cost", "memory",
                 "cost_source", "analyzed_gen", "measured_step_s",
                 "variants", "error", "subexec")

    def __init__(self, name: str):
        self.name = name
        self.calls = 0
        self.dispatch_s = 0.0
        self.generations = 0        # distinct compiled executables seen
        self.compile_s = 0.0        # sum of first-call-per-generation wall
        self.avals = None           # (args, kwargs) aval trees, newest gen
        self.fn_ref = None          # weakref to the live jit function
        self.fingerprint: Dict[str, Any] = {}
        self.cost: Optional[Dict[str, float]] = None
        self.memory: Optional[Dict[str, float]] = None
        self.cost_source: Optional[str] = None   # "xla" | "counted"
        self.analyzed_gen = 0       # generation the analysis belongs to
        self.measured_step_s: Optional[float] = None
        self.variants = 0           # AOT variants folded in (serving)
        self.error: Optional[str] = None
        self.subexec = False        # counted-only sub-executable

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name, "calls": self.calls,
            "dispatch_s": round(self.dispatch_s, 6),
            "generations": self.generations,
            "compile_s": round(self.compile_s, 6),
            "fingerprint": dict(self.fingerprint),
            "cost_source": self.cost_source,
        }
        if self.cost:
            out["cost"] = dict(self.cost)
        if self.memory:
            out["memory"] = dict(self.memory)
        if self.variants:
            out["variants"] = self.variants
        if self.measured_step_s is not None:
            out["measured_step_s"] = self.measured_step_s
        if self.subexec:
            out["subexec"] = True
        if self.error:
            out["error"] = self.error
        return out


class ExecutableCensus:
    """The process-wide census (instantiable for tests). Thread-safe:
    dispatches land from the training thread, serving workers and the
    checkpoint writer alike."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self._enabled = True
        self._roof: Optional[Tuple[float, float]] = None
        self._watermarks: Dict[str, Dict[str, Any]] = {}

    # -- config -----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def configure(self, enabled: Optional[bool] = None) -> "ExecutableCensus":
        with self._lock:
            if enabled is not None:
                self._enabled = bool(enabled)
        return self

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._watermarks.clear()

    def set_roof(self, peak_flops: float, peak_bytes_per_s: float) -> None:
        with self._lock:
            self._roof = (float(peak_flops), float(peak_bytes_per_s))

    def _platform_roof(self) -> Tuple[Optional[float], Optional[float]]:
        if self._roof is not None:
            return self._roof
        try:
            import jax

            plat = jax.devices()[0].platform
        except Exception:
            plat = "cpu"
        return PLATFORM_ROOFS.get(plat, PLATFORM_ROOFS["cpu"])

    # -- registration -----------------------------------------------------
    def _entry(self, name: str) -> _Entry:
        if name not in EXEC_SITES:
            raise ValueError(
                f"unknown executable-census site {name!r} — register it "
                "in common.xprof.EXEC_SITES (and the docstring table)")
        with self._lock:
            e = self._entries.get(name)
            if e is None:
                e = self._entries[name] = _Entry(name)
            return e

    def register_jit(self, name: str, fn, *, donate=None,
                     static_argnames=None):
        """Wrap a ``jax.jit`` callable under census ``name``. The wrapper
        is call-transparent (attribute access, ``.lower`` included, falls
        through to the jit) and donation-safe — only avals are retained.
        Re-registering a name (a rebuilt step) accumulates onto the same
        entry: that IS the retrace-generation ledger. Wrappers resolve
        their entry BY NAME per dispatch, so a :meth:`reset` opens a
        clean window without orphaning live wrappers."""
        fp: Dict[str, Any] = {}
        if donate is not None:
            fp["donate_argnums"] = tuple(donate)
        if static_argnames is not None:
            fp["static_argnames"] = tuple(static_argnames)
        e = self._entry(name)
        with self._lock:
            e.fingerprint.update(fp)
        return _Censused(self, name, fn, fp)

    def register_aot(self, name: str, compiled, *, variant: str = "",
                     compile_s: Optional[float] = None) -> None:
        """Record an explicitly ``.lower().compile()``-d executable. Cost
        and memory analysis are extracted IMMEDIATELY (the object is
        already compiled — nothing traces); repeated variants (serving
        buckets) accumulate flops/bytes onto the one entry."""
        if compiled is None:
            return
        e = self._entry(name)
        cost = _cost_dict(compiled)
        mem = _memory_dict(compiled)
        source = "xla" if cost is not None else "counted"
        if cost is None and mem is not None:
            # counted fallback for backends without AOT cost analysis:
            # bytes from the executable's own argument/output footprint
            # (the same degradation contract analyze() applies)
            nbytes = mem.get("argument_bytes", 0) + mem.get(
                "output_bytes", 0)
            if nbytes:
                cost = {"bytes_accessed": float(nbytes)}
        with self._lock:
            e.generations += 1
            e.variants += 1
            if compile_s:
                e.compile_s += float(compile_s)
            if cost is not None:
                # key-UNION merge: a variant whose analysis omits a key
                # (e.g. no transcendentals) must not erase the other
                # variants' accumulated mass; mixed xla/counted ladders
                # keep every variant's bytes and report the stronger
                # source
                prev = e.cost or {}
                e.cost = {k: prev.get(k, 0.0) + cost.get(k, 0.0)
                          for k in set(prev) | set(cost)}
                e.cost_source = ("xla" if "xla" in (source, e.cost_source)
                                 else "counted")
            elif e.cost_source is None:
                e.cost_source = "counted"
                e.cost = {}
            if mem is not None:
                prev_m = e.memory or {}
                e.memory = {k: prev_m.get(k, 0) + v for k, v in mem.items()}
            if variant:
                e.fingerprint["last_variant"] = variant
            gen = e.generations
        flightrec.event("xprof/exec", executable=name,
                        generation=gen, variant=variant or None,
                        aot=True)

    def note_subexec(self, name: str, flops: Optional[float] = None,
                     bytes_accessed: Optional[float] = None,
                     **attrs) -> None:
        """Counted census entry for a kernel dispatched INSIDE a parent
        executable (fused Pallas updaters). Called at trace time — once
        per parent compile, like the ``precision/*`` counters. The cost
        is LAST-TRACE-WINS, never accumulated: the analytic flops/bytes
        always describe one execution of the most recent parent (a
        rebuild, an analysis re-lowering, or a second fused model must
        not inflate the row); ``generations`` counts the traces seen."""
        e = self._entry(name)
        with self._lock:
            e.subexec = True
            e.generations += 1
            e.cost_source = "counted"
            cost: Dict[str, float] = {}
            if flops is not None:
                cost["flops"] = float(flops)
            if bytes_accessed is not None:
                cost["bytes_accessed"] = float(bytes_accessed)
            e.cost = cost
            for k, v in attrs.items():
                e.fingerprint[k] = v
            gen = e.generations
        flightrec.event("xprof/exec", executable=name,
                        generation=gen, subexec=True)

    # -- dispatch accounting (wrapper callback) ---------------------------
    def _note_call(self, name: str, fn, wrapper, dt: float, args,
                   kwargs) -> None:
        try:
            size = fn._cache_size()
        except Exception:
            size = None
        avals = None
        with self._lock:
            # the entry is resolved BY NAME per dispatch (a reset() must
            # not orphan live wrappers), and wrapper._last_cache is read
            # AND advanced under the census lock: concurrent dispatches
            # through one wrapper (serving workers share a model) must
            # bill one real compile as one generation, not one per
            # racing thread
            e = self._entries.get(name)
            if e is None:
                e = self._entries[name] = _Entry(name)
                e.fingerprint.update(wrapper._fp)
            last = wrapper._last_cache
            if size is None:
                # no cache introspection on this jax: fall back to
                # "first call through this wrapper = one generation"
                compiled_now = last == 0
                size = last + (1 if compiled_now else 0)
            else:
                compiled_now = size > last
            # post-reset (or census re-enabled): the warm executable
            # serving this call joins the fresh window as its FIRST
            # generation — exactly one, no compile wall credited
            # (nothing compiled during this call)
            window_seed = (not compiled_now and e.generations == 0
                           and size > 0)
            wrapper._last_cache = size
            e.calls += 1
            e.dispatch_s += dt
            if compiled_now:
                e.generations += size - last
                e.compile_s += dt
            elif window_seed:
                e.generations += 1
            new_gen = compiled_now or window_seed
            if new_gen:
                gen = e.generations
        if new_gen:
            # aval capture walks the argument pytrees — off-lock, then
            # published in one assignment (last-writer-wins is fine:
            # both racers saw the same signatures)
            avals = _avalize(args, kwargs)
            with self._lock:
                e.avals = avals
                e.fn_ref = weakref.ref(fn)
            flightrec.event("xprof/exec", executable=e.name,
                            generation=gen,
                            compile_s=(round(dt, 6) if compiled_now
                                       else None))

    def note_measured(self, name: str, step_s: float) -> None:
        """Feed a FENCED per-step time (the bench's value-fenced median)
        so the roofline joins against real device time instead of
        host-side submit time."""
        e = self._entry(name)
        with self._lock:
            e.measured_step_s = float(step_s)

    # -- analysis ---------------------------------------------------------
    def analyze(self, names=None, compile: bool = True) -> Dict[str, dict]:
        """Extract XLA cost/memory analysis for registered jit entries by
        re-lowering against their stored avals. RE-TRACES the function
        bodies (trace/* counters move, jax compile events fire) — run
        outside ``tracecheck.steady_state`` regions, at collection time,
        never per step. ``compile=False`` skips the AOT compile (cost
        analysis only, no memory analysis — cheaper). Backends whose
        analysis is unavailable degrade to the counted fallback."""
        with self._lock:
            todo = [e for e in self._entries.values()
                    if (names is None or e.name in names)
                    and not e.subexec and not e.variants
                    and e.avals is not None
                    and (e.cost_source is None
                         or e.analyzed_gen != e.generations)]
        out = {}
        for e in todo:
            self._analyze_one(e, compile)
            out[e.name] = e.summary()
        return out

    def _analyze_one(self, e: _Entry, do_compile: bool) -> None:
        fn = e.fn_ref() if e.fn_ref is not None else None
        args, kwargs = e.avals
        cost = mem = None
        err = None
        fp: Dict[str, Any] = {}
        if fn is None:
            err = "executable collected before analysis"
        else:
            try:
                lowered = fn.lower(*args, **kwargs)
                cost = _cost_dict(lowered)
                try:
                    mem = _out_bytes_dict(lowered)
                except Exception:
                    mem = None
                if do_compile:
                    compiled = lowered.compile()
                    mem = _memory_dict(compiled) or mem
                    if cost is None:
                        cost = _cost_dict(compiled)
                    fp = _sharding_fingerprint(compiled)
            except Exception as exc:   # analysis must never take down
                err = f"{type(exc).__name__}: {exc}"
        with self._lock:
            e.fingerprint.update(fp)
            if cost is not None:
                e.cost = cost
                e.cost_source = "xla"
            else:
                # counted fallback: input bytes from the avals (plus
                # output bytes when the lowering got far enough)
                counted = {"bytes_accessed": _aval_bytes(args, kwargs)}
                if mem and mem.get("output_bytes"):
                    counted["bytes_accessed"] += mem["output_bytes"]
                e.cost = counted
                e.cost_source = "counted"
            if mem is not None:
                e.memory = mem
            e.analyzed_gen = e.generations
            e.error = err

    # -- roofline ---------------------------------------------------------
    def roofline(self) -> Dict[str, dict]:
        """Per-executable roofline attribution: measured step time joined
        with analytic flops/bytes -> MFU, arithmetic intensity, and the
        compute-vs-HBM-bound verdict (AI against the roof's ridge
        point). Entries without analysis carry what they have."""
        peak_f, peak_b = self._platform_roof()
        ridge = (peak_f / peak_b) if peak_f and peak_b else None
        out: Dict[str, dict] = {}
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            row = e.summary()
            step_s = e.measured_step_s
            if step_s is None and e.calls:
                step_s = e.dispatch_s / e.calls
            cost = e.cost or {}
            flops = cost.get("flops")
            nbytes = cost.get("bytes_accessed")
            if step_s:
                row["step_s"] = round(step_s, 6)
            if flops and nbytes:
                row["arithmetic_intensity"] = flops / nbytes
                if ridge is not None:
                    row["bound"] = ("compute" if flops / nbytes >= ridge
                                    else "hbm")
            if flops and step_s and peak_f:
                row["effective_flops_per_s"] = flops / step_s
                row["mfu"] = flops / step_s / peak_f
            if nbytes and step_s and peak_b:
                row["achieved_bytes_per_s"] = nbytes / step_s
            out[e.name] = row
        return out

    def ledger(self) -> Dict[str, float]:
        """The flat ``xla`` profiler ledger (``OpProfiler.LEDGERS``):
        per-executable roofline numbers under slash-keys plus census
        totals and the HBM watermark gauges — everything numeric, so
        ``/api/metrics`` and ``print_statistics`` render it as-is."""
        rows = self.roofline()
        peak_f, peak_b = self._platform_roof()
        out: Dict[str, float] = {}
        if rows:
            out["executables"] = len(rows)
            out["analyzed"] = sum(1 for r in rows.values() if "cost" in r)
            out["calls"] = sum(r.get("calls", 0) for r in rows.values())
            out["dispatch_s"] = round(sum(r.get("dispatch_s", 0.0)
                                          for r in rows.values()), 6)
            if peak_f:
                out["roof_peak_flops"] = peak_f
            if peak_b:
                out["roof_peak_bytes_per_s"] = peak_b
        for name, r in rows.items():
            cost = r.get("cost", {})
            if r.get("calls"):
                out[f"{name}/calls"] = r["calls"]
                out[f"{name}/dispatch_ms"] = round(
                    r["dispatch_s"] / r["calls"] * 1e3, 4)
            if r.get("generations"):
                out[f"{name}/generations"] = r["generations"]
            if r.get("compile_s"):
                out[f"{name}/compile_s"] = round(r["compile_s"], 4)
            if cost.get("flops"):
                out[f"{name}/flops"] = cost["flops"]
            if cost.get("bytes_accessed"):
                out[f"{name}/bytes"] = cost["bytes_accessed"]
            if r.get("memory", {}).get("temp_bytes") is not None:
                out[f"{name}/temp_bytes"] = r["memory"]["temp_bytes"]
            if "arithmetic_intensity" in r:
                out[f"{name}/ai"] = round(r["arithmetic_intensity"], 4)
            if "mfu" in r:
                out[f"{name}/mfu"] = round(r["mfu"], 6)
            if r.get("bound"):
                out[f"{name}/compute_bound"] = float(r["bound"] == "compute")
            if r.get("cost_source") == "counted":
                out[f"{name}/counted"] = 1.0
        with self._lock:
            wms = {p: dict(w) for p, w in self._watermarks.items()}
        for phase, wm in wms.items():
            out[f"hbm/{phase}/peak_live_bytes"] = wm["peak_live_bytes"]
            out[f"hbm/{phase}/last_live_bytes"] = wm["last_live_bytes"]
            out[f"hbm/{phase}/samples"] = wm["samples"]
            if wm.get("peak_device_bytes"):
                out[f"hbm/{phase}/peak_device_bytes"] = \
                    wm["peak_device_bytes"]
        return out

    # -- HBM watermarks ---------------------------------------------------
    def memory_watermark(self, phase: str = "global") -> Dict[str, Any]:
        """Take one memory census (``system_info.memory_summary`` — the
        SAME function ``/api/health`` serves, never a second walk) and
        fold it into the per-phase peak gauges. Returns the census."""
        if not self._enabled:
            return {}
        from .system_info import memory_summary

        census = memory_summary()
        live = int(census.get("live_buffers", {}).get("bytes", 0))
        dev = sum(int(d.get("bytes_in_use", 0))
                  for d in census.get("devices", []))
        rose = False
        with self._lock:
            wm = self._watermarks.setdefault(phase, {
                "peak_live_bytes": 0, "last_live_bytes": 0,
                "peak_device_bytes": 0, "samples": 0})
            wm["samples"] += 1
            wm["last_live_bytes"] = live
            if live > wm["peak_live_bytes"]:
                wm["peak_live_bytes"] = live
                rose = True
            if dev > wm["peak_device_bytes"]:
                wm["peak_device_bytes"] = dev
                rose = True
            peak = wm["peak_live_bytes"]
        prof = OpProfiler.get()
        prof.gauge("xprof/live_buffer_bytes", live)
        if rose:
            prof.gauge(f"xprof/peak_live_bytes/{phase}", peak)
            flightrec.event("xprof/hbm", phase=phase, live_bytes=live,
                            peak_live_bytes=peak, device_bytes=dev)
        return census

    def watermarks(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {p: dict(w) for p, w in self._watermarks.items()}

    def dump_memory_census(self, path: str) -> str:
        """Write the full memory picture (per-phase watermarks + a fresh
        census) as JSON, atomically — the crash-blackbox companion
        (``memcensus.json`` beside ``blackbox.jsonl``), so OOM-class
        postmortems carry the memory state with no live process."""
        from .system_info import memory_summary

        payload = {"watermarks": self.watermarks(),
                   "census": memory_summary(),
                   "ledger": self.ledger()}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)
        return path


class _Censused:
    """Call-transparent census wrapper around one ``jax.jit`` callable.
    ``__getattr__`` falls through (``.lower``, ``._cache_size``, …) so
    existing AOT/introspection code sees the jit unchanged. The entry is
    looked up by name per dispatch — never captured — so a census reset
    cannot orphan a live wrapper."""

    __slots__ = ("_census", "_name", "_fn", "_fp", "_last_cache")

    def __init__(self, census: ExecutableCensus, name: str, fn,
                 fp: Dict[str, Any]):
        self._census = census
        self._name = name
        self._fn = fn
        self._fp = fp
        self._last_cache = 0

    @property
    def wrapped(self):
        return self._fn

    def __call__(self, *args, **kwargs):
        census = self._census
        if not census._enabled:
            return self._fn(*args, **kwargs)
        t0 = _now()
        out = self._fn(*args, **kwargs)
        census._note_call(self._name, self._fn, self,
                          _now() - t0, args, kwargs)
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)


# -- analysis plumbing -----------------------------------------------------

def _cost_dict(lowered_or_compiled) -> Optional[Dict[str, float]]:
    """Normalize ``cost_analysis()`` output (dict, or per-device list)
    to {flops, bytes_accessed, transcendentals}; None when the backend
    has nothing (the graceful-degradation contract)."""
    try:
        cost = lowered_or_compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not cost:
        return None
    out: Dict[str, float] = {}
    for src, dst in (("flops", "flops"),
                     ("bytes accessed", "bytes_accessed"),
                     ("transcendentals", "transcendentals")):
        v = cost.get(src)
        if v is not None and v > 0:
            out[dst] = float(v)
    return out or None


def _memory_dict(compiled) -> Optional[Dict[str, int]]:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for src, dst in (("argument_size_in_bytes", "argument_bytes"),
                     ("output_size_in_bytes", "output_bytes"),
                     ("temp_size_in_bytes", "temp_bytes"),
                     ("alias_size_in_bytes", "alias_bytes"),
                     ("generated_code_size_in_bytes",
                      "generated_code_bytes")):
        v = getattr(ma, src, None)
        if v is not None:
            out[dst] = int(v)
    return out or None


def _out_bytes_dict(lowered) -> Optional[Dict[str, int]]:
    """Output bytes from the lowering's out_info (pre-compile) — feeds
    the counted fallback when cost analysis is unavailable."""
    info = getattr(lowered, "out_info", None)
    if info is None:
        return None
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(info):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            total += int(np.prod(shape)) * np.dtype(dtype).itemsize
    return {"output_bytes": total}


def _sharding_fingerprint(compiled) -> Dict[str, Any]:
    try:
        ins = compiled.input_shardings
        flat = []
        for group in ins if isinstance(ins, tuple) else (ins,):
            try:
                flat.extend(list(group))
            except TypeError:
                flat.append(group)
        kinds = sorted({type(s).__name__ for s in flat if s is not None})
        return {"input_sharding_kinds": tuple(kinds),
                "input_sharding_count": len(flat)}
    except Exception:
        return {}


def _avalize(args, kwargs):
    """(args, kwargs) with array leaves replaced by ShapeDtypeStruct —
    metadata survives donation; non-array leaves (static scalars, None)
    pass through so a later ``lower()`` reproduces the signature."""
    import jax

    def conv(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            try:
                return jax.ShapeDtypeStruct(tuple(shape), dtype)
            except Exception:
                return x
        return x

    return (jax.tree.map(conv, args), jax.tree.map(conv, kwargs))


def _aval_bytes(args, kwargs) -> int:
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves((args, kwargs)):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            total += int(np.prod(shape)) * np.dtype(dtype).itemsize
    return total


# -- the process-wide census + module facade -------------------------------

_CENSUS = ExecutableCensus()


def get() -> ExecutableCensus:
    return _CENSUS


def configure(enabled: Optional[bool] = None) -> ExecutableCensus:
    return _CENSUS.configure(enabled=enabled)


def enabled() -> bool:
    return _CENSUS._enabled


def reset() -> None:
    _CENSUS.reset()


def set_roof(peak_flops: float, peak_bytes_per_s: float) -> None:
    _CENSUS.set_roof(peak_flops, peak_bytes_per_s)


def register_jit(name: str, fn, *, donate=None, static_argnames=None):
    return _CENSUS.register_jit(name, fn, donate=donate,
                                static_argnames=static_argnames)


def register_aot(name: str, compiled, *, variant: str = "",
                 compile_s: Optional[float] = None) -> None:
    _CENSUS.register_aot(name, compiled, variant=variant,
                         compile_s=compile_s)


def note_subexec(name: str, flops: Optional[float] = None,
                 bytes_accessed: Optional[float] = None, **attrs) -> None:
    _CENSUS.note_subexec(name, flops=flops, bytes_accessed=bytes_accessed,
                         **attrs)


def note_measured(name: str, step_s: float) -> None:
    _CENSUS.note_measured(name, step_s)


def analyze(names=None, compile: bool = True) -> Dict[str, dict]:
    return _CENSUS.analyze(names=names, compile=compile)


def roofline() -> Dict[str, dict]:
    return _CENSUS.roofline()


def ledger() -> Dict[str, float]:
    return _CENSUS.ledger()


def census() -> Dict[str, dict]:
    """Structured snapshot of every entry (no analysis triggered)."""
    with _CENSUS._lock:
        return {n: e.summary() for n, e in _CENSUS._entries.items()}


def memory_watermark(phase: str = "global") -> Dict[str, Any]:
    return _CENSUS.memory_watermark(phase)


def watermarks() -> Dict[str, Dict[str, Any]]:
    return _CENSUS.watermarks()


def dump_memory_census(path: str) -> str:
    return _CENSUS.dump_memory_census(path)
