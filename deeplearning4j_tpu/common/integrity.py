"""Silent-corruption defense: replica fingerprints + checkpoint scrubbing.

Every fault the platform survives elsewhere is *loud* — crashes, hangs,
NaN storms, dead coordinators. The failures this module polices are
*silent*: a flaky core computes wrong bits, a replica desyncs after an
elastic event, a retained checkpoint rots on disk — and training keeps
running on poisoned state with every SLO green. Data-parallel training
gives an exact, free invariant to enforce: **replicated state must be
bitwise-identical across replicas**, and under ZeRO-1 the all_gather'd
tiles must reconstruct one consistent model (the replicated-weight-update
contract of automatic cross-replica sharding, PAPERS.md). At param scale
a per-element host comparison is unaffordable, so verification happens
*in-graph* — the same fraction-of-peak argument as the TPU
distributed-linear-algebra work (arXiv:2112.09017).

Four pieces:

- **In-graph fingerprints** (:func:`fingerprint_tree` /
  :func:`fingerprint_flats`): every leaf is bitcast to uint32 words and
  folded with two commutative reductions — a wrapping sum and an xor —
  combined as ``sum * 2654435761 ^ xor``. Commutativity makes the fold
  *layout-invariant*: the dense tree fold and the Zero1Plan flat-bucket
  fold (restricted to each bucket's unpadded ``[:total]`` prefix, so
  shard padding for different worker counts never leaks in) produce the
  same word for the same params. The wrapper computes the fold under a
  ``lax.cond`` every ``check_every`` steps (one O(params) read, no dense
  materialization on the ZeRO-1 path — it rides the existing flat
  buckets), all_gathers the 4-byte digest across the data axis and
  majority-votes the verdict in-graph (:func:`replica_verdict`). The
  result lands in the telemetry aux: zero extra host syncs, zero
  retraces — the check is a cond arm like the fleet alive-mask.

- **Detection → quarantine** (:class:`IntegrityListener` +
  :class:`ReplicaCorruptionError`): the listener drains the aux with one
  batched readback per dispatch window that contains a checked step and
  raises on divergence, naming the minority replica. The supervisor
  classifies it ``silent_corruption`` and quarantines via the existing
  ``resize(lost_replicas=[k])`` shrink — majority-consistent state is
  re-materialized from a *surviving* replica's shard
  (:func:`materialize_from_survivors`; a naive ``device_get`` of a
  "replicated" array reads shard 0, which may be the poisoned copy).
  An un-attributable divergence (2-way split) falls back to
  checkpoint-restart from the last scrub-verified generation.

- **Checkpoint scrubber** (:class:`CheckpointScrubber`): a background
  thread re-hashes retained committed checkpoints against their manifest
  sha256 on a cadence; a mismatch quarantines the generation in the
  manifest (never deleted — it is evidence) so ``last_checkpoint`` /
  restore / ``verify_group_commit`` skip it.

- **Drills**: :func:`apply_bitflip` deterministically flips one mantissa
  bit of one replica's stored copy of a named tensor between dispatches
  (the ``integrity/fingerprint`` fault site's ``bitflip`` kind) — the
  injected corruption persists in carried state exactly like a flaky
  core's would, making every detection path testable.

Observability: ``integrity/*`` counters feed the profiler's integrity
ledger, ``integrity/fingerprint|divergence|scrub|quarantine`` flight-rec
events anchor the watchtower incident chain (divergence is a detection
anchor, quarantine a mitigation anchor), and the ``replica-consistency``
SLO burns on divergences and quarantined generations.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import faultinject, flightrec
from .profiler import OpProfiler

logger = logging.getLogger("deeplearning4j_tpu")

# Knuth's multiplicative constant — decorrelates the two commutative
# folds so a flip that cancels in the sum still moves the combined word
_FNV = 2654435761


class ReplicaCorruptionError(RuntimeError):
    """In-graph replica-consistency check found divergent state.

    ``replica`` is the majority-voted divergent replica index, or None
    when the divergence is un-attributable (2-way split, or N=2 where
    majority is undefined) — the supervisor then falls back to
    checkpoint-restart from the last scrub-verified generation instead
    of quarantining."""

    def __init__(self, message: str, replica: Optional[int] = None,
                 iteration: Optional[int] = None):
        super().__init__(message)
        self.replica = replica
        self.iteration = iteration


# --- in-graph fingerprint folds --------------------------------------

def _fold_words(a):
    """One array -> (uint32 wrapping-sum, uint32 xor) over its raw bits.

    Bitcast, never value-cast: NaN payloads, -0.0 and denormals all
    participate, so the fold is an exact bit identity. Sub-32-bit dtypes
    widen after the bitcast (bf16 -> uint16 -> uint32); 64-bit dtypes
    bitcast to two uint32 words. Both reductions are commutative, which
    is the whole design: any permutation of the same elements — dense
    tree order or Zero1Plan flat-bucket order — folds to the same pair.
    """
    if a.dtype == jnp.bool_:
        a = a.astype(jnp.uint8)
    nbits = a.dtype.itemsize * 8
    if nbits < 32:
        u = jax.lax.bitcast_convert_type(
            a, jnp.dtype("uint%d" % nbits)).astype(jnp.uint32)
    else:
        # == 32 is a plain bitcast; > 32 yields a trailing word axis
        u = jax.lax.bitcast_convert_type(a, jnp.uint32)
    u = u.reshape(-1)
    s = jnp.sum(u, dtype=jnp.uint32)
    x = jax.lax.reduce(u, np.uint32(0), jax.lax.bitwise_xor, (0,))
    return s, x


def combine_fp(a, b):
    """Fold two digests into one (used to mix params + updater state)."""
    return a * jnp.uint32(_FNV) ^ b


def fingerprint_tree(tree) -> jnp.ndarray:
    """uint32 digest of every leaf's bits; permutation-invariant, so it
    equals :func:`fingerprint_flats` of the same params flattened."""
    s = jnp.zeros((), jnp.uint32)
    x = jnp.zeros((), jnp.uint32)
    for leaf in jax.tree.leaves(tree):
        ls, lx = _fold_words(leaf)
        s = s + ls
        x = x ^ lx
    return s * jnp.uint32(_FNV) ^ x


def fingerprint_flats(plan, flats: Dict[str, Any]) -> jnp.ndarray:
    """Digest of a Zero1Plan flat-bucket dict, folding only each bucket's
    unpadded ``[:total]`` prefix (``plan.unpadded_views``) — shard padding
    depends on the worker count and must never enter the digest. Static
    slices, no gather."""
    s = jnp.zeros((), jnp.uint32)
    x = jnp.zeros((), jnp.uint32)
    for v in plan.unpadded_views(flats).values():
        ls, lx = _fold_words(v)
        s = s + ls
        x = x ^ lx
    return s * jnp.uint32(_FNV) ^ x


def bitwise_neq(a, b):
    """Exact bitwise inequality (float ``!=`` lies about NaN)."""
    if a.dtype == jnp.bool_:
        return jnp.any(a != b)
    nbits = a.dtype.itemsize * 8
    dt = jnp.uint32 if nbits >= 32 else jnp.dtype("uint%d" % nbits)
    return jnp.any(jax.lax.bitcast_convert_type(a, dt)
                   != jax.lax.bitcast_convert_type(b, dt))


def replica_verdict(fp, mismatch, axis: str, do_check):
    """All_gather the per-replica digests and majority-vote in-graph.

    Returns replicated int32 scalars ``(checked, diverged, replica)``:
    ``replica`` is the unique minority index when attribution is
    possible, else -1 (2-way split / N=2 / transport mismatch on more
    than one receiver). The gathers run unconditionally — a 4-byte
    scalar per replica per step, constant cost — so no collective ever
    sits inside a ``lax.cond`` arm; only the O(params) fold is gated."""
    fps = jax.lax.all_gather(fp, axis)
    mis = jax.lax.all_gather(mismatch.astype(jnp.int32), axis)
    n = fps.shape[0]
    support = jnp.sum((fps[None, :] == fps[:, None]).astype(jnp.int32),
                      axis=1)
    fp_div = jnp.any(support < n)
    bad = (support < jnp.max(support)) | (mis > 0)
    n_bad = jnp.sum(bad.astype(jnp.int32))
    diverged = (fp_div | jnp.any(mis > 0)) & do_check
    replica = jnp.where(diverged & (n_bad == 1),
                        jnp.argmax(bad).astype(jnp.int32),
                        jnp.int32(-1))
    return (do_check.astype(jnp.int32), diverged.astype(jnp.int32),
            replica)


# --- host-side digest (serving publish verify, test oracle) -----------

def host_fingerprint(tree) -> int:
    """The same digest computed host-side with numpy — the oracle tests
    compare against the in-graph aux value, and the fleet-publish check
    serving runs after a canary promote. One batched readback."""
    leaves = jax.tree.leaves(tree)
    host = jax.device_get(leaves)
    s = 0
    x = 0
    for a in host:
        a = np.ascontiguousarray(a)
        if a.dtype == np.bool_:
            a = a.astype(np.uint8)
        nbits = a.dtype.itemsize * 8
        u = a.reshape(-1).view("uint%d" % min(nbits, 32))
        if nbits < 32:
            u = u.astype(np.uint32)
        s = (s + int(np.add.reduce(u, dtype=np.uint64) & 0xFFFFFFFF)) \
            & 0xFFFFFFFF
        x ^= int(np.bitwise_xor.reduce(u)) if u.size else 0
    return (s * _FNV ^ x) & 0xFFFFFFFF


# --- listener: aux -> detection --------------------------------------

class IntegrityListener:
    """Drains the in-graph consistency verdict and raises on divergence.

    Duck-typed against the listener SPI (iteration_done/telemetry_done/
    epoch_done). ``wants_telemetry`` turns the telemetry aux on;
    ``wants_telemetry_stats = False`` keeps the heavy per-layer stats
    (and their flat-backward opt-out) off — the aux carries just the
    loss and the four integrity scalars, so the A/B cost of this
    listener *is* the fingerprint. Readback discipline matches
    NanSentinelListener: device values are buffered un-synced and
    drained with ONE batched ``jax.device_get`` per dispatch window —
    and only for windows that contain a checked step, which the host
    knows from the iteration counter without touching the device."""

    POLICIES = ("raise", "warn")

    def __init__(self, check_every: int = 8, policy: str = "raise"):
        if policy not in self.POLICIES:
            raise ValueError("policy must be one of %r" % (self.POLICIES,))
        self.check_every = max(1, int(check_every))
        self.policy = policy
        self.wants_telemetry = True
        self.wants_telemetry_stats = False
        self.wants_integrity = self.check_every
        self.fingerprints: List[Tuple[int, int]] = []
        self.divergences: List[Dict[str, int]] = []
        self._buf: List[Tuple[int, Any]] = []

    def iteration_done(self, model, iteration: int, score) -> None:
        pass

    def epoch_done(self, model, epoch: int) -> None:
        self._drain()

    def telemetry_done(self, model, iteration: int, aux) -> None:
        if "integrity_checked" not in aux:
            return
        self._buf.append((iteration, aux))
        if getattr(model, "_at_dispatch_boundary", True):
            # the in-graph check ran at step `it` iff it % every == 0,
            # and note_steps reports iteration = it + 1
            if any((it - 1) % self.check_every == 0 for it, _ in self._buf):
                self._drain()
            else:
                self._buf.clear()

    def _drain(self) -> None:
        if not self._buf:
            return
        buf, self._buf = self._buf, []
        prof = OpProfiler.get()
        with prof.time_section("telemetry/drain"):
            vals = jax.device_get([
                (a["integrity_checked"], a["integrity_diverged"],
                 a["integrity_replica"], a["integrity_fp"])
                for _, a in buf])
        last_fp = None
        for (it, _), (checked, diverged, replica, fp) in zip(buf, vals):
            if not int(checked):
                continue
            prof.count("integrity/checks")
            last_fp = (it, int(fp))
            self.fingerprints.append(last_fp)
            if int(diverged):
                rep = int(replica)
                prof.count("integrity/divergences")
                flightrec.event("integrity/divergence", severity="error",
                                iteration=it, replica=rep, fp=int(fp))
                self.divergences.append(
                    {"iteration": it, "replica": rep, "fp": int(fp)})
                if self.policy == "raise":
                    raise ReplicaCorruptionError(
                        "replica-consistency fingerprint diverged at "
                        "iteration %d (replica %s)"
                        % (it, rep if rep >= 0 else "unattributable"),
                        replica=rep if rep >= 0 else None, iteration=it)
                logger.warning(
                    "integrity: fingerprint divergence at iteration %d "
                    "(replica %s) — policy=warn, training continues",
                    it, rep if rep >= 0 else "unattributable")
        if last_fp is not None:
            flightrec.event("integrity/fingerprint", iteration=last_fp[0],
                            fp=last_fp[1], checks=len(self.fingerprints))

    def state_dict(self) -> dict:
        return {"fingerprints": [[i, f] for i, f in self.fingerprints[-64:]]}

    def load_state_dict(self, state: dict) -> None:
        self.fingerprints = [(int(i), int(f))
                             for i, f in state.get("fingerprints", [])]


# --- drills: deterministic bitflip injection --------------------------

def _uint_view(buf: np.ndarray) -> np.ndarray:
    if buf.dtype == np.bool_:
        return buf.reshape(-1).view(np.uint8)
    return buf.reshape(-1).view("uint%d" % (buf.dtype.itemsize * 8))


def apply_bitflip(holder, mesh, spec: Dict[str, Any]) -> Dict[str, Any]:
    """Flip one mantissa bit of ONE replica's stored copy of a param.

    The ``integrity/fingerprint`` fault site's ``bitflip`` kind: between
    dispatches, the named replica's per-device copy of a (replicated)
    param leaf gets exactly one bit flipped — the corruption then rides
    the carried training state like a flaky core's output would, and the
    next in-graph check must catch it. Spec fields: ``replica`` (device
    index on the data axis), ``tensor`` (substring of the leaf path;
    default = first floating leaf), ``bit`` (default 12 — a mantissa bit
    for every float dtype in use), ``offset`` (flat element index).

    Implementation detail that makes this a *pure data* fault: the leaf
    is rebuilt with ``jax.make_array_from_single_device_arrays`` keeping
    its replicated sharding, so the step's compiled executable, sharding
    metadata and donation contract are untouched — zero retraces."""
    replica = int(spec.get("replica", 0))
    bit = int(spec.get("bit", 12))
    offset = int(spec.get("offset", 0))
    name = spec.get("tensor")
    devices = list(mesh.devices.flat)
    if not 0 <= replica < len(devices):
        raise ValueError("bitflip replica %d outside mesh of %d"
                         % (replica, len(devices)))

    flat, treedef = jax.tree_util.tree_flatten_with_path(holder._params)
    target_i = None
    for i, (path, leaf) in enumerate(flat):
        label = jax.tree_util.keystr(path)
        if name is not None:
            if name in label:
                target_i = i
                break
        elif jnp.issubdtype(leaf.dtype, jnp.floating):
            target_i = i
            break
    if target_i is None:
        raise ValueError("bitflip: no param leaf matches %r" % (name,))
    path, leaf = flat[target_i]
    label = jax.tree_util.keystr(path)

    from jax.sharding import NamedSharding, PartitionSpec
    arr = leaf
    replicated = (isinstance(arr, jax.Array)
                  and getattr(arr.sharding, "is_fully_replicated", False)
                  and len(arr.addressable_shards) == len(devices))
    if not replicated:
        if (isinstance(arr, jax.Array)
                and not arr.sharding.is_fully_replicated):
            raise ValueError("bitflip target %s is sharded — flip a "
                             "replicated param instead" % label)
        arr = jax.device_put(jnp.asarray(arr),
                             NamedSharding(mesh, PartitionSpec()))
    pieces = []
    for shard in arr.addressable_shards:
        buf = np.array(shard.data)
        if shard.device == devices[replica]:
            words = _uint_view(buf)
            words[offset % words.size] ^= np.asarray(
                1 << bit, dtype=words.dtype)
        pieces.append(jax.device_put(buf, shard.device))
    flipped = jax.make_array_from_single_device_arrays(
        arr.shape, arr.sharding, pieces)
    leaves = [flipped if i == target_i else l
              for i, (_, l) in enumerate(flat)]
    holder._params = jax.tree_util.tree_unflatten(treedef, leaves)
    OpProfiler.get().count("integrity/bitflips_injected")
    logger.warning("integrity: injected bitflip on replica %d tensor %s "
                   "bit %d offset %d", replica, label, bit, offset)
    return {"replica": replica, "tensor": label, "bit": bit,
            "offset": offset}


# --- majority-consistent host materialization -------------------------

def materialize_from_survivors(tree, devices: Sequence, lost:
                               Sequence[int]):
    """Host-materialize carried state reading REPLICATED leaves from a
    surviving replica's shard. ``jax.device_get`` on a replicated array
    reads addressable shard 0 — if replica 0 is the quarantined one,
    the naive path would rebuild the shrunk fleet from the poisoned
    copy. Sharded leaves (ZeRO-1 flat updater state) assemble normally:
    every shard is owned by exactly one replica, so there is nothing to
    choose."""
    lost_set = {int(r) for r in lost}
    survivor = next((i for i in range(len(devices)) if i not in lost_set),
                    None)
    surv_dev = devices[survivor] if survivor is not None else None

    def pull(leaf):
        if (surv_dev is not None and isinstance(leaf, jax.Array)
                and not leaf.is_deleted()
                and getattr(leaf.sharding, "is_fully_replicated", False)):
            for shard in leaf.addressable_shards:
                if shard.device == surv_dev:
                    return np.array(shard.data)
        return np.array(jax.device_get(leaf))

    return jax.tree.map(pull, tree)


# --- checkpoint scrubber ----------------------------------------------

def _flip_file_byte(path: str, offset: int, bit: int) -> None:
    """Scrub-drill helper: rot one byte of an on-disk zip in place."""
    size = os.path.getsize(path)
    if size == 0:
        return
    with open(path, "r+b") as f:
        f.seek(offset % size)
        byte = f.read(1)
        f.seek(offset % size)
        f.write(bytes([byte[0] ^ (1 << (bit % 8))]))


class CheckpointScrubber:
    """Background re-verification of retained committed checkpoints.

    Walks the manifest on a cadence, re-hashes each non-quarantined
    generation against its committed sha256, stamps passing entries with
    a ``scrub`` record (the supervisor's 2-way-split fallback resumes
    only from scrub-verified generations) and quarantines failures in
    the manifest — the file is never deleted; a rotten checkpoint is
    evidence. Single writer thread; manifest read-modify-writes go
    through util.checkpoint's manifest lock, so the scrubber and the
    async CheckpointWriter never tear each other's updates.

    Fault site ``checkpoint/scrub`` fires once per entry per pass with a
    monotonically increasing ordinal: ``transient`` skips the entry this
    pass (verification is retryable by construction — next pass covers
    it), ``bitflip`` rots the zip on disk *before* hashing, turning the
    scrubber's own drill into a self-contained corruption scenario."""

    def __init__(self, directory: str, interval_s: float = 30.0):
        self.directory = directory
        self.interval_s = max(0.05, float(interval_s))
        self.passes = 0
        self._ordinal = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "CheckpointScrubber":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="ckpt-scrubber", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrub_now()
            except Exception:
                logger.exception("integrity: scrub pass failed")

    def scrub_now(self) -> Dict[str, int]:
        """One scrub pass; returns {scanned, verified, quarantined,
        skipped}. Callable directly (tests, drills) — the thread is just
        a cadence."""
        from ..util import checkpoint as _ckpt
        prof = OpProfiler.get()
        summary = {"scanned": 0, "verified": 0, "quarantined": 0,
                   "skipped": 0}
        for entry in _ckpt.read_manifest(self.directory):
            if not isinstance(entry, dict) or "sha256" not in entry:
                summary["skipped"] += 1
                continue
            if entry.get("quarantined"):
                summary["skipped"] += 1
                continue
            ordinal = self._ordinal
            self._ordinal += 1
            try:
                advisory = faultinject.fault_point("checkpoint/scrub",
                                                   ordinal)
            except faultinject.TransientFault:
                prof.count("integrity/scrub_retries")
                summary["skipped"] += 1
                continue
            path = os.path.join(self.directory, entry["file"])
            for spec in advisory:
                if spec.get("kind") == "bitflip":
                    _flip_file_byte(path, int(spec.get("offset", 128)),
                                    int(spec.get("bit", 0)))
            summary["scanned"] += 1
            try:
                ok = _ckpt._sha256_file(path) == entry["sha256"]
            except OSError:
                ok = False
            if ok:
                _ckpt.record_scrub(self.directory, entry["file"], True)
                prof.count("integrity/scrub_verified")
                summary["verified"] += 1
            else:
                _ckpt.record_scrub(self.directory, entry["file"], False,
                                   reason="sha256 mismatch on scrub")
                summary["quarantined"] += 1
        self.passes += 1
        prof.count("integrity/scrub_passes")
        flightrec.event("integrity/scrub", directory=self.directory,
                        **summary)
        return summary
