"""Deterministic fault injection for the training/serving stack.

Reference analog: the failure modes the dl4j-scaleout operational layer is
built around (SURVEY §5.3) — preempted workers, torn checkpoint writes,
flaky input sources, NaN batches, wedged inference replicas. None of them
are reproducible on demand in the wild, so the fault-tolerance code paths
(checkpoint fallback, pipeline retry, replica retirement, kill-resume)
would otherwise only run in production. This module makes every one of
them a *deterministic, step-indexed* event:

- A :class:`FaultPlan` is a list of fault specs, each bound to a SITE
  (``"pipeline/bind"``, ``"pipeline/place"``, ``"train/step"``,
  ``"train/wedge"``, ``"device/loss"``, ``"supervisor/hang"``,
  ``"checkpoint/pre_rename"``, ``"inference/worker"``,
  ``"inference/probe"``, ``"elastic/probe"``, ``"serving/enqueue"``,
  ``"serving/dispatch"``, ``"serving/admission"``,
  ``"autoscale/decide"``, ``"serving/promote"``) and a zero-based
  INDEX at that site (batch ordinal within a fit call, checkpoint commit
  sequence, inference request ordinal, supervisor attempt/probe ordinal,
  serving request ordinal at enqueue / serving batch ordinal at
  dispatch — the deterministic drills behind the serving-smoke bench's
  kill-a-replica run and the wedged-replica deadline tests: ``slow``
  delays a bucket dispatch, ``transient`` forces one requeue-and-retry,
  ``dead_replica`` retires the dispatching replica with its in-flight
  requests requeued).
- Instrumented code calls :func:`fault_point(site, index)` at the matching
  place. Raising kinds (``transient``, ``crash``, ``dead_replica``) raise
  there; ``slow`` sleeps in place; ``preempt`` delivers a real SIGTERM to
  this process (the supervisor's handler turns it into a resumable exit
  at the next step boundary); advisory kinds (``nan``, ``bitflip``) are
  returned for the caller to apply (poison the batch it is about to
  bind; flip one mantissa bit of one replica's stored param copy).
- Plans come from code (:func:`set_plan` — tests) or the environment
  (``DL4J_TPU_FAULT_PLAN`` = inline JSON or ``@/path/to/plan.json`` —
  subprocess kill tests), so a hard-killed worker can be relaunched with
  the exact same fault schedule.

Spec fields: ``{"site": ..., "kind": ..., "index": k}`` plus per-kind
extras — ``times`` (how many calls at that index fire, default 1; the
retry tests use ``times: 2`` to fail two attempts then recover),
``seconds`` (``slow``; for ``wedge`` the block's timeout ceiling),
``mode`` (``crash``: ``"raise"`` raises :class:`SimulatedCrash`,
``"exit"`` hard-kills the process via ``os._exit`` — the no-cleanup
death a preempted worker sees), ``code`` (exit status, default 137),
``replica`` (``device_loss``: which data-axis replica died — the
step-indexed ``device/loss`` site raises :class:`DeviceLostError`
carrying it, the deterministic input to the supervisor's online
shrink-and-continue path).

The ``wedge`` kind simulates a HUNG dispatch (a wedged device, a
deadlocked collective): the calling thread blocks until
:func:`release_wedges` (the supervisor's watchdog calls it when it
abandons the attempt) or the spec's ``seconds`` ceiling, then raises
:class:`WedgeReleased` — the wedged thread unwinds and dies rather than
resuming training concurrently with its restarted replacement.

Every fired fault bumps an ``OpProfiler`` counter
(``faults/<site>/<kind>``), so a run can assert both that injected faults
actually fired and that zero fired in production configs.

Site registry
-------------
The table below is generated-checked against :data:`FAULT_SITES` by
graftlint's ``fault-site-registry`` rule: every site must appear here, in
the registry, at ≥1 ``fault_point`` call site, and in ≥1 test/bench
drill — adding or removing a site without updating all four is a lint
failure, so drills and docs cannot silently drift.

====================  ======================  ==============================
site                  kinds accepted          drill that exercises it
====================  ======================  ==============================
pipeline/bind         transient, slow, nan    test_fault_tolerance retry /
                                              NaN-poison drills; fault-smoke
pipeline/place        transient, slow         test_fault_tolerance H2D
                                              placement-retry drills
train/step            crash, preempt          test_kill_resume exact-parity
                                              kill (exit mode); supervisor
                                              restart drills; fault-smoke
                                              (``preempt`` delivers a real
                                              SIGTERM to this process — the
                                              soak-smoke preemption drill)
train/wedge           wedge                   test_supervisor watchdog
                                              abandonment drill
device/loss           device_loss             test_elastic shrink drills;
                                              elastic-smoke bench
supervisor/hang       wedge, slow             test_supervisor pre-heartbeat
                                              hang drill
checkpoint/pre_rename crash                   test_fault_tolerance
                                              torn-write drills
inference/worker      dead_replica            test_fault_tolerance replica
                                              retirement / pool drills
inference/probe       transient               test_supervisor resurrection
                                              failed-probe backoff
elastic/probe         transient               test_elastic grow-back
                                              probe-failure backoff
serving/enqueue       transient, slow         test_serving admission drills
serving/dispatch      slow, transient,        test_serving wedged-dispatch /
                      dead_replica            requeue / kill drills;
                                              serving-smoke kill drill
serving/admission     transient, slow         test_autoscale deterministic
                                              429 shed drill (transient =
                                              this request is shed; slow =
                                              admission decision stalls)
autoscale/decide      transient               test_autoscale skipped-tick
                                              drill (one controller tick
                                              fails, loop carries on)
serving/promote       transient               test_autoscale / autoscale-
                                              smoke forced-violation drill
                                              (promoted weights "violate"
                                              -> bitwise auto-rollback)
pipeline/stage        device_loss, slow,      test_pipeline_parallel
                      wedge                   kill-a-stage remap drills;
                                              pipeline-parallel-smoke
                                              (``device_loss`` names the
                                              lost STAGE via ``stage``;
                                              ``slow`` = straggler stage;
                                              ``wedge`` = hung schedule)
watchtower/evaluate   transient               test_watchtower skipped-tick
                                              drill; soak-smoke (transient
                                              = one evaluation tick is
                                              skipped, the loop carries
                                              on — alerts lose a sample,
                                              never the state machine)
cluster/init          transient               test_cluster bring-up retry /
                                              deadline-diagnosis drills;
                                              cluster-smoke dead-coordinator
                                              drill (transient = one
                                              refused coordinator connect)
cluster/heartbeat     slow, wedge             test_cluster stale-rank
                                              drills; cluster-smoke (slow =
                                              a late beat, wedge = the
                                              heartbeat thread dies — the
                                              rank goes stale while its
                                              process stays alive)
cluster/barrier       crash                   test_cluster rank-dies-at-
                                              the-fence drill; cluster-
                                              smoke (survivors must time
                                              out naming THIS rank missing
                                              with its staleness)
cluster/commit        crash                   test_cluster torn-group-
                                              commit drill (rank 0 dies
                                              between the fences; the
                                              previous generation stays
                                              restorable)
integrity/fingerprint bitflip                 test_integrity bitflip-
                                              detection / quarantine
                                              drills; integrity-smoke
                                              (``bitflip`` flips one
                                              mantissa bit of ONE
                                              replica's stored param copy
                                              between dispatches — spec
                                              fields ``replica``,
                                              ``tensor``, ``bit``,
                                              ``offset``; the in-graph
                                              fingerprint must catch it)
checkpoint/scrub      transient, bitflip      test_integrity scrubber
                                              drills; integrity-smoke
                                              scrub drill (``bitflip``
                                              rots a byte of the retained
                                              zip on disk before hashing;
                                              ``transient`` skips that
                                              entry this pass — next pass
                                              covers it)
====================  ======================  ==============================
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from . import flightrec

logger = logging.getLogger("deeplearning4j_tpu")

ENV_PLAN = "DL4J_TPU_FAULT_PLAN"

# The central site registry (see the module docstring table, which the
# fault-site-registry lint keeps in sync with this dict): site name ->
# accepted kinds + the drill that exercises it. FaultPlan validates spec
# sites against it so a typo'd site fails at plan construction instead of
# silently never firing.
FAULT_SITES = {
    "pipeline/bind": {
        "kinds": ("transient", "slow", "nan"),
        "drill": "test_fault_tolerance retry/NaN-poison; fault-smoke"},
    "pipeline/place": {
        "kinds": ("transient", "slow"),
        "drill": "test_fault_tolerance H2D placement-retry"},
    "train/step": {
        "kinds": ("crash", "preempt"),
        "drill": "test_kill_resume exact-parity kill; supervisor restarts; "
                 "soak-smoke SIGTERM preemption"},
    "train/wedge": {
        "kinds": ("wedge",),
        "drill": "test_supervisor watchdog abandonment"},
    "device/loss": {
        "kinds": ("device_loss",),
        "drill": "test_elastic shrink; elastic-smoke"},
    "supervisor/hang": {
        "kinds": ("wedge", "slow"),
        "drill": "test_supervisor pre-heartbeat hang"},
    "checkpoint/pre_rename": {
        "kinds": ("crash",),
        "drill": "test_fault_tolerance torn-write"},
    "inference/worker": {
        "kinds": ("dead_replica",),
        "drill": "test_fault_tolerance replica retirement"},
    "inference/probe": {
        "kinds": ("transient",),
        "drill": "test_supervisor resurrection probe backoff"},
    "elastic/probe": {
        "kinds": ("transient",),
        "drill": "test_elastic grow-back probe failure"},
    "serving/enqueue": {
        "kinds": ("transient", "slow"),
        "drill": "test_serving admission drills"},
    "serving/dispatch": {
        "kinds": ("slow", "transient", "dead_replica"),
        "drill": "test_serving wedge/requeue/kill; serving-smoke"},
    "serving/admission": {
        "kinds": ("transient", "slow"),
        "drill": "test_autoscale deterministic-429 shed drill"},
    "autoscale/decide": {
        "kinds": ("transient",),
        "drill": "test_autoscale skipped-tick drill"},
    "serving/promote": {
        "kinds": ("transient",),
        "drill": "test_autoscale forced-violation rollback; "
                 "autoscale-smoke"},
    "pipeline/stage": {
        "kinds": ("device_loss", "slow", "wedge"),
        "drill": "test_pipeline_parallel kill-a-stage remap; "
                 "pipeline-parallel-smoke"},
    "watchtower/evaluate": {
        "kinds": ("transient",),
        "drill": "test_watchtower skipped-tick drill; soak-smoke"},
    "cluster/init": {
        "kinds": ("transient",),
        "drill": "test_cluster bring-up retry/deadline drills; "
                 "cluster-smoke dead-coordinator drill"},
    "cluster/heartbeat": {
        "kinds": ("slow", "wedge"),
        "drill": "test_cluster stale-rank drills; cluster-smoke"},
    "cluster/barrier": {
        "kinds": ("crash",),
        "drill": "test_cluster rank-dies-at-the-fence drill; "
                 "cluster-smoke"},
    "cluster/commit": {
        "kinds": ("crash",),
        "drill": "test_cluster torn-group-commit drill"},
    "integrity/fingerprint": {
        "kinds": ("bitflip",),
        "drill": "test_integrity bitflip-detection/quarantine drills; "
                 "integrity-smoke"},
    "checkpoint/scrub": {
        "kinds": ("transient", "bitflip"),
        "drill": "test_integrity scrubber drills; integrity-smoke "
                 "scrub drill"},
}


class TransientFault(RuntimeError):
    """A retryable failure (flaky storage read, interrupted H2D transfer).
    The input pipeline retries these with bounded exponential backoff."""

    transient = True


class SimulatedCrash(BaseException):
    """An injected process death. Derives from BaseException so ordinary
    ``except Exception`` recovery paths cannot accidentally swallow the
    "kill" — it unwinds like a real SIGKILL would end the process."""


class DeadReplicaFault(RuntimeError):
    """An inference replica dying mid-request (wedged device, OOM-killed
    worker). ParallelInference retires the worker that sees one."""


class DeviceLostError(RuntimeError):
    """A data-parallel TRAINING replica's device disappeared mid-run (ICE
    link down, chip fault, host eviction of one accelerator). Unlike
    :class:`SimulatedCrash` (the whole process dies) the surviving
    replicas — and the holder's dispatch-boundary state — are intact, so
    the supervisor's ``shrink_and_continue`` policy can resize the data
    axis online instead of checkpoint-restarting. ``replica`` names the
    lost data-axis index when known (the injected ``device_loss`` kind
    carries it from the fault spec; real XLA failures usually don't);
    ``stage`` likewise names the lost PIPELINE stage — the
    ``pipeline/stage`` site's drills carry it, and the supervisor's
    ``remap_and_continue`` policy consumes it."""

    def __init__(self, message: str, replica: Optional[int] = None,
                 stage: Optional[int] = None):
        super().__init__(message)
        self.replica = replica
        self.stage = stage


class WedgeReleased(BaseException):
    """An injected wedge unblocked (watchdog abandonment or timeout).
    BaseException for the same reason as SimulatedCrash: the wedged
    thread must DIE, not be resurrected by a broad ``except Exception``
    — its supervisor has already restarted the work elsewhere."""


_wedge_event = threading.Event()


def release_wedges() -> None:
    """Unblock every thread parked in an injected ``wedge`` fault; each
    raises :class:`WedgeReleased` and unwinds. The supervisor's watchdog
    calls this when it abandons a hung attempt."""
    _wedge_event.set()


def reset_wedges() -> None:
    """Re-arm the wedge latch (test setup / after a supervised restart)."""
    _wedge_event.clear()


def is_transient(exc: BaseException) -> bool:
    """The pipeline's retry predicate: opt-in via the ``transient``
    attribute (so user iterators can mark their own retryable errors)."""
    return bool(getattr(exc, "transient", False))


class FaultPlan:
    """A deterministic, consumable schedule of faults. Thread-safe: sites
    fire from the training thread, checkpoint-writer thread, and inference
    workers alike."""

    def __init__(self, faults: List[Dict[str, Any]]):
        self._lock = threading.Lock()
        self._specs = []
        for f in faults:
            spec = dict(f)
            spec.setdefault("times", 1)
            spec["_fired"] = 0
            if "site" not in spec or "kind" not in spec:
                raise ValueError(f"fault spec needs site and kind: {f!r}")
            if spec["site"] not in FAULT_SITES:
                raise ValueError(
                    f"unknown fault site {spec['site']!r} — register it "
                    "in FAULT_SITES (and the docstring table) first")
            self._specs.append(spec)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls(json.loads(text))

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        raw = os.environ.get(ENV_PLAN)
        if not raw:
            return None
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        return cls.from_json(raw)

    def take(self, site: str, index: Optional[int]) -> List[Dict[str, Any]]:
        """Consume and return the specs firing at (site, index). A spec
        with no ``index`` matches every call at its site (up to ``times``)."""
        fired = []
        with self._lock:
            for spec in self._specs:
                if spec["site"] != site or spec["_fired"] >= spec["times"]:
                    continue
                # an indexed spec only matches the SAME index — an
                # index-less call site (e.g. the manifest's own atomic
                # write) never consumes an indexed fault
                want = spec.get("index")
                if want is not None and want != index:
                    continue
                spec["_fired"] += 1
                fired.append(spec)
        return fired

    def fired_count(self, site: Optional[str] = None) -> int:
        with self._lock:
            return sum(s["_fired"] for s in self._specs
                       if site is None or s["site"] == site)


_plan_lock = threading.Lock()
_plan: Optional[FaultPlan] = None
_env_checked = False


def get_plan() -> Optional[FaultPlan]:
    """The active plan: set_plan() wins; otherwise DL4J_TPU_FAULT_PLAN is
    parsed once per process. None (the overwhelmingly common case) keeps
    fault_point() to a single attribute check."""
    global _plan, _env_checked
    with _plan_lock:
        if _plan is None and not _env_checked:
            _env_checked = True
            _plan = FaultPlan.from_env()
        return _plan


def set_plan(plan: Optional[FaultPlan]) -> None:
    global _plan, _env_checked
    reset_wedges()   # a stale release must not defang the new plan's wedges
    with _plan_lock:
        _plan = plan
        _env_checked = True   # an explicit None must not resurrect the env plan


def clear_plan() -> None:
    """Reset to 'no plan, env re-read on next use' (test teardown)."""
    global _plan, _env_checked
    reset_wedges()
    with _plan_lock:
        _plan = None
        _env_checked = False


def fault_point(site: str, index: Optional[int] = None) -> List[Dict[str, Any]]:
    """The instrumentation hook. Raising/sleeping kinds act here; advisory
    specs (``nan`` — and any unrecognized kind) are returned for the call
    site to apply. Returns [] when no plan is active (the hot-path cost is
    one function call + one lock-free None check)."""
    plan = _plan if _env_checked else get_plan()
    if plan is None:
        return []
    fired = plan.take(site, index)
    if not fired:
        return []
    from .profiler import OpProfiler

    prof = OpProfiler.get()
    advisory = []
    for spec in fired:
        kind = spec["kind"]
        prof.count(f"faults/{site}/{kind}")
        # timeline entry BEFORE the fault acts: a crash/wedge that
        # unwinds from here is already on the record for the black box.
        # A replica-addressed spec (bitflip, device_loss) stamps the
        # replica on the event — the incident chain's cause anchor then
        # NAMES the corrupted replica, not just the site.
        extra = ({"replica": spec["replica"]} if "replica" in spec else {})
        flightrec.event("fault/fired", severity="warn", site=site,
                        kind=kind, index=index, **extra)
        logger.warning("faultinject: firing %s at %s[%s]", kind, site, index)
        if kind == "slow":
            time.sleep(float(spec.get("seconds", 0.1)))
        elif kind == "wedge":
            _wedge_event.wait(timeout=float(spec.get("seconds", 300.0)))
            raise WedgeReleased(
                f"injected wedge at {site}[{index}] released")
        elif kind == "transient":
            raise TransientFault(
                f"injected transient fault at {site}[{index}]")
        elif kind == "dead_replica":
            raise DeadReplicaFault(
                f"injected replica death at {site}[{index}]")
        elif kind == "device_loss":
            # step-indexed, names a replica or a pipeline stage: the
            # deterministic elastic drills (site "device/loss" feeds the
            # supervisor's shrink-and-continue via .replica; site
            # "pipeline/stage" feeds remap-and-continue via .stage)
            rep = spec.get("replica")
            stg = spec.get("stage")
            raise DeviceLostError(
                f"injected device loss at {site}[{index}]"
                + (f" (replica {rep})" if rep is not None else "")
                + (f" (stage {stg})" if stg is not None else ""),
                replica=rep, stage=stg)
        elif kind == "crash":
            if spec.get("mode", "raise") == "exit":
                os._exit(int(spec.get("code", 137)))
            raise SimulatedCrash(f"injected crash at {site}[{index}]")
        elif kind == "preempt":
            # a REAL SIGTERM to our own pid — the supervisor's installed
            # handler sets its preempt flag and training unwinds at the
            # next step boundary, exactly the eviction a borg/k8s reclaim
            # delivers. Nothing raises here: the signal is the fault.
            import signal as _signal

            os.kill(os.getpid(), _signal.SIGTERM)
        else:
            advisory.append(spec)
    return advisory


def retry_call(fn, what: str, max_retries: int = 3,
               base_delay_s: float = 0.05, max_delay_s: float = 2.0):
    """Run ``fn()`` retrying TRANSIENT failures (:func:`is_transient`)
    with bounded exponential backoff. Non-transient exceptions and the
    final exhausted attempt propagate unchanged. Every retry bumps
    ``pipeline/retries`` and the backoff wall time is ledgered under the
    ``pipeline/retry_backoff`` profiler section — the fault-smoke bench
    and tests assert recovery happened (and didn't in clean runs)."""
    from .profiler import OpProfiler

    prof = OpProfiler.get()
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:
            if not is_transient(e) or attempt >= max_retries:
                raise
            delay = min(base_delay_s * (2 ** attempt), max_delay_s)
            logger.warning("%s failed transiently (%s); retry %d/%d in "
                           "%.2fs", what, e, attempt + 1, max_retries, delay)
            prof.count("pipeline/retries")
            with prof.time_section("pipeline/retry_backoff"):
                time.sleep(delay)
            attempt += 1
