"""Watchtower: SLO error budgets, burn-rate alerting, incident reports.

PRs 2/10/15 left the repo exporting a flight recorder, Prometheus
metrics, per-executable rooflines, HBM watermarks and a crash blackbox —
but nothing *watched* those signals: an operator had to notice a shed
storm or a restart budget burning down by staring at ``/api/metrics``.
This module is the missing control loop, the reference stack's
``StatsListener``/remote-UI monitoring role (SURVEY §5.5) rebuilt as SRE
practice:

SLOs & error budgets
--------------------
An :class:`SLO` is a declarative objective over signals the repo already
exports (serving per-class p99 and sheds, supervisor restart/storm
counters, fleet NaN culls, tracecheck violations, xprof retrace
generations, HBM watermarks). Two sampler shapes cover all of them:

- ``kind="ratio"``: the sampler returns CUMULATIVE ``(bad, total)``
  counts (e.g. failed vs served requests) — availability-style SLOs;
- ``kind="gauge"``: the sampler returns truthy when THIS evaluation tick
  violates (p99 over budget, watermark over ceiling, a counter moved) —
  each tick contributes one compliance sample.

Both reduce to a cumulative ``(t, bad, total)`` series per SLO, from
which the rolling **error budget** (allowed bad fraction over
``period_s``) and **burn rates** fall out as window deltas.

Multi-window burn-rate alerting
-------------------------------
À la the SRE workbook: burn rate over a window = (observed bad fraction
/ budget). A **page** fires when both the fast (5 m) and mid (1 h)
windows burn ≥ ``page_burn`` (14.4× ≈ budget gone in <2 days); a
**warn** when both the mid and slow (6 h) windows burn ≥ ``warn_burn``
(6×). Raising is immediate; clearing takes ``clear_ticks`` consecutive
clean evaluations (hysteresis — no flapping). Every transition emits a
``watchtower/alert`` flight-recorder event, bumps ``watchtower/*``
profiler counters, and moves the ``watchtower/alert_state/<slo>`` gauge
(0 ok / 1 warn / 2 page) that ``/api/metrics`` re-exports as
``dl4j_alert_state``.

Incident reports
----------------
Every alert firing — and every supervisor failure classification, via
:func:`note_supervisor_failure` — triggers :meth:`Watchtower.
assemble_incident`: walk the flight-recorder ring backwards from the
triggering event, follow correlation ids across subsystems, and join the
blackbox tail, the profiler ledger snapshot, the HBM watermarks and the
executable-census rows into one ``incident-<id>.json`` (atomic
tmp+rename, beside the blackbox) with a derived
cause→detection→mitigation→recovery chain. Open incidents are
re-assembled every evaluation tick until their chain completes (or a
timeout), so mitigation/recovery events that land *after* detection
still make the report. ``GET /api/incidents`` lists and serves them.

The evaluation tick is itself a registered fault site
(``watchtower/evaluate``, kind ``transient`` = one skipped tick) so the
soak can prove a wobbly evaluator loses one sample, not the alert.

Threading: one daemon evaluator thread ticks at ``interval_s`` while
callers (HTTP handlers, benches, the supervisor hook) read stats and
open incidents concurrently — ``Watchtower`` is registered in
graftlint's SHARED_CLASSES and every state mutation holds ``_lock``.
Sampling, event emission and file IO happen outside the lock.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import faultinject, flightrec
from .profiler import OpProfiler

logger = logging.getLogger("deeplearning4j_tpu")

# alert states (also the wire values of dl4j_alert_state)
OK, WARN, PAGE = 0, 1, 2
_STATE_NAMES = {OK: "ok", WARN: "warn", PAGE: "page"}

# chain-derivation anchors: which registered event names can play which
# role in a cause→detection→mitigation→recovery chain
_CAUSE_NAMES = ("fault/fired", "tracecheck/violation", "cluster/rank_lost")
_DETECTION_NAMES = ("watchtower/alert", "supervisor/attempt_failed",
                    "supervisor/watchdog_fire", "supervisor/give_up",
                    "cluster/barrier", "integrity/divergence")
_MITIGATION_NAMES = ("supervisor/restart", "supervisor/preempted",
                     "elastic/resize", "pipeline/remap",
                     "serving/rollback", "serving/retire", "serving/shed",
                     "autoscale/scale", "fleet/cull", "fleet/nan_cull",
                     "cluster/group_restart", "integrity/quarantine")
_RECOVERY_NAMES = ("supervisor/attempt_start", "supervisor/completed",
                   "checkpoint/restore", "inference/resurrected",
                   "serving/promote", "fleet/spawn", "cluster/form")


# -- samplers --------------------------------------------------------------

def counter_ratio_sampler(bad: Tuple[str, ...],
                          total: Tuple[str, ...]) -> Callable[[], Tuple[int, int]]:
    """Ratio sampler over profiler counters: cumulative (bad, total)."""
    def sample() -> Tuple[int, int]:
        prof = OpProfiler.get()
        return (sum(prof.counter_value(n) for n in bad),
                sum(prof.counter_value(n) for n in total))
    return sample


def counter_increment_sampler(*names: str) -> Callable[[], bool]:
    """Gauge sampler that violates on any increment of the summed
    counters since the previous tick. The first call arms (never
    violates) — a watchtower attached mid-run must not page on history."""
    state: Dict[str, Optional[int]] = {"last": None}

    def sample() -> bool:
        prof = OpProfiler.get()
        cur = sum(prof.counter_value(n) for n in names)
        prev, state["last"] = state["last"], cur
        return prev is not None and cur > prev
    return sample


def threshold_sampler(value_fn: Callable[[], Optional[float]],
                      ceiling: float) -> Callable[[], bool]:
    """Gauge sampler that violates while ``value_fn()`` exceeds
    ``ceiling`` (None = no reading = compliant)."""
    def sample() -> bool:
        try:
            v = value_fn()
        except Exception:
            return False
        return v is not None and v > ceiling
    return sample


class SLO:
    """One declarative objective. ``budget`` is the allowed bad fraction
    over ``period_s`` (0.001 = 99.9 %). ``incident`` picks what an alert
    firing does: ``"open"`` assembles a fresh incident, ``"attach"``
    joins the newest open incident for the same correlation family
    (supervisor-domain SLOs, whose failures already opened one via
    :func:`note_supervisor_failure`), ``"none"`` alerts only."""

    def __init__(self, name: str, sampler: Callable, budget: float,
                 kind: str = "gauge", description: str = "",
                 fast_s: float = 300.0, mid_s: float = 3600.0,
                 slow_s: float = 21600.0, page_burn: float = 14.4,
                 warn_burn: float = 6.0, clear_ticks: int = 3,
                 period_s: float = 86400.0, incident: str = "open"):
        if kind not in ("ratio", "gauge"):
            raise ValueError(f"kind must be 'ratio' or 'gauge', got {kind!r}")
        if incident not in ("open", "attach", "none"):
            raise ValueError(f"incident must be open/attach/none, "
                             f"got {incident!r}")
        if not 0.0 < budget <= 1.0:
            raise ValueError(f"budget must be in (0, 1], got {budget}")
        self.name = name
        self.sampler = sampler
        self.budget = float(budget)
        self.kind = kind
        self.description = description
        self.fast_s = float(fast_s)
        self.mid_s = float(mid_s)
        self.slow_s = float(slow_s)
        self.page_burn = float(page_burn)
        self.warn_burn = float(warn_burn)
        self.clear_ticks = max(1, int(clear_ticks))
        self.period_s = float(period_s)
        self.incident = incident


class _SloState:
    """Per-SLO mutable slot owned by the Watchtower (under its lock)."""

    def __init__(self) -> None:
        self.samples: List[Tuple[float, float, float]] = []  # (t, bad, tot)
        self.bad = 0.0           # cumulative (gauge kind accumulates here)
        self.total = 0.0
        self.state = OK
        self.pending = 0         # consecutive ticks below current state
        self.burns = (0.0, 0.0, 0.0)
        self.transitions = 0


def _window_burn(samples: List[Tuple[float, float, float]], now: float,
                 window_s: float, budget: float) -> float:
    """Burn rate over the trailing window: (Δbad/Δtotal)/budget, with
    the window start read from the newest sample at/older than it (the
    first sample when the series is younger than the window)."""
    if len(samples) < 2:
        return 0.0
    base = samples[0]
    start = now - window_s
    for s in reversed(samples):
        if s[0] <= start:
            base = s
            break
    db = samples[-1][1] - base[1]
    dt = samples[-1][2] - base[2]
    if dt <= 0:
        return 0.0
    return (db / dt) / budget


class Watchtower:
    """The evaluator: samples every SLO at ``interval_s`` (daemon thread
    via :meth:`start`, or deterministically via :meth:`evaluate_now`),
    runs the multi-window burn-rate state machine, and owns the incident
    registry under ``incident_dir``."""

    def __init__(self, slos: List[SLO], interval_s: float = 5.0,
                 incident_dir: Optional[str] = None,
                 ring_context: int = 400, lookback_s: float = 60.0,
                 finalize_after_s: float = 120.0, enabled: bool = True):
        names = [s.name for s in slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self._lock = threading.RLock()
        self._slos: Dict[str, SLO] = {s.name: s for s in slos}
        self._states: Dict[str, _SloState] = {n: _SloState() for n in names}
        self.interval_s = float(interval_s)
        self.incident_dir = incident_dir
        self.ring_context = int(ring_context)
        self.lookback_s = float(lookback_s)
        self.finalize_after_s = float(finalize_after_s)
        self._enabled = bool(enabled)
        self._tick = 0
        self._skipped = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._incident_seq = 0
        self._incidents: Dict[str, Dict[str, Any]] = {}   # id -> spec

    # -- lifecycle --------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def configure(self, enabled: Optional[bool] = None) -> "Watchtower":
        with self._lock:
            if enabled is not None:
                self._enabled = bool(enabled)
        return self

    def start(self) -> "Watchtower":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="watchtower", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            t, self._thread = self._thread, None
        self._stop.set()
        if t is not None:
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_now()
            except Exception:
                logger.warning("watchtower: evaluation tick failed",
                               exc_info=True)

    # -- evaluation -------------------------------------------------------
    def evaluate_now(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One evaluation tick. ``now`` (monotonic seconds) is
        injectable so tests drive the window math without sleeping.
        Returns a summary; a tick skipped by the ``watchtower/evaluate``
        transient drill reports ``skipped=True`` with no state change."""
        if not self._enabled:
            return {"tick": self._tick, "skipped": True, "states": {}}
        with self._lock:
            ordinal = self._tick
            self._tick = ordinal + 1
        try:
            faultinject.fault_point("watchtower/evaluate", ordinal)
        except faultinject.TransientFault:
            with self._lock:
                self._skipped += 1
            OpProfiler.get().count("watchtower/skipped_evals")
            return {"tick": ordinal, "skipped": True, "states": {}}
        if now is None:
            now = time.monotonic()
        prof = OpProfiler.get()
        prof.count("watchtower/evaluations")

        # sample OUTSIDE the lock (samplers read other subsystems' locks)
        readings: Dict[str, Any] = {}
        for name, slo in self._slos.items():
            try:
                readings[name] = slo.sampler()
            except Exception:
                logger.warning("watchtower: sampler for SLO %r failed",
                               name, exc_info=True)
                readings[name] = None

        transitions: List[Tuple[str, int, int, Tuple[float, ...], float]] = []
        summary: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for name, slo in self._slos.items():
                st = self._states[name]
                self._absorb(slo, st, readings.get(name), now)
                burns = tuple(
                    _window_burn(st.samples, now, w, slo.budget)
                    for w in (slo.fast_s, slo.mid_s, slo.slow_s))
                st.burns = burns
                target = OK
                if burns[0] >= slo.page_burn and burns[1] >= slo.page_burn:
                    target = PAGE
                elif burns[1] >= slo.warn_burn and burns[2] >= slo.warn_burn:
                    target = WARN
                frm = st.state
                if target > st.state:        # raise immediately
                    st.state = target
                    st.pending = 0
                elif target < st.state:      # clear only after N clean ticks
                    st.pending += 1
                    if st.pending >= slo.clear_ticks:
                        st.state = target
                        st.pending = 0
                else:
                    st.pending = 0
                if st.state != frm:
                    st.transitions += 1
                    transitions.append((name, frm, st.state, burns,
                                        self._budget_remaining(slo, st, now)))
                summary[name] = {"state": st.state,
                                 "fast_burn": round(burns[0], 4),
                                 "mid_burn": round(burns[1], 4),
                                 "slow_burn": round(burns[2], 4)}

        for name, frm, to, burns, remaining in transitions:
            self._on_transition(name, frm, to, burns, remaining)
        self._refresh_incidents()
        return {"tick": ordinal, "skipped": False, "states": summary}

    @staticmethod
    def _absorb(slo: SLO, st: _SloState, reading: Any, now: float) -> None:
        """Fold one sampler reading into the cumulative series."""
        if slo.kind == "ratio":
            if reading is None:
                return
            bad, total = float(reading[0]), float(reading[1])
            if st.samples and (bad < st.bad or total < st.total):
                # counters went backwards (profiler reset) — re-base
                st.samples = []
            st.bad, st.total = bad, total
        else:
            st.bad += 1.0 if reading else 0.0
            st.total += 1.0
        st.samples.append((now, st.bad, st.total))
        # bound the series to what the slow window + period math can use
        horizon = now - max(slo.slow_s, slo.period_s) - 1.0
        while len(st.samples) > 2 and st.samples[1][0] < horizon:
            st.samples.pop(0)

    @staticmethod
    def _budget_remaining(slo: SLO, st: _SloState, now: float) -> float:
        """Fraction of the period's error budget still unspent."""
        if len(st.samples) < 2:
            return 1.0
        base = st.samples[0]
        start = now - slo.period_s
        for s in reversed(st.samples):
            if s[0] <= start:
                base = s
                break
        db = st.samples[-1][1] - base[1]
        dt = st.samples[-1][2] - base[2]
        if dt <= 0:
            return 1.0
        return max(0.0, 1.0 - (db / dt) / slo.budget)

    def _on_transition(self, name: str, frm: int, to: int,
                       burns: Tuple[float, ...], remaining: float) -> None:
        prof = OpProfiler.get()
        sev = "error" if to == PAGE else "warn" if to == WARN else "info"
        flightrec.event("watchtower/alert", severity=sev, slo=name,
                        frm=_STATE_NAMES[frm], to=_STATE_NAMES[to],
                        fast_burn=round(burns[0], 4),
                        mid_burn=round(burns[1], 4),
                        slow_burn=round(burns[2], 4),
                        budget_remaining=round(remaining, 4))
        prof.count("watchtower/alerts")
        if to == PAGE:
            prof.count("watchtower/pages")
        elif to == WARN:
            prof.count("watchtower/warns")
        else:
            prof.count("watchtower/clears")
        prof.gauge(f"watchtower/alert_state/{name}", to)
        slo = self._slos[name]
        if to > frm and slo.incident != "none":
            self.assemble_incident(
                kind="alert", reason=f"{name} {_STATE_NAMES[to]}",
                slo=name, attach_only=(slo.incident == "attach"))

    def alert_states(self) -> Dict[str, int]:
        with self._lock:
            return {n: st.state for n, st in self._states.items()}

    def stats(self) -> Dict[str, float]:
        """The ``watchtower`` profiler ledger (flat, numeric): per-SLO
        state / fast burn / budget remaining plus engine totals."""
        out: Dict[str, float] = {}
        now = time.monotonic()
        with self._lock:
            out["slos"] = len(self._slos)
            out["evaluations"] = self._tick
            out["skipped_evals"] = self._skipped
            out["incidents_open"] = sum(
                1 for i in self._incidents.values() if not i["finalized"])
            out["incidents_total"] = len(self._incidents)
            for name, st in self._states.items():
                slo = self._slos[name]
                out[f"state/{name}"] = st.state
                out[f"fast_burn/{name}"] = round(st.burns[0], 4)
                out[f"budget_remaining/{name}"] = round(
                    self._budget_remaining(slo, st, now), 4)
        return out

    # -- incidents --------------------------------------------------------
    @staticmethod
    def _corr_family(corr: Optional[str]) -> Optional[str]:
        """``inc3.a2`` -> ``inc3`` (one supervised incarnation = one
        family); anything else is its own family."""
        if corr and ".a" in corr and corr.startswith("inc"):
            return corr.split(".a", 1)[0]
        return corr

    def assemble_incident(self, kind: str, reason: str,
                          corr: Optional[str] = None,
                          slo: Optional[str] = None,
                          attach_only: bool = False,
                          attachments: Optional[Dict[str, Any]] = None
                          ) -> Optional[str]:
        """Open (or join) an incident and write its report. Returns the
        report path, or None when assembly is off (no ``incident_dir``)
        or an ``attach_only`` alert found nothing to join.
        ``attachments`` are caller-supplied payloads carried verbatim in
        the report (the cluster supervisor attaches the merged per-rank
        blackboxes here — one incident file tells the whole group's
        story)."""
        if self.incident_dir is None or not self._enabled:
            return None
        if corr is None:
            corr = flightrec.get().correlation()
        family = self._corr_family(corr)
        prof = OpProfiler.get()
        with self._lock:
            joined = None
            for inc in reversed(list(self._incidents.values())):
                if inc["finalized"]:
                    continue
                if (slo is not None and inc.get("slo") == slo) or \
                        (family is not None
                         and self._corr_family(inc.get("corr")) == family):
                    joined = inc
                    break
            if joined is not None:
                joined["alerts"].append(
                    {"kind": kind, "reason": reason, "slo": slo,
                     "corr": corr, "t": time.time()})
                if attachments:
                    joined.setdefault("attachments", {}).update(attachments)
                inc = joined
            elif attach_only:
                return None
            else:
                self._incident_seq += 1
                iid = f"{self._incident_seq:04d}"
                inc = {"id": iid, "kind": kind, "reason": reason,
                       "slo": slo, "corr": corr,
                       "opened_t": time.time(),
                       "opened_m": time.monotonic(),
                       "attachments": dict(attachments or {}),
                       "alerts": [], "finalized": False, "resolved": False,
                       "path": os.path.join(self.incident_dir,
                                            f"incident-{iid}.json")}
                self._incidents[inc["id"]] = inc
        if joined is None:
            prof.count("watchtower/incidents")
            flightrec.event("watchtower/incident", severity="warn",
                            id=inc["id"], kind=kind, reason=reason,
                            path=inc["path"])
        self._write_report(inc)
        return inc["path"]

    def _refresh_incidents(self) -> None:
        with self._lock:
            open_incs = [i for i in self._incidents.values()
                         if not i["finalized"]]
        for inc in open_incs:
            report = self._write_report(inc)
            age = time.monotonic() - inc["opened_m"]
            slo_ok = inc.get("slo") is None or \
                self.alert_states().get(inc["slo"], OK) == OK
            done = (report["complete"] and slo_ok) or \
                age > self.finalize_after_s
            if done:
                with self._lock:
                    inc["finalized"] = True
                    inc["resolved"] = report["complete"]
                self._write_report(inc)
                OpProfiler.get().count("watchtower/incidents_finalized")
                flightrec.event("watchtower/incident", severity="info",
                                id=inc["id"], resolved=report["complete"],
                                path=inc["path"])

    # -- report assembly --------------------------------------------------
    def _select_events(self, inc: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Walk the ring backwards from the incident's anchor: keep
        every event in its correlation family plus every non-info or
        chain-anchor event inside the lookback window (and anything
        after the anchor — mitigation/recovery land later)."""
        family = self._corr_family(inc.get("corr"))
        floor = inc["opened_m"] - self.lookback_s
        sel: List[Dict[str, Any]] = []
        for e in reversed(flightrec.get().snapshot()):
            if len(sel) >= self.ring_context:
                break
            in_family = family is not None and \
                self._corr_family(e.get("corr")) == family
            interesting = e["sev"] != "info" or \
                e["name"] in _CAUSE_NAMES + _DETECTION_NAMES + \
                _MITIGATION_NAMES + _RECOVERY_NAMES
            if in_family or (e["m"] >= floor and interesting):
                sel.append(e)
        sel.reverse()
        return sel

    def _derive_chain(self, inc: Dict[str, Any],
                      evs: List[Dict[str, Any]]) -> Dict[str, Any]:
        def brief(e: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
            if e is None:
                return None
            return {"name": e["name"], "sev": e["sev"], "t": e["t"],
                    "seq": e["seq"], "corr": e.get("corr"),
                    "attrs": e.get("attrs", {})}

        # For supervisor-opened incidents prefer events carrying the
        # incident's exact correlation id — a second fault in the same
        # incarnation must not anchor on the first attempt's events.
        exact = inc.get("corr") if inc["kind"] != "alert" else None
        # The detection event is what TRIGGERED assembly (the supervisor
        # hook and the alert transition both emit it immediately before
        # opening), so it can never predate the opening by more than an
        # evaluator tick. Bounding the scan there keeps a fresh
        # supervisor's recycled correlation id (two drills both running
        # as inc1.a1) from anchoring detection on a PRIOR incident's
        # events that happen to share the string.
        det_floor = inc["opened_m"] - max(1.0, 2.0 * self.interval_s)

        def _scan_detection(pool: List[Dict[str, Any]]):
            for e in pool:
                if e["name"] not in _DETECTION_NAMES or \
                        e["m"] < det_floor:
                    continue
                if inc["kind"] == "alert":
                    if e["name"] == "watchtower/alert" and \
                            e["attrs"].get("slo") == inc.get("slo") and \
                            e["attrs"].get("to") != "ok":
                        return e
                elif e["name"] != "watchtower/alert":
                    return e
            return None

        evs_exact = [e for e in evs if e.get("corr") == exact] \
            if exact is not None else evs
        detection = _scan_detection(evs_exact) or _scan_detection(evs)
        cause = None
        det_seq = detection["seq"] if detection else None
        for pool in ((evs_exact, evs) if exact is not None else (evs,)):
            for e in reversed(pool):
                if e["name"] in _CAUSE_NAMES and \
                        (det_seq is None or e["seq"] <= det_seq):
                    cause = e
                    break
            if cause is not None:
                break
        anchor = cause["seq"] if cause else det_seq
        mitigation = None
        if anchor is not None:
            for e in evs:
                if e["seq"] > anchor and e["name"] in _MITIGATION_NAMES:
                    mitigation = e
                    break
        recovery = None
        if mitigation is not None:
            for e in evs:
                if e["seq"] <= mitigation["seq"]:
                    continue
                if e["name"] in _RECOVERY_NAMES:
                    recovery = e
                    break
                # an alert clearing back to ok is itself the recovery
                # anchor for purely alert-detected incidents
                if e["name"] == "watchtower/alert" and \
                        e["attrs"].get("slo") == inc.get("slo") and \
                        e["attrs"].get("to") == "ok":
                    recovery = e
                    break
        chain = {"cause": brief(cause), "detection": brief(detection),
                 "mitigation": brief(mitigation),
                 "recovery": brief(recovery)}
        chain["complete"] = all(chain[k] is not None for k in
                                ("cause", "detection", "mitigation",
                                 "recovery"))
        return chain

    def _write_report(self, inc: Dict[str, Any]) -> Dict[str, Any]:
        evs = self._select_events(inc)
        chain = self._derive_chain(inc, evs)
        prof = OpProfiler.get()
        try:
            ledgers = prof.ledger_stats()
        except Exception:
            ledgers = {}
        watermarks: Dict[str, float] = {}
        census: Dict[str, float] = {}
        try:
            from . import xprof
            for k, v in xprof.ledger().items():
                if k.startswith("hbm/"):
                    watermarks[k] = v
                else:
                    census[k] = v
        except Exception:
            pass
        blackbox = None
        bb = last_blackbox()
        if bb is not None:
            tail: List[Any] = []
            try:
                with open(bb, "r", encoding="utf-8") as f:
                    for line in f.readlines()[-16:]:
                        try:
                            tail.append(json.loads(line))
                        except ValueError:
                            pass
            except OSError:
                pass
            blackbox = {"path": bb, "tail": tail}
        report = {
            "id": inc["id"], "kind": inc["kind"], "reason": inc["reason"],
            "slo": inc.get("slo"), "corr": inc.get("corr"),
            "opened_t": inc["opened_t"], "updated_t": time.time(),
            "resolved": inc["resolved"], "finalized": inc["finalized"],
            "complete": chain["complete"], "chain": chain,
            "alerts": list(inc["alerts"]), "events": evs,
            "attachments": inc.get("attachments", {}),
            "blackbox": blackbox, "ledgers": ledgers,
            "watermarks": watermarks, "census": census,
        }
        try:
            os.makedirs(self.incident_dir, exist_ok=True)
            tmp = inc["path"] + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(report, f, default=str)
            os.replace(tmp, inc["path"])
        except OSError:
            logger.warning("watchtower: incident write to %s failed",
                           inc["path"], exc_info=True)
        return report

    def incidents(self) -> List[Dict[str, Any]]:
        """Newest-first incident index (metadata only, for HTTP)."""
        with self._lock:
            incs = sorted(self._incidents.values(),
                          key=lambda i: i["id"], reverse=True)
            return [{"id": i["id"], "kind": i["kind"],
                     "reason": i["reason"], "slo": i.get("slo"),
                     "corr": i.get("corr"), "opened_t": i["opened_t"],
                     "finalized": i["finalized"],
                     "resolved": i["resolved"], "path": i["path"]}
                    for i in incs]

    def last_incident(self) -> Optional[Dict[str, Any]]:
        idx = self.incidents()
        if not idx:
            return None
        newest = idx[0]
        tail = None
        try:
            with open(newest["path"], "r", encoding="utf-8") as f:
                rep = json.load(f)
            tail = {"chain": rep.get("chain"),
                    "complete": rep.get("complete"),
                    "events": rep.get("events", [])[-8:]}
        except (OSError, ValueError):
            pass
        return {**newest, "tail": tail}


# -- process-wide installation + module facade -----------------------------

_TOWER: Optional[Watchtower] = None
_tower_lock = threading.Lock()
_LAST_BLACKBOX: Optional[str] = None


def install(tower: Watchtower) -> Watchtower:
    """Make ``tower`` the process-wide instance the supervisor hook,
    ``/api/metrics`` and ``/api/health`` consult. Returns it."""
    global _TOWER
    with _tower_lock:
        _TOWER = tower
    return tower


def uninstall() -> None:
    global _TOWER
    with _tower_lock:
        t, _TOWER = _TOWER, None
    if t is not None:
        t.stop()


def get() -> Optional[Watchtower]:
    return _TOWER


def alert_states() -> Dict[str, int]:
    """{slo: 0|1|2} for the ``dl4j_alert_state`` Prometheus family —
    empty (zero cost, zero rows) when no watchtower is installed."""
    t = _TOWER
    return t.alert_states() if t is not None else {}


def stats() -> Dict[str, float]:
    """The ``watchtower`` ledger payload (see ``OpProfiler.LEDGERS``)."""
    t = _TOWER
    return t.stats() if t is not None else {}


def note_blackbox(path: str) -> None:
    """The supervisor reports every blackbox dump here so incident
    reports (and ``/api/health``'s ``last_incident``) can point at the
    newest one without knowing the checkpoint layout."""
    global _LAST_BLACKBOX
    with _tower_lock:
        _LAST_BLACKBOX = path


def last_blackbox() -> Optional[str]:
    return _LAST_BLACKBOX


def note_supervisor_failure(failure_class: str, policy: str,
                            corr: Optional[str] = None,
                            error: str = "") -> Optional[str]:
    """Supervisor hook: every failure classification triggers incident
    assembly on the installed watchtower (no-op when none is installed —
    the supervised path owes zero overhead to observability it didn't
    ask for)."""
    t = _TOWER
    if t is None:
        return None
    try:
        return t.assemble_incident(
            kind="supervisor",
            reason=f"{failure_class} -> {policy}" + (
                f" ({error})" if error else ""),
            corr=corr)
    except Exception:
        logger.warning("watchtower: supervisor incident assembly failed",
                       exc_info=True)
        return None


def incidents() -> List[Dict[str, Any]]:
    t = _TOWER
    return t.incidents() if t is not None else []


def last_incident() -> Optional[Dict[str, Any]]:
    """The ``/api/health`` ``last_incident`` pointer: the newest
    incident (path + chain/event tail), falling back to the newest
    blackbox when no incident was ever assembled."""
    t = _TOWER
    if t is not None:
        li = t.last_incident()
        if li is not None:
            return li
    bb = last_blackbox()
    if bb is None:
        return None
    tail: List[Any] = []
    try:
        with open(bb, "r", encoding="utf-8") as f:
            for line in f.readlines()[-8:]:
                try:
                    tail.append(json.loads(line))
                except ValueError:
                    pass
    except OSError:
        return None
    return {"kind": "blackbox", "path": bb, "tail": tail}


# -- the default objective catalog ----------------------------------------

def default_slos(engine: Any = None,
                 hbm_ceiling_bytes: Optional[float] = None,
                 fast_s: float = 300.0, mid_s: float = 3600.0,
                 slow_s: float = 21600.0, period_s: float = 86400.0,
                 clear_ticks: int = 3) -> List[SLO]:
    """The stock catalog over signals the repo already exports:
    availability (served vs errored requests), per-class latency p99
    when a :class:`~..parallel.serving.ServingEngine` is handed in,
    NaN-free steps, the restart budget, retrace flatness and the HBM
    watermark ceiling. Window arguments exist so compressed-time drills
    (the soak) can scale 5m/1h/6h down without touching thresholds."""
    win = dict(fast_s=fast_s, mid_s=mid_s, slow_s=slow_s,
               period_s=period_s, clear_ticks=clear_ticks)
    slos = [
        SLO("serving-availability",
            counter_ratio_sampler(bad=("serving/batch_errors",),
                                  total=("serving/requests",)),
            budget=0.001, kind="ratio",
            description="99.9% of admitted requests complete", **win),
        SLO("train-nan-free",
            counter_increment_sampler("telemetry/nan_events",
                                      "fleet/nan_culls"),
            budget=0.001, incident="attach",
            description="no poisoned updates reach the params", **win),
        SLO("replica-consistency",
            counter_increment_sampler("integrity/divergences",
                                      "integrity/quarantined_checkpoints"),
            budget=0.001, incident="attach",
            description="replicas stay bitwise-identical and retained "
                        "checkpoints verify on scrub", **win),
        SLO("restart-budget",
            counter_increment_sampler("supervisor/restarts",
                                      "supervisor/storm_trips"),
            budget=0.01, incident="attach",
            description="supervised restarts stay rare", **win),
        SLO("retrace-flat",
            counter_increment_sampler("tracecheck/violations"),
            budget=0.001, incident="attach",
            description="steady-state regions never retrace/sync", **win),
    ]
    if engine is not None:
        for cls in getattr(engine, "slo_classes", lambda: [])():
            slos.append(SLO(
                f"latency-{cls.name}",
                threshold_sampler(
                    lambda name=cls.name: engine.class_recent_p99(name),
                    float(cls.p99_ms)),
                budget=0.01,
                description=f"{cls.name} rolling p99 under "
                            f"{cls.p99_ms:g} ms", **win))
    if hbm_ceiling_bytes is not None:
        def _peak() -> Optional[float]:
            from . import xprof
            vals = [v for k, v in xprof.ledger().items()
                    if k.startswith("hbm/") and k.endswith("peak_live_bytes")]
            return max(vals) if vals else None
        slos.append(SLO(
            "hbm-ceiling", threshold_sampler(_peak, hbm_ceiling_bytes),
            budget=0.01,
            description="peak live HBM stays under the ceiling", **win))
    return slos
