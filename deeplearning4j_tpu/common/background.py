"""Background-thread producer/consumer helpers.

``prefetch_iter`` is the generic core of the overlap pattern
``AsyncDataSetIterator`` uses for ETL (reference:
``AsyncDataSetIterator``'s blocking queue): run a generator on a worker
thread, hand items to the consumer through a bounded queue, propagate
exceptions, and never leave the worker blocked if the consumer abandons
the iteration. Word2Vec uses it to overlap host pair-generation with
device training rounds (reference analog: the 20-thread
``SequenceVectors`` fit loop keeps the JNI kernels fed; here ONE producer
thread keeps the XLA dispatch queue fed).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, List, TypeVar

T = TypeVar("T")

_END = object()


def prefetch_iter(source: Iterable[T], maxsize: int = 8) -> Iterator[T]:
    """Yield items of ``source``, produced on a background thread through
    a bounded queue of ``maxsize`` items.

    Exceptions raised by ``source`` re-raise at the consuming site after
    already-produced items drain. Abandoning the returned iterator
    (``break`` / GC) releases the worker.
    """
    q: "queue.Queue" = queue.Queue(maxsize=maxsize)
    stop = threading.Event()
    err: List[BaseException] = []

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in source:
                if stop.is_set() or not _put(item):
                    return
        except BaseException as e:
            err.append(e)
        finally:
            _put(_END)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            yield item
        if err:
            raise err[0]
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=5.0)
