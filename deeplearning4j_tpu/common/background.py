"""Background-thread producer/consumer helpers.

``prefetch_iter`` is the generic core of the overlap pattern
``AsyncDataSetIterator`` uses for ETL (reference:
``AsyncDataSetIterator``'s blocking queue): run a generator on a worker
thread, hand items to the consumer through a bounded queue, propagate
exceptions, and never leave the worker blocked if the consumer abandons
the iteration. Word2Vec uses it to overlap host pair-generation with
device training rounds (reference analog: the 20-thread
``SequenceVectors`` fit loop keeps the JNI kernels fed; here ONE producer
thread keeps the XLA dispatch queue fed).
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Callable, Iterable, Iterator, List, Optional, TypeVar

T = TypeVar("T")
U = TypeVar("U")

_END = object()


def prefetch_iter(source: Iterable[T], maxsize: int = 8) -> Iterator[T]:
    """Yield items of ``source``, produced on a background thread through
    a bounded queue of ``maxsize`` items.

    Exceptions raised by ``source`` re-raise at the consuming site after
    already-produced items drain. Abandoning the returned iterator
    (``break`` / GC) releases the worker.
    """
    q: "queue.Queue" = queue.Queue(maxsize=maxsize)
    stop = threading.Event()
    err: List[BaseException] = []

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in source:
                if stop.is_set() or not _put(item):
                    return
        except BaseException as e:
            err.append(e)
        finally:
            _put(_END)

    t = threading.Thread(target=worker, daemon=True,
                         name="dl4j-prefetch-worker")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            yield item
        if err:
            # re-raising the ORIGINAL exception object surfaces the
            # producer's frames at the consuming site: its __traceback__
            # (captured on the worker thread) is preserved and the
            # consumer's raise appends this frame to it
            raise err[0]
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=5.0)


def staged_iter(source: Iterable[T],
                stage: Optional[Callable[[T], U]] = None,
                depth: int = 2,
                host_prefetch: int = 0) -> Iterator[U]:
    """Double-buffered staging: yield ``stage(item)`` for each item of
    ``source``, with ``stage`` issued up to ``depth`` items AHEAD of the
    consumer.

    This is the async-device-feed core of the training input pipeline:
    ``stage`` is typically ``jax.device_put`` (or a sharded placement),
    which returns immediately while the H2D copy proceeds asynchronously —
    so with ``depth`` >= 1 the transfer of batch *n+1* overlaps the
    consumer's compute on batch *n*, and ``depth`` = 2 keeps one extra
    batch in flight (classic double buffering). Device memory held is
    bounded by ``depth`` staged batches.

    ``stage`` runs on the CONSUMER thread deliberately: device_put from a
    worker thread serializes cross-thread array use catastrophically
    through the axon TPU relay (measured in round 4 — see
    data/record_iterator.py), while consumer-side device_put is itself
    async, so nothing is lost on direct backends. Host-side work (decode /
    vectorize / pad) can still run on a worker thread by passing
    ``host_prefetch`` > 0, which routes ``source`` through
    :func:`prefetch_iter` with that queue size.
    """
    if depth < 0:
        raise ValueError(f"depth must be >= 0, got {depth}")
    it: Iterator[T] = (prefetch_iter(source, maxsize=host_prefetch)
                       if host_prefetch > 0 else iter(source))
    if stage is None:
        stage = lambda x: x  # noqa: E731
    buf: "collections.deque" = collections.deque()
    try:
        for item in it:
            buf.append(stage(item))
            if len(buf) > depth:
                yield buf.popleft()
        while buf:
            yield buf.popleft()
    finally:
        # an abandoned staged_iter must close the inner prefetch
        # generator NOW (running its finally: stop + drain + join) rather
        # than leaving the worker thread to GC timing — tests that break
        # out of a fit epoch would otherwise leak daemon threads
        close = getattr(it, "close", None)
        if close is not None:
            close()
