"""Runtime trace sanitizer: hard-fail on retraces / host syncs in a
declared steady-state region.

graftlint (tools/graftlint) is the static half of the trace-boundary
discipline; this is the runtime half. The whole-graph-compilation line
of work (nGraph, the Julia-to-TPU compiler — PAPERS.md) and this repo's
own PR 1/2 both land on the same invariant: after warmup, a training or
serving hot loop must be *replay* — no new traces, no new XLA compiles,
no surprise device→host round-trips. The repo already measures that
invariant (the ``trace/*`` profiler counters the step builders bump at
trace time); :func:`steady_state` turns it into an armed tripwire:

    with tracecheck.steady_state("timed fit"):
        model.fit(it, epochs=1)
    # SteadyStateViolation if anything (re)traced, compiled, or called
    # jax.device_get inside the region

Three independent detectors, because each sees through a different
blind spot:

- **jax monitoring hooks** — ``/jax/core/compile/backend_compile_duration``
  events count real XLA compiles and ``jaxpr_trace_duration`` events
  count traces, including jits this repo did not write (the first
  offending event records a host stack snapshot for the report);
- **``trace/*`` counters** — the step builders bump these inside their
  jitted Python bodies, so a retrace served from the persistent
  compilation cache (no backend compile!) is still caught;
- **``jax.device_get`` hook** — the region wraps the function and counts
  calls against ``max_host_syncs`` (default 0). On TPU/GPU an optional
  transfer guard (``jax.transfer_guard_device_to_host("disallow")``)
  additionally catches *implicit* D2H transfers; on the CPU test mesh
  that guard never fires (host arrays are zero-copy views — the very
  aliasing the donation-alias lint exists for), which is why the
  explicit hook exists.

Violations raise at region EXIT (raising from inside jax's monitoring
callback would unwind through the middle of a compile), carrying every
detector's evidence. Every region bumps ``tracecheck/regions``; every
violating region bumps ``tracecheck/violations`` — the bench smoke
configs assert on both sides (clean runs arm it silently, the injected
retrace drill must trip it).
"""

from __future__ import annotations

import contextlib
import threading
import traceback
from typing import Dict, List, Optional

from . import flightrec
from .profiler import OpProfiler


class SteadyStateViolation(RuntimeError):
    """The declared steady-state region (re)traced, compiled, or blocked
    on the host. ``report`` carries the per-detector evidence."""

    def __init__(self, message: str, report: Dict):
        super().__init__(message)
        self.report = report


class _Region:
    """Mutable state of one armed region (returned by steady_state)."""

    def __init__(self, label: str):
        self.label = label
        self.compiles = 0
        self.traces = 0
        self.host_syncs = 0
        self.first_stack: Optional[str] = None
        self.counter_deltas: Dict[str, int] = {}

    def report(self) -> Dict:
        return {"label": self.label, "compiles": self.compiles,
                "traces": self.traces, "host_syncs": self.host_syncs,
                "counter_deltas": dict(self.counter_deltas),
                "first_stack": self.first_stack}


_active_lock = threading.Lock()
_active_region: Optional[_Region] = None

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"


@contextlib.contextmanager
def steady_state(label: str = "steady-state", *, allow_compiles: int = 0,
                 max_host_syncs: Optional[int] = 0,
                 watch_prefixes=("trace/",),
                 transfer_guard: bool = False):
    """Declare everything inside the ``with`` to be steady state.

    ``allow_compiles``: new traces/compiles tolerated, counted as the
    max over the detectors so one real retrace isn't multiply billed.
    At the default 0 the jaxpr-trace events are policed too (nothing may
    trace); with a nonzero budget only backend compiles and watched
    counters count, because one logical compile emits several trace
    events. ``max_host_syncs``: explicit ``jax.device_get`` calls
    tolerated (a declared once-per-window telemetry drain belongs in
    this budget, not hidden); ``None`` counts but does not police —
    for regions whose sync cadence is data-dependent by design. ``watch_prefixes``: profiler counter
    prefixes that must not move. ``transfer_guard``: also arm jax's
    device-to-host transfer guard (meaningful on TPU/GPU only).

    Yields the region object (``.compiles`` / ``.traces`` /
    ``.host_syncs`` so far); raises :class:`SteadyStateViolation` at
    exit when any budget is exceeded. Regions do not nest — the inner
    declaration would silently re-budget the outer one.
    """
    global _active_region
    import jax
    from jax._src import monitoring

    region = _Region(label)
    with _active_lock:
        if _active_region is not None:
            raise RuntimeError(
                f"steady_state regions do not nest (active: "
                f"{_active_region.label!r})")
        _active_region = region

    prof = OpProfiler.get()
    prof.count("tracecheck/regions")
    counters_before = {k: v for k, v in prof.get_counters().items()
                       if any(k.startswith(p) for p in watch_prefixes)}

    armed = True

    def on_event(name: str, **kw) -> None:
        # duration listener: fires for compile-pipeline stages
        if not armed:
            return
        if name == _COMPILE_EVENT:
            region.compiles += 1
        elif name == _TRACE_EVENT:
            region.traces += 1
        else:
            return
        if region.first_stack is None:
            region.first_stack = "".join(traceback.format_stack(limit=18))

    def on_duration(name: str, duration: float, **kw) -> None:
        on_event(name)

    orig_device_get = jax.device_get

    def counting_device_get(*args, **kw):
        if armed:
            region.host_syncs += 1
            if region.first_stack is None and max_host_syncs is not None \
                    and region.host_syncs > max_host_syncs:
                region.first_stack = "".join(
                    traceback.format_stack(limit=18))
        return orig_device_get(*args, **kw)

    monitoring.register_event_duration_secs_listener(on_duration)
    jax.device_get = counting_device_get
    guard = jax.transfer_guard_device_to_host("disallow") \
        if transfer_guard else contextlib.nullcontext()
    try:
        with guard:
            yield region
    finally:
        armed = False
        jax.device_get = orig_device_get
        try:
            monitoring._unregister_event_duration_listener_by_callback(
                on_duration)
        except Exception:       # pragma: no cover - private API moved;
            pass                # the armed flag keeps the leak inert
        with _active_lock:
            _active_region = None

    counters_after = {k: v for k, v in prof.get_counters().items()
                      if any(k.startswith(p) for p in watch_prefixes)}
    region.counter_deltas = {
        k: counters_after[k] - counters_before.get(k, 0)
        for k in counters_after
        if counters_after[k] != counters_before.get(k, 0)}

    problems: List[str] = []
    retraces = max(region.compiles, sum(region.counter_deltas.values()))
    if allow_compiles == 0:
        # the jaxpr-trace detector closes the persistent-compile-cache
        # blind spot: a cache-served retrace of a jit with no trace/*
        # counter emits ONLY trace events (no backend compile). One
        # logical compile emits SEVERAL trace events (inner jaxprs), so
        # the event count is unusable against a nonzero budget — it only
        # polices the strict "nothing may trace at all" case.
        retraces = max(retraces, region.traces)
    if retraces > allow_compiles:
        moved = ", ".join(f"{k}+{v}" for k, v in
                          sorted(region.counter_deltas.items())) or \
            f"{region.compiles} backend compile(s), {region.traces} " \
            "jaxpr trace(s)"
        problems.append(f"retraced/compiled inside steady state: {moved} "
                        f"(allowed {allow_compiles})")
    if max_host_syncs is not None and region.host_syncs > max_host_syncs:
        problems.append(f"{region.host_syncs} jax.device_get host "
                        f"sync(s) (allowed {max_host_syncs})")
    if problems:
        prof.count("tracecheck/violations")
        flightrec.event("tracecheck/violation", severity="error",
                        label=label, problems="; ".join(problems))
        stack = f"\nfirst offender stack:\n{region.first_stack}" \
            if region.first_stack else ""
        raise SteadyStateViolation(
            f"steady-state region {label!r}: " + "; ".join(problems)
            + stack, region.report())
