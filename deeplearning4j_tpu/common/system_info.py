"""SystemInfo: hardware/software environment dump.

Reference: nd4j-common ``org/nd4j/systeminfo/SystemInfo.java`` (SURVEY
§5.5) — appended to crash reports and shown in the UI's system tab. TPU
shape: host (OS, python, CPU, RAM), jax/device inventory with live
per-device memory stats from the PJRT client, and the framework's
library versions.
"""

from __future__ import annotations

import os
import platform
import sys
from typing import Any, Dict, List


def _host_ram_bytes() -> int:
    try:
        return (os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES"))
    except (ValueError, OSError, AttributeError):
        return 0


def _rss_bytes() -> int:
    """Current process resident set size; without /proc the PEAK RSS is
    the best portable approximation (ru_maxrss: KiB on Linux, bytes on
    macOS). 0 when unknowable."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return peak if sys.platform == "darwin" else peak * 1024
    except Exception:
        return 0


def memory_summary() -> Dict[str, Any]:
    """Live device/host memory telemetry — cheap enough to poll (the
    ``UIServer`` ``/api/health`` endpoint and the dashboard's health strip
    call it per request). Per-device PJRT memory stats, the live-buffer
    census (``jax.live_arrays``: count + bytes — the leak detector), and
    host RSS vs total RAM."""
    out: Dict[str, Any] = {"host": {"ram_bytes": _host_ram_bytes(),
                                    "rss_bytes": _rss_bytes()}}
    try:
        import jax

        devices: List[Dict[str, Any]] = []
        for d in jax.devices():
            dev: Dict[str, Any] = {"id": d.id, "platform": d.platform}
            try:
                stats = d.memory_stats()
            except Exception:       # CPU backends have none
                stats = None
            if stats:
                dev["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
                dev["bytes_limit"] = int(stats.get("bytes_limit", 0))
                dev["peak_bytes_in_use"] = int(
                    stats.get("peak_bytes_in_use", 0))
            devices.append(dev)
        out["devices"] = devices
        out["backend"] = jax.default_backend()
        try:
            live = jax.live_arrays()
            out["live_buffers"] = {
                "count": len(live),
                "bytes": int(sum(int(getattr(a, "nbytes", 0) or 0)
                                 for a in live))}
        except Exception:           # pragma: no cover - older jax
            pass
    except Exception as e:          # pragma: no cover - jax init failure
        out["jax_error"] = str(e)
    return out


def gather() -> Dict[str, Any]:
    """Structured environment snapshot (JSON-serializable)."""
    info: Dict[str, Any] = {
        "os": f"{platform.system()} {platform.release()}",
        "machine": platform.machine(),
        "python": sys.version.split()[0],
        "cpu_count": os.cpu_count(),
        "host_ram_bytes": _host_ram_bytes(),
    }
    try:
        import jax

        info["jax_version"] = jax.__version__
        devices: List[Dict[str, Any]] = []
        for d in jax.devices():
            dev = {"id": d.id, "platform": d.platform,
                   "kind": getattr(d, "device_kind", "?")}
            try:
                stats = d.memory_stats()
            except Exception:       # CPU backends have none
                stats = None
            if stats:
                dev["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
                dev["bytes_limit"] = int(stats.get("bytes_limit", 0))
                dev["peak_bytes_in_use"] = int(
                    stats.get("peak_bytes_in_use", 0))
            devices.append(dev)
        info["devices"] = devices
        info["default_backend"] = jax.default_backend()
    except Exception as e:          # pragma: no cover - jax init failure
        info["jax_error"] = str(e)
    for mod in ("flax", "optax", "orbax.checkpoint", "numpy"):
        try:
            m = __import__(mod)
            for part in mod.split(".")[1:]:
                m = getattr(m, part)
            info[f"{mod}_version"] = getattr(m, "__version__", "?")
        except ImportError:
            pass
    return info


def dump() -> str:
    """Human-readable report (the reference's text-dump contract)."""
    info = gather()
    lines = ["=== SystemInfo ==="]
    for k in ("os", "machine", "python", "cpu_count"):
        lines.append(f"{k}: {info.get(k)}")
    ram = info.get("host_ram_bytes") or 0
    lines.append(f"host RAM: {ram / 2**30:.1f} GiB")
    lines.append(f"jax: {info.get('jax_version', '?')} "
                 f"(backend {info.get('default_backend', '?')})")
    for d in info.get("devices", []):
        mem = ""
        if "bytes_in_use" in d:
            mem = (f" — {d['bytes_in_use'] / 2**20:.0f} MiB in use"
                   f" / {d['bytes_limit'] / 2**20:.0f} MiB"
                   f" (peak {d['peak_bytes_in_use'] / 2**20:.0f})")
        lines.append(f"device {d['id']}: {d['platform']} {d['kind']}{mem}")
    for k, v in info.items():
        if k.endswith("_version") and k != "jax_version":
            lines.append(f"{k.replace('_version', '')}: {v}")
    return "\n".join(lines)


class SystemInfo:
    """Reference-shaped static facade."""

    gather = staticmethod(gather)
    dump = staticmethod(dump)
    memory_summary = staticmethod(memory_summary)
    # reference spelling
    getSystemInfo = staticmethod(dump)
