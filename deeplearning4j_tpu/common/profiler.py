"""OpProfiler-shaped profiling front (SURVEY §5.1).

Reference: nd4j ``OpProfiler`` (per-op timing aggregation, NAN_PANIC mode)
and ``PerformanceTracker`` (bandwidth numbers). On this stack the per-op
dimension lives inside XLA, so the device-side story is a trace: ``start()``/
``stop()`` (or ``with trace(logdir)``) drive ``jax.profiler`` and produce a
TensorBoard-loadable trace of every kernel. The host-side section API
(``time_section``) aggregates wall times by name — the analog of the
reference's per-op counters for the Python orchestration layer.

NAN_PANIC itself is ``Environment.get().set_check_nan(True)`` →
``jax_debug_nans`` (§5.1's named toggle).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import flightrec


class OpProfiler:
    _instance: Optional["OpProfiler"] = None
    _lock = threading.Lock()

    #: every derived ledger the profiler exposes, by (label, method
    #: name) — the one list ``print_statistics``, ``/api/health`` and
    #: the ``/api/metrics`` Prometheus renderer all iterate, so a new
    #: ledger can never be health-only or metrics-only by accident.
    LEDGERS: Tuple[Tuple[str, str], ...] = (
        ("overlap", "overlap_stats"),
        ("telemetry", "telemetry_stats"),
        ("checkpoint", "checkpoint_stats"),
        ("supervisor", "supervisor_stats"),
        ("collectives", "collective_stats"),
        ("elastic", "elastic_stats"),
        ("pipeline", "pipeline_stats"),
        ("serving", "serving_stats"),
        ("autoscale", "autoscale_stats"),
        ("fleet", "fleet_stats"),
        ("precision", "precision_stats"),
        ("xla", "xla_stats"),
        ("tracecheck", "tracecheck_stats"),
        ("faults", "fault_stats"),
        ("watchtower", "watchtower_stats"),
        ("integrity", "integrity_stats"),
    )

    def __init__(self) -> None:
        self._trace_dir: Optional[str] = None
        self._sections: Dict[str, Dict[str, float]] = {}
        self._counters: Dict[str, int] = {}
        self._gauge_names: set = set()

    @classmethod
    def get(cls) -> "OpProfiler":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # --- device trace (jax.profiler → TensorBoard trace viewer) ---------
    def start(self, logdir: str) -> None:
        import jax

        with self._lock:
            if self._trace_dir is not None:
                raise RuntimeError("profiler already tracing")
            self._trace_dir = logdir
        try:
            jax.profiler.start_trace(logdir)
        except BaseException:
            # a failed start (unwritable logdir) must not wedge the
            # profiler in "already tracing" with no trace to stop
            with self._lock:
                self._trace_dir = None
            raise
        from .environment import Environment

        Environment.get().set_profiling(True)

    def stop(self) -> None:
        import jax

        with self._lock:
            if self._trace_dir is None:
                return
            self._trace_dir = None
        jax.profiler.stop_trace()
        from .environment import Environment

        Environment.get().set_profiling(False)

    @contextlib.contextmanager
    def trace(self, logdir: str):
        self.start(logdir)
        try:
            yield self
        finally:
            self.stop()

    # --- host-side section counters (OpProfiler counter analog) ---------
    @contextlib.contextmanager
    def time_section(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            # under the lock: sections are bumped from the training
            # thread, the checkpoint writer and inference workers alike —
            # unlocked read-modify-write drops updates
            with self._lock:
                s = self._sections.setdefault(
                    name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
                s["count"] += 1
                s["total_s"] += dt
                s["max_s"] = max(s["max_s"], dt)
            # individual durations feed the flight recorder's timeline
            # (Chrome-trace X events on the emitting thread's lane);
            # the aggregate above stays the ledger source of truth.
            # Emitted OUTSIDE the profiler lock — the recorder has its
            # own, and nesting them would order the two locks.
            flightrec.event("profiler/section", section=name, dur_s=dt)

    def get_statistics(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._sections.items()}

    # --- event counters (compile/retrace accounting) --------------------
    # The train-step builders bump ``trace/<name>`` INSIDE the function
    # handed to jax.jit: the Python body only executes while jax traces,
    # so the counter counts (re)traces — each of which implies an XLA
    # compile — and stays silent on cached executions. Tests and the bench
    # assert "one compile per fit config" directly on these.
    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: int) -> None:
        """Set a counter to an absolute value (last-write-wins) — for
        level quantities like the live elastic worker count, where adding
        would be meaningless."""
        with self._lock:
            self._counters[name] = int(value)
            # remembered so /api/metrics can render levels as Prometheus
            # gauges instead of (monotonicity-implying) counters
            self._gauge_names.add(name)

    def gauge_names(self) -> set:
        """Counter names set via :meth:`gauge` (levels, not totals)."""
        with self._lock:
            return set(self._gauge_names)

    def counter_value(self, name: str) -> int:
        return self._counters.get(name, 0)

    def get_counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def trace_counts(self) -> Dict[str, int]:
        """Just the ``trace/*`` counters (the retrace ledger)."""
        return {k: v for k, v in self._counters.items()
                if k.startswith("trace/")}

    def overlap_stats(self) -> Dict[str, float]:
        """Transfer-vs-compute overlap summary for the input pipeline:
        ``host_wait_s`` is time fit() spent blocked on the next (staged)
        batch, ``dispatch_s`` is time spent issuing train steps. A healthy
        overlapped loop keeps host_wait a small fraction of dispatch."""
        out: Dict[str, float] = {}
        for sec, key in (("pipeline/next_batch", "host_wait_s"),
                         ("pipeline/dispatch", "dispatch_s")):
            s = self._sections.get(sec)
            if s:
                out[key] = s["total_s"]
                out[key.replace("_s", "_count")] = s["count"]
        if "host_wait_s" in out and "dispatch_s" in out:
            busy = out["host_wait_s"] + out["dispatch_s"]
            if busy > 0:
                out["host_wait_frac"] = out["host_wait_s"] / busy
        return out

    def telemetry_stats(self) -> Dict[str, float]:
        """In-graph-telemetry drain ledger: host time spent in the batched
        aux readbacks (``telemetry/drain`` sections — the ONLY host sync
        the telemetry layer pays) plus the drained-step counter. Empty
        when telemetry never ran."""
        out: Dict[str, float] = {}
        s = self._sections.get("telemetry/drain")
        if s:
            out = {"drain_s": s["total_s"], "drain_count": s["count"],
                   "drain_max_s": s["max_s"]}
        n = self._counters.get("telemetry/drained_steps")
        if n:
            out["drained_steps"] = n
        return out

    def checkpoint_stats(self) -> Dict[str, float]:
        """Checkpoint-path ledger: snapshot time (the batched readback on
        the training thread — the ONLY hot-loop cost of async
        checkpointing), background write/commit time, committed count and
        bytes. Empty when no checkpoint ever committed."""
        out: Dict[str, float] = {}
        for sec, key in (("checkpoint/snapshot", "snapshot_s"),
                         ("checkpoint/write", "write_s")):
            s = self._sections.get(sec)
            if s:
                out[key] = s["total_s"]
                out[key.replace("_s", "_count")] = s["count"]
        for ctr, key in (("checkpoint/committed", "committed"),
                         ("checkpoint/bytes", "bytes")):
            n = self._counters.get(ctr)
            if n:
                out[key] = n
        return out

    def supervisor_stats(self) -> Dict[str, float]:
        """Self-healing-loop ledger: supervised attempts, restarts,
        watchdog fires, preemptions, storm trips, give-ups (the
        ``supervisor/*`` counters) plus backoff wall time — the /api/health
        and drill-test view of what the restart loop actually did. Empty
        when no supervisor ever ran."""
        out: Dict[str, float] = {
            k.split("/", 1)[1]: v for k, v in self._counters.items()
            if k.startswith("supervisor/")}
        s = self._sections.get("supervisor/backoff")
        if s:
            out["backoff_s"] = s["total_s"]
            out["backoff_count"] = s["count"]
        return out

    def collective_stats(self) -> Dict[str, float]:
        """Gradient-exchange ledger (``collective/*`` + ``zero1/*``
        counters): bytes moved per collective kind (dense ``psum`` vs the
        ZeRO-1 ``reduce_scatter``/``all_gather`` pair), the ZeRO-1 sharded
        updater-state footprint, and the encoded-exchange element counters
        with the derived density and the reference wire-format byte
        estimate — ``ThresholdCompression``'s two encodings: 4-byte sparse
        indices below 1/16 density, 2-bit bitmap above (the estimate takes
        the cheaper per run). Empty when no ParallelWrapper fit ran."""
        out: Dict[str, float] = {
            k.split("/", 1)[1]: v for k, v in self._counters.items()
            if k.startswith("collective/")}
        for ctr, key in (("zero1/updater_state_bytes_total",
                          "zero1_updater_state_bytes_total"),
                         ("zero1/updater_state_bytes_per_replica",
                          "zero1_updater_state_bytes_per_replica")):
            n = self._counters.get(ctr)
            if n:
                out[key] = n
        sent = out.get("encoded_elems_sent")
        total = out.get("encoded_elems_total")
        if total:
            out["encoded_density"] = sent / total
            out["encoded_bytes_est"] = int(min(4 * sent, total // 4))
            out["encoded_dense_bytes_equiv"] = int(4 * total)
        return out

    def elastic_stats(self) -> Dict[str, float]:
        """Online-resize ledger (``elastic/*`` counters): resizes split
        into shrinks/grows, grow-back probe attempts and failures, the
        live ``workers`` gauge, plus the resize wall-time section — the
        /api/health and elastic-smoke view of what the elastic data axis
        actually did. Empty until a parallel fit runs (every parallel fit
        sets the ``workers`` gauge — the live data-axis width is a level,
        not an elastic event); resize/probe counters appear only after an
        actual elastic event."""
        out: Dict[str, float] = {
            k.split("/", 1)[1]: v for k, v in self._counters.items()
            if k.startswith("elastic/")}
        s = self._sections.get("elastic/resize")
        if s:
            out["resize_s"] = s["total_s"]
            out["resize_count"] = s["count"]
        return out

    def pipeline_stats(self) -> Dict[str, float]:
        """Pipeline-parallel ledger (the PipelineTrainer's counters —
        NOT the input pipeline's, which live on the overlap/fault
        ledgers): live ``stages`` gauge, ``remaps`` + remap wall time,
        ``microbatches`` dispatched, schedule tick occupancy
        (``busy_ticks``/``tick_slots`` from the same mask tables the
        compiled step executes) with the derived ``bubble_fraction`` —
        the /api/health, /api/metrics and pipeline-parallel-smoke view
        of what the stage axis actually did. Empty until a
        PipelineTrainer fit runs."""
        out: Dict[str, float] = {}
        for ctr, key in (("pipeline/stages", "stages"),
                         ("pipeline/remaps", "remaps"),
                         ("pipeline/microbatches", "microbatches"),
                         ("pipeline/busy_ticks", "busy_ticks"),
                         ("pipeline/tick_slots", "tick_slots")):
            n = self._counters.get(ctr)
            if n:
                out[key] = n
        slots = out.get("tick_slots")
        if slots:
            out["bubble_fraction"] = 1.0 - out.get("busy_ticks", 0) / slots
        s = self._sections.get("pipeline/remap")
        if s:
            out["remap_s"] = s["total_s"]
            out["remap_count"] = s["count"]
        return out

    def serving_stats(self) -> Dict[str, float]:
        """Serving-tier ledger (``serving/*`` counters + sections): request
        and batch counts, bucket fill ratio (real rows / dispatched bucket
        capacity) and its complement pad waste, queue-depth high-water,
        requeues ridden through replica retirement, oversize admissions,
        the traces-after-warmup counter (MUST stay 0 in steady state —
        the serving-smoke bench hard-fails on it), and the dispatch /
        warmup wall-time sections. Rolling p50/p99 request latency lives
        on the engines themselves (``ServingEngine.latency_stats()`` — a
        quantile is not a counter); ``parallel.serving.serving_health()``
        merges both views for ``/api/health``. Empty when no ServingEngine
        ever dispatched."""
        out: Dict[str, float] = {
            k.split("/", 1)[1]: v for k, v in self._counters.items()
            if k.startswith("serving/")}
        cap = out.get("capacity_rows")
        if cap:
            out["fill_ratio"] = out.get("rows", 0) / cap
            out["pad_waste"] = out.get("pad_rows", 0) / cap
        for sec, key in (("serving/dispatch", "dispatch_s"),
                         ("serving/warmup", "warmup_s")):
            s = self._sections.get(sec)
            if s:
                out[key] = s["total_s"]
                out[key.replace("_s", "_count")] = s["count"]
        return out

    def autoscale_stats(self) -> Dict[str, float]:
        """Closed-loop autoscaler ledger (``autoscale/*`` counters):
        controller ticks, scale-ups/downs actuated, held decisions,
        skipped (drilled) evaluations, and the live ``replicas`` gauge —
        the /api/health and autoscale-smoke view of what the controller
        actually did. Empty until an :class:`parallel.autoscale.
        Autoscaler` ticks."""
        return {k.split("/", 1)[1]: v for k, v in self._counters.items()
                if k.startswith("autoscale/")}

    def fleet_stats(self) -> Dict[str, float]:
        """Vmapped-fleet ledger (``fleet/*`` counters): culls, spawns,
        per-member NaN culls, telemetry-window drains, and the live
        ``members`` gauge (alive count — every FleetTrainer sets it at
        construction and on every lifecycle change). The /api/health,
        /api/metrics and fleet-smoke view of what the population
        actually did. Empty until a :class:`parallel.fleet.FleetTrainer`
        exists."""
        return {k.split("/", 1)[1]: v for k, v in self._counters.items()
                if k.startswith("fleet/")}

    def precision_stats(self) -> Dict[str, float]:
        """Mixed-precision ledger (``precision/*`` counters): fused
        update-kernel hits split by execution engine (``fused_buckets_
        pallas`` vs ``fused_buckets_xla``) and the fallbacks onto the
        per-leaf path, the fused BN epilogue hits / residual-chain hits /
        shape-gate fallbacks, the stochastic-rounding draw count baked
        into the compiled step (``sr_draws`` — uint32 per element per
        trace), and the live updater-state byte gauges by dtype
        (``updater_state_bytes_<dtype>`` + ``_total`` — the footprint
        the bf16 state mode halves). Counters are trace-time (one bump
        per compiled step, not per execution); byte gauges are levels.
        Empty until a fit or fused inference runs."""
        return {k.split("/", 1)[1]: v for k, v in self._counters.items()
                if k.startswith("precision/")}

    def xla_stats(self) -> Dict[str, float]:
        """XLA performance-observatory ledger (``common.xprof``): the
        per-executable roofline rows — calls, mean dispatch ms, retrace
        generations, compile wall, analytic flops/bytes, arithmetic
        intensity, MFU and the compute-vs-HBM-bound verdict — plus the
        census totals and the per-phase HBM watermark gauges, flattened
        under slash-keys. Cost fields appear after ``xprof.analyze()``
        ran (analysis re-traces, so it is explicit — never per step);
        everything else accrues live. Empty until an executable
        registers with the census."""
        try:
            from . import xprof

            return xprof.ledger()
        except Exception:       # census import/jax failure: ledger-silent
            return {}

    def tracecheck_stats(self) -> Dict[str, float]:
        """Steady-state sanitizer ledger (``tracecheck/*`` counters):
        regions armed and regions that tripped. The bench smoke configs
        assert both directions — clean runs arm without tripping, the
        injected-retrace drill must trip. Empty until a
        ``tracecheck.steady_state`` region runs."""
        return {k.split("/", 1)[1]: v for k, v in self._counters.items()
                if k.startswith("tracecheck/")}

    def fault_stats(self) -> Dict[str, float]:
        """Fault-tolerance ledger: injected-fault counters
        (``faults/<site>/<kind>``), pipeline retry count, and backoff wall
        time. The fault-smoke bench asserts on these both ways: injected
        faults fired, and clean configs fired none."""
        out: Dict[str, float] = {k: v for k, v in self._counters.items()
                                 if k.startswith("faults/")}
        n = self._counters.get("pipeline/retries")
        if n:
            out["retries"] = n
        s = self._sections.get("pipeline/retry_backoff")
        if s:
            out["retry_backoff_s"] = s["total_s"]
        return out

    def integrity_stats(self) -> Dict[str, float]:
        """Silent-corruption-defense ledger (``integrity/*`` counters):
        in-graph fingerprint checks and divergences, injected bitflip
        drills, scrub passes / verified entries / retries, and
        quarantined checkpoint generations (replica quarantines ride the
        supervisor ledger as ``quarantines``). Empty until an
        IntegrityListener or CheckpointScrubber runs — a clean soak
        window must show ``checks`` advancing with zero ``divergences``
        and zero ``quarantined_checkpoints``."""
        return {k.split("/", 1)[1]: v for k, v in self._counters.items()
                if k.startswith("integrity/")}

    def watchtower_stats(self) -> Dict[str, float]:
        """SLO watchtower ledger (``common.watchtower``): per-SLO alert
        state (0 ok / 1 warn / 2 page), fast-window burn rate and error
        budget remaining, plus evaluation/incident totals. Riding
        :data:`LEDGERS` puts it on ``/api/health``, ``/api/metrics`` and
        ``print_statistics`` in one move. Empty until a
        :class:`~.watchtower.Watchtower` is installed."""
        try:
            from . import watchtower

            return watchtower.stats()
        except Exception:   # watchtower absent/uninstalled: ledger-silent
            return {}

    def ledger_stats(self) -> Dict[str, Dict[str, float]]:
        """Every non-empty derived ledger (:data:`LEDGERS`), keyed by
        label — the same set ``print_statistics`` renders and
        ``/api/metrics`` exports."""
        out: Dict[str, Dict[str, float]] = {}
        for label, attr in self.LEDGERS:
            stats = getattr(self, attr)()
            if stats:
                out[label] = stats
        return out

    def print_statistics(self) -> str:
        lines = [f"{'section':<32}{'count':>8}{'total ms':>12}"
                 f"{'mean ms':>12}{'max ms':>12}"]
        for name, s in sorted(self.get_statistics().items(),
                              key=lambda kv: -kv[1]["total_s"]):
            mean = s["total_s"] / max(s["count"], 1)
            lines.append(f"{name:<32}{s['count']:>8}"
                         f"{s['total_s'] * 1e3:>12.2f}"
                         f"{mean * 1e3:>12.2f}{s['max_s'] * 1e3:>12.2f}")
        for label, stats in self.ledger_stats().items():
            lines.append(f"[{label}] " + "  ".join(
                f"{k}={round(v, 6) if isinstance(v, float) else v}"
                for k, v in sorted(stats.items())
                if isinstance(v, (int, float))))
        out = "\n".join(lines)
        print(out)
        return out

    def reset(self) -> None:
        with self._lock:
            self._sections.clear()
            self._counters.clear()
            self._gauge_names.clear()
