"""Atomic, async, resumable training checkpoints.

Reference: dl4j's CheckpointListener + ModelSerializer give rolling model
zips, but the operational contract here is orbax-grade (SURVEY §5.3): a
checkpoint either exists completely or not at all, a reader can prove
which, and a resumed run is *bit-identical* to one that was never killed.

Three layers:

- **Snapshot** (:func:`snapshot_training_state`): the training thread
  captures params / layer states / updater state / the thread's RNG key
  in ONE batched readback (``jax.device_get`` issues every D2H copy
  asynchronously, then gathers), plus the host-side counters that make
  resume exact — iteration/epoch, the data-pipeline cursor
  (epochs_done / steps_in_epoch maintained by ``data.pipeline``), and any
  listener state exposed through the ``state_dict``/``load_state_dict``
  protocol. The snapshot is pure host data: the background writer never
  touches live (donatable) device buffers.

- **Commit** (:func:`commit_checkpoint`): serialize → ``<name>.tmp`` →
  flush+fsync → ``os.replace`` → fsync(dir). The final name only ever
  appears for a complete file. A sha256 of the exact committed bytes goes
  into ``checkpoint.json`` (the manifest, itself written atomically), and
  retention deletes only fully-committed files — the manifest drops an
  entry before its file is unlinked, so no window exists where the index
  references a deleted checkpoint.

- **Verify** (:func:`last_checkpoint`): walk the manifest newest→oldest,
  re-hashing each candidate; a missing/truncated/bit-flipped file is
  warned about and skipped, falling back to the newest intact entry. With
  no usable manifest (torn write, pre-manifest directory), a directory
  scan validates each ``checkpoint_*.zip`` (zip CRC + meta entry) and
  picks the newest intact one.

The zip payload is the ModelSerializer container (v1 readers — plain
``MultiLayerNetwork.load`` — still work) plus a ``resume.json`` entry
carrying the rng/cursor/listener state; ``restore_training_state``
consumes it for ``fit(resume_from=...)``.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import queue
import threading
import time
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common import faultinject, flightrec
from ..common.profiler import OpProfiler

logger = logging.getLogger("deeplearning4j_tpu")

MANIFEST_NAME = "checkpoint.json"
RESUME_ENTRY = "resume.json"
ACC_ENTRY = "accumulatorState.npz"
MANIFEST_FORMAT = 2

# In-process serialization of manifest read-modify-writes: the async
# CheckpointWriter thread folds commits while the integrity scrubber
# thread stamps scrub results / quarantines generations — without one
# owning lock the two would tear each other's updates (the file write
# itself is atomic; the read-modify-write around it is not)
_MANIFEST_LOCK = threading.RLock()


class StaleIncarnationError(RuntimeError):
    """A writer from an OLDER incarnation tried to commit into a
    directory a newer incarnation has claimed (``checkpoint.json``
    carries a monotonic ``incarnation`` id). The supervised-restart
    fence: a wedged pre-restart process that wakes up late can never
    clobber its replacement's checkpoints — the commit is refused and
    the manifest stays untouched."""


# --------------------------------------------------------------------------
# snapshot
# --------------------------------------------------------------------------

def snapshot_training_state(model, listeners=None,
                            rng_state: Optional[Dict[str, Any]] = None
                            ) -> Dict[str, Any]:
    """Host-side snapshot of everything resume needs, taken on the
    training thread at a dispatch boundary. One batched readback.
    ``rng_state`` overrides the calling thread's RNG stream state — the
    supervisor's preemption flush runs on the MONITOR thread but must
    record the TRAINING thread's stream (RNG instances are per-thread)."""
    import jax

    from ..ndarray.rng import get_random

    state = rng_state if rng_state is not None else get_random().get_state()
    acc_state = getattr(model, "_acc_state", None)
    with OpProfiler.get().time_section("checkpoint/snapshot"):
        host = jax.device_get(
            (model._params, model._states, model._updater_state,
             acc_state if acc_state else None, state["key"]))
        # device_get may return ZERO-COPY views of the device buffers on
        # the CPU backend — and the very next train step DONATES those
        # buffers, so the background writer would read freed memory
        # (observed as glibc heap corruption). Force owning copies; the
        # memcpy is trivial next to the serialize it feeds.
        params, states, upd, acc, key = jax.tree.map(np.array, host)
    # ZeRO-1 runs hold the updater state in the flat sharded layout; the
    # ON-DISK layout is always the dense params-mirroring tree (a pure
    # permutation), so a checkpoint restores into a single-device fit, a
    # dense data-parallel fit, or a ZeRO-1 fit with a DIFFERENT worker
    # count without any format negotiation — resharding is just
    # re-flattening for the new count.
    from ..parallel.sharding import unflatten_updater_state

    upd = unflatten_updater_state(upd, params, xp=np)
    fit_epoch0 = getattr(model, "_fit_epoch0", model._epoch)
    # the configuration is immutable across a fit — serialize it once per
    # model, not once per checkpoint
    conf_json = getattr(model, "_ckpt_conf_json", None)
    if conf_json is None:
        conf_json = model.conf.to_json()
        model._ckpt_conf_json = conf_json
    # the stored-moment dtype is part of the training numerics: record
    # it in the meta + manifest so restore can refuse a silent flip
    from ..learning.precision import state_dtype_of

    return {
        "kind": type(model).__name__,
        "conf_json": conf_json,
        "params": params,
        "states": states,
        "updater": upd,
        "state_dtype": state_dtype_of(model.conf.global_conf.updater),
        "accumulator": acc,
        "iteration": int(model._iteration),
        "epoch": int(model._epoch),
        "rng": {"seed": int(state.get("seed", get_random().get_seed())),
                "key": np.asarray(key).tolist(),
                "key_dtype": str(np.asarray(key).dtype)},
        "cursor": dict(
            {
                "epochs_done": int(model._epoch) - int(fit_epoch0),
                "steps_in_epoch": int(getattr(model, "_steps_in_epoch", 0)),
                # the LIVE data-parallel worker count at snapshot time: an
                # elastic run may be mid-shrink, and the resume metadata
                # must say how many replicas were actually training
                # (diagnostics + the resharding log line; the state itself
                # is layout-independent, so restore works under any count)
                "workers": int(getattr(model, "_live_workers", 1)),
            },
            # the LIVE pipeline stage count, same story as workers: a
            # remapped run's snapshot names the count it was training at
            # (the per-layer on-disk layout restores under ANY stage
            # count). Only pipeline fits set the attr, so every other
            # path's resume.json bytes are unchanged.
            **({"stages": int(model._live_stages)}
               if hasattr(model, "_live_stages") else {})),
        "listener_state": gather_listener_state(listeners),
    }


def gather_listener_state(listeners) -> Dict[str, Any]:
    """Listeners opt into exact resume with ``state_dict`` /
    ``load_state_dict`` (JSON-serializable). Keyed by position+class so
    restore maps back onto the same listener arrangement."""
    out: Dict[str, Any] = {}
    for i, lst in enumerate(listeners or []):
        fn = getattr(lst, "state_dict", None)
        if callable(fn):
            try:
                out[f"{i}:{type(lst).__name__}"] = fn()
            except Exception:
                logger.warning("state_dict of %s failed; its state will "
                               "not resume", type(lst).__name__,
                               exc_info=True)
    return out


def restore_listener_state(listeners, state: Dict[str, Any]) -> None:
    for i, lst in enumerate(listeners or []):
        key = f"{i}:{type(lst).__name__}"
        fn = getattr(lst, "load_state_dict", None)
        if callable(fn) and key in state:
            fn(state[key])


def serialize_snapshot(snapshot: Dict[str, Any]) -> bytes:
    """Snapshot → ModelSerializer-container zip bytes (+ resume.json).

    ZIP_STORED on purpose: trained float params are incompressible noise,
    so DEFLATE costs ~6x the wall time of the raw copy for little size
    win — and checkpoint cadence is bounded by write latency, not disk
    space (readers accept either compression transparently)."""
    from .model_serializer import (_COEFF_ENTRY, _CONF_ENTRY, _META_ENTRY,
                                   _STATES_ENTRY, _UPDATER_ENTRY,
                                   _savez_leaves)

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr(_CONF_ENTRY, snapshot["conf_json"])
        zf.writestr(_COEFF_ENTRY, _savez_leaves(snapshot["params"]))
        zf.writestr(_STATES_ENTRY, _savez_leaves(snapshot["states"]))
        zf.writestr(_META_ENTRY, json.dumps({
            "iteration": snapshot["iteration"], "epoch": snapshot["epoch"],
            "kind": snapshot["kind"], "format_version": 2,
            "updater_state_dtype": snapshot.get("state_dtype"),
        }))
        if snapshot["updater"] is not None:
            zf.writestr(_UPDATER_ENTRY, _savez_leaves(snapshot["updater"]))
        if snapshot.get("accumulator"):
            # stateful gradient-exchange state (encoded residual carry +
            # threshold + ledger counters): restored lazily by the wrapper
            # against ITS accumulator's template (the zip stays readable
            # by consumers that know nothing about accumulators)
            zf.writestr(ACC_ENTRY, _savez_leaves(snapshot["accumulator"]))
        resume = {
            "rng": snapshot["rng"],
            "cursor": snapshot["cursor"],
            "listener_state": snapshot["listener_state"],
        }
        if snapshot.get("fleet") is not None:
            # stacked-fleet extras (parallel.fleet): alive mask, carried
            # per-member stream keys, hyper grid, member seeds — what a
            # bit-exact fleet resume needs beyond the stacked trees.
            # Solo readers never look for the key, so member and plain
            # checkpoints are untouched.
            resume["fleet"] = snapshot["fleet"]
        zf.writestr(RESUME_ENTRY, json.dumps(resume))
    return buf.getvalue()


# --------------------------------------------------------------------------
# atomic commit + manifest
# --------------------------------------------------------------------------

def _fsync_dir(directory: str) -> None:
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return     # platforms without directory fds
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_write(path: str, data: bytes, seq: Optional[int] = None,
                  durable: bool = True) -> None:
    """data → <path>.tmp → fsync → rename. The faultinject site sits in
    the torn-write window the rename is there to close. ``durable=False``
    skips the fsyncs (still atomic): used for the manifest, whose loss is
    recoverable — ``last_checkpoint`` falls back to a directory scan."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        if durable:
            f.flush()
            os.fsync(f.fileno())
    faultinject.fault_point("checkpoint/pre_rename", seq)
    os.replace(tmp, path)
    if durable:
        _fsync_dir(os.path.dirname(path) or ".")


def read_manifest_doc(directory: str) -> Dict[str, Any]:
    """The whole manifest document ({} when missing or unparseable — a
    torn manifest must not take the checkpoints with it; the scan
    fallback still finds them). Carries ``checkpoints`` (entries, oldest
    first) and ``incarnation`` (the monotonic supervised-restart fence)."""
    path = os.path.join(directory, MANIFEST_NAME)
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else {}
    except FileNotFoundError:
        return {}
    except (json.JSONDecodeError, OSError):
        logger.warning("unreadable checkpoint manifest %s; falling back to "
                       "directory scan", path)
        return {}


def read_manifest(directory: str) -> List[Any]:
    """Manifest entries, oldest first. v2 entries are dicts (file/sha256/
    iteration/tag, optionally bytes); v1 entries are bare path strings."""
    entries = read_manifest_doc(directory).get("checkpoints", [])
    return entries if isinstance(entries, list) else []


def manifest_incarnation(directory: str) -> int:
    """The directory's current incarnation id (0 = never claimed)."""
    try:
        return int(read_manifest_doc(directory).get("incarnation", 0))
    except (TypeError, ValueError):
        return 0


def write_manifest(directory: str, entries: List[Any],
                   incarnation: Optional[int] = None) -> None:
    doc: Dict[str, Any] = {"format": MANIFEST_FORMAT, "checkpoints": entries}
    if incarnation is None:
        incarnation = manifest_incarnation(directory)
    if incarnation:
        doc["incarnation"] = int(incarnation)
    _atomic_write(os.path.join(directory, MANIFEST_NAME),
                  json.dumps(doc).encode(), durable=False)


def claim_incarnation(directory: str) -> int:
    """Bump and record the directory's incarnation id, invalidating every
    writer fenced to an older one (their commits raise
    :class:`StaleIncarnationError`). Called once per supervised (re)start
    BEFORE the new attempt's writer is built."""
    os.makedirs(directory, exist_ok=True)
    with _MANIFEST_LOCK:
        doc = read_manifest_doc(directory)
        inc = int(doc.get("incarnation", 0) or 0) + 1
        write_manifest(directory, doc.get("checkpoints", []),
                       incarnation=inc)
    return inc


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _entry_name(e: Any) -> str:
    return e["file"] if isinstance(e, dict) else os.path.basename(e)


def _entry_bytes(directory: str, e: Any) -> int:
    if isinstance(e, dict) and "bytes" in e:
        return int(e["bytes"])
    try:
        return os.path.getsize(os.path.join(directory, _entry_name(e)))
    except OSError:
        return 0


def _append_and_retain(directory: str, name: str, sha: str, iteration: int,
                       keep_last: int, size: Optional[int] = None,
                       max_total_bytes: Optional[int] = None,
                       incarnation: Optional[int] = None,
                       state_dtype: Optional[str] = None,
                       fleet: Optional[Dict[str, Any]] = None) -> None:
    """Fold one committed file into the manifest and apply retention —
    count-based (``keep_last``) then disk-budget (``max_total_bytes``:
    oldest committed entries drop until the total fits; the newest always
    survives). Only COMMITTED files are ever deleted, and the manifest
    stops referencing a file BEFORE it is unlinked: a crash between the
    two leaves an orphan file, never a dangling index. ``incarnation``
    fences the fold: an older-incarnation writer raises
    :class:`StaleIncarnationError` and the manifest is untouched."""
    with _MANIFEST_LOCK:
        doc = read_manifest_doc(directory)
        current = int(doc.get("incarnation", 0) or 0)
        if incarnation is not None and int(incarnation) < current:
            raise StaleIncarnationError(
                f"writer incarnation {incarnation} is stale: {directory} "
                f"was claimed by incarnation {current}; refusing to "
                f"commit {name}")
        old = doc.get("checkpoints", [])
        entries = [e for e in (old if isinstance(old, list) else [])
                   if _entry_name(e) != name]
        entry: Dict[str, Any] = {"file": name, "sha256": sha,
                                 "iteration": int(iteration),
                                 "tag": name[len("checkpoint_"):
                                             -len(".zip")]}
        if size is not None:
            entry["bytes"] = int(size)
        if state_dtype is not None:
            # low-precision updater state: surfaced in the manifest so
            # ops tooling (and humans) can see the stored-moment dtype
            # without opening the zip
            entry["state_dtype"] = str(state_dtype)
        if fleet is not None:
            # fleet provenance (parallel.fleet): {"members": M} for a
            # stacked fleet checkpoint, plus {"member": k} for a sliced
            # single-member one — ops tooling can tell a member export
            # from a solo run and a stacked state from a dense one
            # without opening the zip
            entry["fleet"] = {k: int(v) for k, v in fleet.items()}
        entries.append(entry)
        retained, dropped = entries, []
        if keep_last and len(entries) > keep_last:
            retained, dropped = entries[-keep_last:], entries[:-keep_last]
        if max_total_bytes:
            total = sum(_entry_bytes(directory, e) for e in retained)
            while len(retained) > 1 and total > max_total_bytes:
                total -= _entry_bytes(directory, retained[0])
                dropped.append(retained[0])
                retained = retained[1:]
                OpProfiler.get().count("checkpoint/bytes_gc")
        # pass the resolved value through (0 included) — None would make
        # write_manifest re-read the manifest it was just handed
        write_manifest(directory, retained,
                       incarnation=max(current, int(incarnation or 0)))
    for e in dropped:
        try:
            os.remove(os.path.join(directory, _entry_name(e)))
        except FileNotFoundError:
            pass


def commit_checkpoint(directory: str, tag: str, data: bytes,
                      iteration: int, keep_last: int,
                      seq: Optional[int] = None,
                      max_total_bytes: Optional[int] = None,
                      incarnation: Optional[int] = None,
                      state_dtype: Optional[str] = None,
                      fleet: Optional[Dict[str, Any]] = None) -> str:
    """Atomically commit one checkpoint and fold it into the manifest;
    apply retention. Returns the committed path. Single-writer per
    directory (the listener's writer thread or the sync caller).
    ``incarnation``: the writer's fence id — checked BEFORE the file is
    written (so a stale writer leaves no orphan zip either) and again
    under the manifest fold. ``fleet``: provenance metadata for stacked-
    fleet / sliced-member commits, recorded on the manifest entry."""
    prof = OpProfiler.get()
    if incarnation is not None \
            and manifest_incarnation(directory) > int(incarnation):
        raise StaleIncarnationError(
            f"writer incarnation {incarnation} is stale: {directory} was "
            f"claimed by incarnation {manifest_incarnation(directory)}")
    name = f"checkpoint_{tag}.zip"
    path = os.path.join(directory, name)
    with prof.time_section("checkpoint/write"):
        _atomic_write(path, data, seq=seq)
        _append_and_retain(directory, name, hashlib.sha256(data).hexdigest(),
                           iteration, keep_last, size=len(data),
                           max_total_bytes=max_total_bytes,
                           incarnation=incarnation, state_dtype=state_dtype,
                           fleet=fleet)
    prof.count("checkpoint/committed")
    prof.count("checkpoint/bytes", len(data))
    # committed on the writer thread in the async path: the ambient
    # correlation id (the supervisor's attempt) rides along, so the
    # timeline shows WHICH attempt's save this durability point belongs to
    flightrec.event("checkpoint/commit", tag=tag, file=name,
                    iteration=int(iteration), bytes=len(data))
    return path


def committed_checkpoints(directory: str) -> List[str]:
    """Committed checkpoint paths, oldest first — manifest order when one
    exists, else an iteration-ordered directory scan. The listener's
    restart-surviving ``saved`` list."""
    entries = read_manifest(directory)
    if entries:
        paths = (os.path.join(directory, _entry_name(e)) for e in entries)
        return [p for p in paths if os.path.exists(p)]
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    cands = [os.path.join(directory, f) for f in names
             if f.startswith("checkpoint_") and f.endswith(".zip")]
    return [p for _, _, p in sorted(
        (_checkpoint_iteration(p), os.path.getmtime(p), p) for p in cands)]


def register_committed(directory: str, path: str, iteration: int,
                       keep_last: int, max_total_bytes: Optional[int] = None,
                       incarnation: Optional[int] = None) -> None:
    """Fold an already-written checkpoint file (legacy ``model.save``
    path) into the verified manifest and apply retention."""
    try:
        size: Optional[int] = os.path.getsize(path)
    except OSError:
        size = None
    _append_and_retain(directory, os.path.basename(path),
                       _sha256_file(path), iteration, keep_last, size=size,
                       max_total_bytes=max_total_bytes,
                       incarnation=incarnation)


def clean_stale_tmp(directory: str) -> int:
    """Remove ``*.tmp`` left by writes torn mid-flight (the rename never
    happened, so they are garbage by construction)."""
    n = 0
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return 0
    for f in names:
        if f.endswith(".tmp"):
            try:
                os.remove(os.path.join(directory, f))
                n += 1
            except OSError:
                pass
    return n


# --------------------------------------------------------------------------
# verified reads
# --------------------------------------------------------------------------

def _zip_intact(path: str) -> bool:
    from .model_serializer import _META_ENTRY

    try:
        with zipfile.ZipFile(path) as zf:
            if zf.testzip() is not None:
                return False
            json.loads(zf.read(_META_ENTRY))
        return True
    except Exception:
        return False


def _checkpoint_iteration(path: str) -> int:
    from .model_serializer import _META_ENTRY

    try:
        with zipfile.ZipFile(path) as zf:
            return int(json.loads(zf.read(_META_ENTRY)).get("iteration", -1))
    except Exception:
        return -1


def _update_entry(directory: str, name: str, mutate) -> bool:
    """Locked read-modify-write of one manifest entry (by file name).
    Returns whether an entry was found and rewritten."""
    with _MANIFEST_LOCK:
        doc = read_manifest_doc(directory)
        entries = doc.get("checkpoints", [])
        if not isinstance(entries, list):
            return False
        hit = False
        for e in entries:
            if isinstance(e, dict) and e.get("file") == name:
                mutate(e)
                hit = True
        if hit:
            write_manifest(directory, entries)
        return hit


def quarantine_checkpoint(directory: str, name: str,
                          reason: str = "") -> bool:
    """Mark one generation quarantined in the manifest. The file is
    NEVER deleted — a rotten checkpoint is evidence for the post-mortem
    (which bits flipped, when the scrub caught it) — but every reader
    (:func:`verify_checkpoint`, :func:`last_checkpoint`,
    :func:`verify_group_commit`, :func:`scan_newest_intact`) skips it
    from now on, even if a later re-hash happens to pass: quarantine is
    sticky by design."""
    def mut(e):
        e["quarantined"] = True
        e["quarantine_reason"] = str(reason)[:200]
        e["quarantine_t"] = time.time()
    hit = _update_entry(directory, name, mut)
    if hit:
        OpProfiler.get().count("integrity/quarantined_checkpoints")
        flightrec.event("integrity/quarantine", severity="warn",
                        file=name, reason=str(reason)[:200])
        logger.warning("checkpoint %s quarantined: %s", name, reason)
    return hit


def record_scrub(directory: str, name: str, ok: bool,
                 reason: str = "") -> bool:
    """Fold one scrub verdict into the manifest: a pass stamps the entry
    with ``scrub = {ok, t}`` (the supervisor's corruption fallback
    resumes only from scrub-verified generations); a fail quarantines
    the generation (:func:`quarantine_checkpoint`)."""
    if not ok:
        return quarantine_checkpoint(
            directory, name, reason or "scrub checksum mismatch")

    def mut(e):
        e["scrub"] = {"ok": True, "t": time.time()}
    return _update_entry(directory, name, mut)


def verify_checkpoint(directory: str, entry: Any) -> Optional[str]:
    """One manifest entry → verified path, or None (with a warning).
    Quarantined generations are refused even when the bytes re-hash
    clean — the scrubber marked them as evidence, not candidates."""
    if isinstance(entry, str):      # v1 manifest: existence + zip CRC only
        path = entry if os.path.isabs(entry) else os.path.join(
            directory, os.path.basename(entry))
        if os.path.exists(path) and _zip_intact(path):
            return path
        logger.warning("checkpoint %s missing or corrupt; skipping", path)
        return None
    if entry.get("quarantined"):
        logger.warning("checkpoint %s is quarantined (%s); skipping",
                       entry.get("file"),
                       entry.get("quarantine_reason", "scrub"))
        return None
    path = os.path.join(directory, entry["file"])
    if not os.path.exists(path):
        logger.warning("checkpoint %s indexed but missing; skipping", path)
        return None
    if _sha256_file(path) != entry.get("sha256"):
        logger.warning("checkpoint %s fails its manifest checksum "
                       "(truncated or bit-flipped write); skipping", path)
        return None
    return path


def last_checkpoint(directory: str,
                    require_scrubbed: bool = False) -> Optional[str]:
    """Newest checkpoint that PROVES intact — manifest+checksum first,
    newest→oldest (quarantined generations skipped), then the
    directory-scan fallback. ``require_scrubbed`` (the supervisor's
    silent-corruption restart fallback) PREFERS the newest
    scrub-verified generation — a background re-hash vouched for the
    bytes after commit — falling back to the ordinary walk (whose
    verify re-hashes at read time anyway) with a warning when no scrub
    pass has stamped anything yet."""
    entries = read_manifest(directory)
    if require_scrubbed:
        for entry in reversed(entries):
            if (isinstance(entry, dict) and not entry.get("quarantined")
                    and (entry.get("scrub") or {}).get("ok")):
                path = verify_checkpoint(directory, entry)
                if path is not None:
                    return path
        logger.warning(
            "no scrub-verified checkpoint in %s; falling back to the "
            "newest checksum-verified generation", directory)
    for entry in reversed(entries):
        path = verify_checkpoint(directory, entry)
        if path is not None:
            return path
    return scan_newest_intact(directory)


def verify_group_commit(directory: str, tag: str) -> Optional[str]:
    """A non-zero rank's post-publish check in the cluster group-commit
    protocol (``parallel.cluster``): the manifest must name
    ``checkpoint_<tag>.zip`` AND its checksum must verify — only then
    may the rank resume past the publish barrier. Returns the verified
    path, or None (commit absent from the manifest, torn, or
    quarantined by the scrubber — :func:`verify_checkpoint` refuses
    quarantined generations). The directory-scan fallback is
    deliberately NOT consulted: a group commit is only published once
    the MANIFEST says so."""
    name = f"checkpoint_{tag}.zip"
    for entry in reversed(read_manifest(directory)):
        if _entry_name(entry) == name:
            return verify_checkpoint(directory, entry)
    return None


def scan_newest_intact(directory: str) -> Optional[str]:
    """Manifest-less fallback: every committed ``checkpoint_*.zip`` is
    validated (zip CRC + meta entry) and the one with the highest
    iteration (mtime tiebreak) wins. Generations the manifest marks
    quarantined stay skipped here too — the scan must not resurrect
    what the scrubber condemned (a flip inside zip payload bytes can
    leave the CRC walk green)."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return None
    quarantined = {_entry_name(e) for e in read_manifest(directory)
                   if isinstance(e, dict) and e.get("quarantined")}
    cands = []
    for f in names:
        if not (f.startswith("checkpoint_") and f.endswith(".zip")):
            continue
        if f in quarantined:
            logger.warning("checkpoint %s is quarantined; scan skips it",
                           f)
            continue
        path = os.path.join(directory, f)
        if _zip_intact(path):
            cands.append((_checkpoint_iteration(path),
                          os.path.getmtime(path), path))
        else:
            logger.warning("checkpoint %s is corrupt; skipping", path)
    if not cands:
        return None
    return max(cands)[2]


# --------------------------------------------------------------------------
# resume
# --------------------------------------------------------------------------

def read_resume_state(path: str) -> Dict[str, Any]:
    """The resume.json payload (empty dict for pre-PR-3 checkpoints —
    they restore params/updater but fast-forward nothing)."""
    with zipfile.ZipFile(path) as zf:
        if RESUME_ENTRY not in zf.namelist():
            return {}
        return json.loads(zf.read(RESUME_ENTRY))


def read_checkpoint_params(path: str, params_template, states_template
                           ) -> Tuple[Any, Any]:
    """Read JUST (params, states) from a checkpoint/model zip against
    the given templates — the serving tier's canaried
    ``publish_checkpoint`` loads candidate weights WITHOUT constructing
    or mutating a training model (and without touching the RNG stream,
    updater state, or pipeline cursor a full restore carries). Host
    trees; the caller owns device placement."""
    from .model_serializer import (_COEFF_ENTRY, _STATES_ENTRY,
                                   _load_into_tree)

    with zipfile.ZipFile(path) as zf:
        params = _load_into_tree(zf.read(_COEFF_ENTRY), params_template,
                                 "coefficient")
        states = _load_into_tree(zf.read(_STATES_ENTRY), states_template,
                                 "state")
    return params, states


def restore_training_state(model, path: str, listeners=None,
                           restore_rng: bool = True,
                           convert_state_dtype: bool = False
                           ) -> Dict[str, int]:
    """Load a checkpoint INTO an existing (init()-ed) model and return the
    pipeline cursor ``{"epochs_done": d, "steps_in_epoch": s}``. Restores
    params / states / updater state / iteration / epoch / the calling
    thread's RNG key / listener state — the full set a bit-identical
    continuation needs.

    ``convert_state_dtype``: a checkpoint whose stored updater moments
    disagree with the configured ``updater.state_dtype`` is refused
    (the dtype is part of the numerics); pass True to convert with one
    explicit round-to-nearest cast instead."""
    from ..ndarray.rng import get_random
    from .model_serializer import load_state_entries

    with zipfile.ZipFile(path) as zf:
        # shared with ModelSerializer._restore: zip-entry loading +
        # device materialization (donation safety) live in ONE place
        load_state_entries(zf, model, load_updater=True,
                           convert_state_dtype=convert_state_dtype)
        # accumulator state (encoded-exchange residuals etc.) restores
        # LAZILY: the raw npz bytes ride on the model until a wrapper
        # with the owning accumulator rebuilds the tree from its template
        # (non-wrapper resumes simply never touch the blob)
        model._acc_blob = (zf.read(ACC_ENTRY)
                           if ACC_ENTRY in zf.namelist() else None)
        model._acc_state = None
    # the restored params replace donated jit buffers — compiled steps
    # referencing the old ones must rebuild
    for attr in ("_fit_step", "_chunk_step", "_tbptt_step", "_infer_fn"):
        if hasattr(model, attr):
            setattr(model, attr, None)
    resume = read_resume_state(path)
    if restore_rng and resume.get("rng"):
        get_random().set_state(resume["rng"])
    if listeners and resume.get("listener_state"):
        restore_listener_state(listeners, resume["listener_state"])
    cursor = resume.get("cursor") or {}
    saved_workers = cursor.get("workers")
    if saved_workers is not None:
        # purely informational (the on-disk layout is worker-count-
        # independent) but load-bearing for elastic diagnostics: the
        # restore log names the count the snapshot was training at, and
        # the wrapper's resharding warning can compare against it
        model._ckpt_workers = int(saved_workers)
        logger.info("checkpoint %s was taken under %d data-parallel "
                    "worker(s)", os.path.basename(path), saved_workers)
    saved_stages = cursor.get("stages")
    if saved_stages is not None:
        # informational, like workers: the pipeline layout on disk is
        # per-layer and stage-count-independent, but diagnostics should
        # name the stage count the snapshot was training at
        model._ckpt_stages = int(saved_stages)
        logger.info("checkpoint %s was taken under %d pipeline stage(s)",
                    os.path.basename(path), saved_stages)
    flightrec.event("checkpoint/restore", file=os.path.basename(path),
                    epochs_done=int(cursor.get("epochs_done", 0)),
                    steps_in_epoch=int(cursor.get("steps_in_epoch", 0)))
    return {"epochs_done": int(cursor.get("epochs_done", 0)),
            "steps_in_epoch": int(cursor.get("steps_in_epoch", 0))}


def begin_fit_cursor(model, resume_from: Optional[str],
                     listeners=None, keep_flat: bool = False
                     ) -> Optional[tuple]:
    """The one resume-cursor setup every fit path shares (MLN /
    ComputationGraph / ParallelWrapper): restore the checkpoint into the
    model (when resuming) and anchor the cursor bookkeeping —
    ``_fit_epoch0`` pins epoch counting to the LOGICAL run, so a
    checkpoint taken after a resume still records its cursor relative to
    the original call, and ``_steps_in_epoch`` counts dispatched steps
    for the snapshot. Returns the pipeline ``skip`` tuple, or None for a
    fresh fit.

    ``keep_flat``: a ZeRO-1 fit (ParallelWrapper + ReduceScatter
    accumulator) keeps/accepts the flat sharded updater layout and does
    its own (re)sharding; every OTHER fit path needs the dense tree, so a
    model whose last fit left flat state (same-process handoff) is
    normalized here before its step builder ever sees it."""
    if not keep_flat:
        _ensure_dense_updater_layout(model)
    # liveness metadata is per-fit: a model that last trained on a
    # pipeline must not stamp a stale stage count into a later
    # non-pipeline fit's checkpoints (PipelineTrainer.fit re-sets the
    # attr right after this anchor)
    if hasattr(model, "_live_stages"):
        del model._live_stages
    if resume_from is None:
        model._fit_epoch0 = model._epoch
        model._steps_in_epoch = 0
        return None
    cursor = restore_training_state(model, resume_from, listeners=listeners)
    model._fit_epoch0 = model._epoch - cursor["epochs_done"]
    model._steps_in_epoch = cursor["steps_in_epoch"]
    return (cursor["epochs_done"], cursor["steps_in_epoch"])


def _ensure_dense_updater_layout(model) -> None:
    """Flat (ZeRO-1) updater state → dense params-mirroring tree, device-
    materialized with owning buffers (donation safety). No-op for dense
    state/None."""
    from ..parallel.sharding import is_flat_state, unflatten_updater_state

    state = getattr(model, "_updater_state", None)
    if not is_flat_state(state):
        return
    import jax
    import jax.numpy as jnp

    host = unflatten_updater_state(jax.device_get(state),
                                   jax.device_get(model._params), xp=np)
    model._updater_state = jax.tree.map(lambda a: jnp.array(a), host)


# --------------------------------------------------------------------------
# async writer
# --------------------------------------------------------------------------

class CheckpointWriter:
    """One background thread that serializes snapshots and commits them
    atomically, so the training loop never blocks on zip/deflate/disk.
    Bounded queue (depth 2): if checkpoints outrun the disk, submission
    applies backpressure rather than buffering unboundedly. A write that
    fails (including an injected pre-rename crash in ``raise`` mode) is
    logged and recorded in ``errors``; the manifest is untouched, so
    ``last_checkpoint`` keeps pointing at the previous intact one."""

    def __init__(self, directory: str, keep_last: int = 3,
                 on_commit=None, max_total_bytes: Optional[int] = None,
                 incarnation: Optional[int] = None):
        self.dir = directory
        self.keep_last = keep_last
        self.max_total_bytes = max_total_bytes
        self.incarnation = incarnation
        self.errors: List[BaseException] = []
        self._on_commit = on_commit
        self._q: "queue.Queue" = queue.Queue(maxsize=2)
        # pending counts submitted-but-uncommitted jobs under a condition
        # variable (an Event would race: submit's clear can interleave
        # with the worker observing a momentarily-empty queue and
        # re-setting it, making flush() return with a job still queued)
        self._pending = 0
        self._cond = threading.Condition()
        self._seq = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dl4j-ckpt-writer")
        self._thread.start()

    def submit(self, snapshot: Dict[str, Any], tag: str) -> None:
        with self._cond:
            self._pending += 1
            seq = self._seq
            self._seq += 1
        self._q.put((snapshot, tag, seq))

    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            snapshot, tag, seq = job
            try:
                data = serialize_snapshot(snapshot)
                path = commit_checkpoint(self.dir, tag, data,
                                         snapshot["iteration"],
                                         self.keep_last, seq=seq,
                                         max_total_bytes=self.max_total_bytes,
                                         incarnation=self.incarnation,
                                         state_dtype=snapshot.get("state_dtype"))
                if self._on_commit is not None:
                    self._on_commit(path)
            except BaseException as e:     # incl. SimulatedCrash(raise)
                self.errors.append(e)
                logger.warning("async checkpoint %s failed: %s", tag, e,
                               exc_info=not isinstance(
                                   e, faultinject.SimulatedCrash))
            finally:
                with self._cond:
                    self._pending -= 1
                    self._cond.notify_all()

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted checkpoint is committed (or
        failed). The listener's explicit durability points — ``flush``/
        ``close``/reading ``saved`` — come through here; nothing flushes
        implicitly, so the training loop never stalls on the writer."""
        with self._cond:
            return self._cond.wait_for(lambda: self._pending == 0, timeout)

    def close(self, timeout: float = 30.0) -> None:
        drained = self.flush(timeout)
        try:
            # bounded put: with a wedged writer (stalled disk) and a full
            # queue, close() must not hang the training thread forever
            self._q.put(None, timeout=5.0 if drained else 1.0)
        except queue.Full:
            logger.warning("checkpoint writer did not drain within %.0fs; "
                           "abandoning it (daemon thread)", timeout)
        self._thread.join(timeout)
