"""CrashReportingUtil: OOM/crash post-mortem memory dump.

Reference: dl4j-nn ``org/deeplearning4j/nn/util/CrashReportingUtil.java``
(SURVEY §2.3 Common/infra, §5.3) — on an OOM it writes system info,
workspace state, and a memory-by-layer estimate. TPU shape: SystemInfo
(incl. live PJRT HBM stats), per-layer parameter counts/bytes, and an
activation-memory estimate per layer for a given minibatch — the numbers
that tell a user WHICH layer blew HBM.
"""

from __future__ import annotations

import datetime
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _tree_bytes(tree) -> int:
    return sum(np.asarray(a).nbytes
               for a in jax.tree_util.tree_leaves(tree))


def _tree_count(tree) -> int:
    return sum(int(np.prod(np.shape(a)))
               for a in jax.tree_util.tree_leaves(tree))


def generate_memory_status_report(model, minibatch: int = 32) -> str:
    """The crash-report body: system info + per-layer param/activation
    memory table for a ``MultiLayerNetwork`` or ``ComputationGraph``."""
    from ..common.system_info import SystemInfo

    lines = [f"=== deeplearning4j-tpu memory status report "
             f"({datetime.datetime.now().isoformat(timespec='seconds')}) ===",
             SystemInfo.dump(), "", f"--- model (minibatch={minibatch}) ---"]
    params = model._params
    names = (list(params.keys()) if isinstance(params, dict)
             else list(range(len(params))))
    layers = getattr(model.conf, "layers", None)
    total_param_bytes = 0
    for n in names:
        p = params[n]
        pb = _tree_bytes(p)
        total_param_bytes += pb
        label = n
        if layers is not None and isinstance(n, int) and n < len(layers):
            label = f"{n} ({type(layers[n]).__name__})"
        lines.append(f"layer {label}: {_tree_count(p):,} params, "
                     f"{pb / 2**20:.2f} MiB")
    lines.append(f"total parameters: {total_param_bytes / 2**20:.2f} MiB "
                 "(x2-3 live during training: gradients + updater state)")

    # activation-memory estimate: eval_shape the forward, sum per-layer
    # output sizes at the given minibatch (the reference estimates
    # per-layer activation memory the same way, analytically)
    try:
        act_bytes = _activation_estimate(model, minibatch, lines)
        lines.append(f"activation estimate (fwd, minibatch {minibatch}): "
                     f"{act_bytes / 2**20:.2f} MiB (backward roughly "
                     "doubles this without gradient_checkpointing)")
    except Exception as e:           # estimate is best-effort
        lines.append(f"activation estimate unavailable: {e}")
    return "\n".join(lines)


def _activation_estimate(model, minibatch: int, lines) -> int:
    from ..nn.multilayer import MultiLayerNetwork

    if not isinstance(model, MultiLayerNetwork):
        raise ValueError("per-layer activation walk supports "
                         "MultiLayerNetwork (graphs: use the profiler)")
    it = model.conf.input_type
    from ..nn.conf.inputs import CNNInput, FFInput, RNNInput

    if isinstance(it, FFInput):
        shape = (minibatch, it.size)
    elif isinstance(it, RNNInput):
        shape = (minibatch, it.timesteps or 16, it.size)
    elif isinstance(it, CNNInput):
        shape = (minibatch, it.channels, it.height, it.width)
    else:
        raise ValueError(f"unsupported input type {it}")
    total = 0
    x = jax.ShapeDtypeStruct(shape, jnp.float32)
    key = jax.random.PRNGKey(0)
    for i, layer in enumerate(model.layers):
        pre = model.conf.preprocessors.get(i)
        if pre is not None:
            x = jax.eval_shape(pre, x)

        def run(xx, lp=model._params[i], st=model._states[i], _l=layer):
            out, _ = _l.apply(lp, xx, st, False, key)
            return out

        x = jax.eval_shape(run, x)
        nbytes = int(np.prod(x.shape)) * x.dtype.itemsize
        total += nbytes
        lines.append(f"  activation[{i} {type(layer).__name__}]: "
                     f"{tuple(x.shape)} = {nbytes / 2**20:.2f} MiB")
    return total


def write_memory_crash_dump(model, path: Optional[str] = None,
                            minibatch: int = 32) -> str:
    """Write the report to ``path`` (default: cwd
    ``dl4j-tpu-memory-crash-dump-<ts>.txt``) and return the path —
    the reference's ``writeMemoryCrashDump`` contract."""
    if path is None:
        ts = datetime.datetime.now().strftime("%Y%m%d-%H%M%S")
        path = os.path.abspath(f"dl4j-tpu-memory-crash-dump-{ts}.txt")
    with open(path, "w", encoding="utf-8") as f:
        f.write(generate_memory_status_report(model, minibatch))
    return path


class CrashReportingUtil:
    """Reference-shaped static facade."""

    generate_memory_status_report = staticmethod(
        generate_memory_status_report)
    write_memory_crash_dump = staticmethod(write_memory_crash_dump)
    # reference spelling
    writeMemoryCrashDump = staticmethod(write_memory_crash_dump)
