from .model_serializer import write_model, restore_multi_layer_network, restore_normalizer
from .crash_reporting import CrashReportingUtil
from .checkpoint import (snapshot_training_state, restore_training_state,
                         commit_checkpoint, last_checkpoint, CheckpointWriter)
