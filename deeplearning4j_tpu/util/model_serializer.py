"""ModelSerializer — the model zip container.

Reference: dl4j-nn ``org.deeplearning4j.util.ModelSerializer`` (SURVEY.md
§5.4): zip = configuration.json + coefficients.bin (flattened params) +
updaterState.bin + optional normalizer.bin. Same inventory here with npz
payloads; one shared writer/restorer serves both MultiLayerNetwork and
ComputationGraph (``writeModel/restoreMultiLayerNetwork/
restoreComputationGraph`` contract).
"""

from __future__ import annotations

import io
import json
import logging
import zipfile
from typing import Optional

import jax
import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

_CONF_ENTRY = "configuration.json"
_COEFF_ENTRY = "coefficients.npz"
_STATES_ENTRY = "states.npz"
_UPDATER_ENTRY = "updaterState.npz"
_NORMALIZER_ENTRY = "normalizer.json"
_META_ENTRY = "meta.json"


def _savez_leaves(tree) -> bytes:
    """Leaves → npz. ml_dtypes leaves (bfloat16 updater state) are not
    native numpy dtypes and crash np.savez, so they ship as a same-width
    integer view with the real dtype tagged into the entry name
    (``<i>::bfloat16``); ``_load_into_tree`` views them back. Plain
    ``<i>`` entries stay byte-identical to every pre-existing archive."""
    leaves, _ = jax.tree.flatten(tree)
    entries = {}
    for i, l in enumerate(leaves):
        a = np.asarray(l)
        if a.dtype.kind == "V" or a.dtype.name == "bfloat16":
            entries[f"{i}::{a.dtype.name}"] = a.view(
                np.dtype(f"u{a.dtype.itemsize}"))
        else:
            entries[str(i)] = a
    buf = io.BytesIO()
    np.savez(buf, **entries)
    return buf.getvalue()


def _load_into_tree(data: bytes, template, what: str, cast_to_template: bool = False):
    arrays = np.load(io.BytesIO(data))
    names = {}
    for n in arrays.files:
        idx, _, tag = n.partition("::")
        names[int(idx)] = (n, tag or None)
    leaves, treedef = jax.tree.flatten(template)
    if len(arrays.files) != len(leaves):
        raise ValueError(
            f"{what} count mismatch: archive has {len(arrays.files)}, "
            f"configuration implies {len(leaves)}")
    restored = []
    for i in range(len(leaves)):
        n, tag = names[i]
        a = np.asarray(arrays[n])
        if tag is not None:
            import ml_dtypes

            a = a.view(np.dtype(getattr(ml_dtypes, tag)))
        restored.append(a)
    if cast_to_template:
        restored = [r.astype(np.asarray(t).dtype) for r, t in zip(restored, leaves)]
    return jax.tree.unflatten(treedef, restored)


def write_model(model, path: str, save_updater: bool = False,
                normalizer=None) -> None:
    """Shared writer for MultiLayerNetwork and ComputationGraph. The zip
    is staged to ``<path>.tmp`` and renamed into place, so a crash
    mid-save never leaves a torn file at the target name (the same
    atomicity contract util.checkpoint builds its manifest on)."""
    import os

    tmp = path + ".tmp"
    try:
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(_CONF_ENTRY, model.conf.to_json())
            zf.writestr(_COEFF_ENTRY, _savez_leaves(model._params))
            zf.writestr(_STATES_ENTRY, _savez_leaves(model._states))
            zf.writestr(_META_ENTRY, json.dumps({
                "iteration": model._iteration, "epoch": model._epoch,
                "kind": type(model).__name__, "format_version": 1,
            }))
            if save_updater and model._updater_state is not None:
                # a ZeRO-1 fit leaves the updater state in the flat
                # sharded layout; the container's layout is ALWAYS the
                # dense params-mirroring tree (see util.checkpoint)
                from ..parallel.sharding import unflatten_updater_state

                upd = unflatten_updater_state(
                    jax.device_get(model._updater_state),
                    jax.device_get(model._params))
                zf.writestr(_UPDATER_ENTRY, _savez_leaves(upd))
            if normalizer is not None:
                zf.writestr(_NORMALIZER_ENTRY,
                            json.dumps(normalizer.to_json()))
        os.replace(tmp, path)
    except BaseException:
        # don't strand a half-written tmp at an arbitrary user path
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def _materialize_on_device(tree):
    """Restored trees become DEVICE arrays before they reach a model: the
    fit step donates these buffers, and donating an array that zero-copy
    aliases numpy-owned host memory (possible on the CPU backend) frees
    memory numpy still owns — observed as glibc heap corruption under the
    persistent compilation cache."""
    import jax.numpy as jnp

    return jax.tree.map(lambda a: jnp.array(jnp.asarray(a)), tree)


def load_state_entries(zf: zipfile.ZipFile, model,
                       load_updater: bool = True,
                       convert_state_dtype: bool = False) -> None:
    """Load the container's coefficient/state/meta(/updater) entries INTO
    an existing initialized model, device-materialized. Shared by
    :func:`_restore` (fresh model from the zip's conf) and
    ``util.checkpoint.restore_training_state`` (resume into a live model)
    so the donation-safety materialization cannot drift between them.

    The updater-state dtype is part of the training numerics
    (``updater.state_dtype`` — bf16 moments round differently than
    fp32), so an archive whose stored moments disagree with the current
    configuration is REFUSED rather than silently widened/narrowed.
    ``convert_state_dtype=True`` is the explicit opt-in: one
    round-to-nearest cast onto the configured dtype, logged."""
    names = zf.namelist()
    model._params = _materialize_on_device(_load_into_tree(
        zf.read(_COEFF_ENTRY), model._params, "coefficient",
        cast_to_template=True))
    if _STATES_ENTRY in names:
        model._states = _materialize_on_device(_load_into_tree(
            zf.read(_STATES_ENTRY), model._states, "state"))
    meta = json.loads(zf.read(_META_ENTRY))
    model._iteration = meta.get("iteration", 0)
    model._epoch = meta.get("epoch", 0)
    if load_updater:
        if _UPDATER_ENTRY in names:
            state0 = model.conf.global_conf.updater.init(model._params)
            restored = _load_into_tree(
                zf.read(_UPDATER_ENTRY), state0, "updater state")
            import jax.numpy as jnp

            # jnp's dtype lattice, not numpy's: ml_dtypes bfloat16 is
            # floating to jax but a void type to np.issubdtype
            _floating = lambda d: jnp.issubdtype(d, jnp.floating)  # noqa: E731
            mismatch = sorted({
                f"{np.asarray(r).dtype}->{np.asarray(t).dtype}"
                for r, t in zip(jax.tree.leaves(restored),
                                jax.tree.leaves(state0))
                if np.asarray(r).dtype != np.asarray(t).dtype
                and _floating(np.asarray(t).dtype)})
            if mismatch:
                if not convert_state_dtype:
                    sd = getattr(model.conf.global_conf.updater,
                                 "state_dtype", None)
                    raise ValueError(
                        f"updater state dtype mismatch ({', '.join(mismatch)}): "
                        f"the checkpoint's stored moments do not match the "
                        f"configured state_dtype={sd!r}. A silent cast would "
                        f"change training numerics — pass "
                        f"convert_state_dtype=True (restore_training_state / "
                        f"load_state_entries) to convert explicitly, or match "
                        f"the updater's state_dtype to the checkpoint.")
                logger.info("converting updater state dtype (%s) to the "
                            "configured state_dtype", ", ".join(mismatch))
                restored = jax.tree.map(
                    lambda r, t: np.asarray(r).astype(np.asarray(t).dtype)
                    if _floating(np.asarray(t).dtype)
                    else np.asarray(r), restored, state0)
            model._updater_state = _materialize_on_device(restored)
        else:
            model._updater_state = None


def _restore(path: str, model_cls, conf_cls, load_updater: bool):
    with zipfile.ZipFile(path) as zf:
        conf = conf_cls.from_json(zf.read(_CONF_ENTRY).decode())
        model = model_cls(conf)
        model.init()
        load_state_entries(zf, model, load_updater=load_updater)
    return model


def restore_multi_layer_network(path: str, load_updater: bool = False):
    from ..nn.conf.builder import MultiLayerConfiguration
    from ..nn.multilayer import MultiLayerNetwork

    return _restore(path, MultiLayerNetwork, MultiLayerConfiguration, load_updater)


def restore_computation_graph(path: str, load_updater: bool = False):
    from ..nn.graph import ComputationGraph, ComputationGraphConfiguration

    return _restore(path, ComputationGraph, ComputationGraphConfiguration, load_updater)


def restore_model(path: str, load_updater: bool = False):
    """Restore either model class, dispatching on the container's
    ``meta.json`` kind entry (reference ``ModelSerializer.restore*`` pair,
    merged — the zip records what it holds)."""
    with zipfile.ZipFile(path) as zf:
        meta = json.loads(zf.read(_META_ENTRY))
    if meta.get("kind") == "ComputationGraph":
        return restore_computation_graph(path, load_updater)
    return restore_multi_layer_network(path, load_updater)


def restore_normalizer(path: str):
    from ..data.normalizers import normalizer_from_json

    with zipfile.ZipFile(path) as zf:
        if _NORMALIZER_ENTRY not in zf.namelist():
            return None
        return normalizer_from_json(json.loads(zf.read(_NORMALIZER_ENTRY)))
